#!/usr/bin/env python
"""Bench-history regression sentinel over PERF_LEDGER.jsonl.

``bench.py`` appends one ``perf_ledger`` record per run (every emitted
metric as ``name -> {value, unit}`` plus the analytical cost-model
numbers). This tool compares the LAST entry against the median of the
preceding ``--last N`` entries, metric by metric, and flags any move beyond
``--threshold`` in the *worse* direction — the direction is derived from
the unit (``rows/s`` up is good, ``seconds`` up is bad), so one rule covers
throughputs, latencies and accuracy bars alike::

    python tools/perf_sentinel.py PERF_LEDGER.jsonl            # report
    python tools/perf_sentinel.py PERF_LEDGER.jsonl --strict   # CI gate

``--strict`` exits 2 on any regression, which is how ``bench --smoke``
becomes a perf gate (``TPU_ML_PERF_SENTINEL=1`` makes the bench invoke this
itself after appending). A fresh ledger (fewer than 2 entries) always
passes — there is no history to regress against. Smoke and full-shape runs
are never compared with each other (filtered on the entry's ``smoke``
flag), tuned and untuned runs likewise (filtered on the entry's ``tuning``
signature, so a bench run under a different autotuner config never judges
— or poisons — the default-config history), autotuner search-trial
entries (``search_trial`` flag) are excluded from history outright, and
metrics absent from history are reported as new, not judged.

Two gates stack on top of the history comparison:

- **Vanished metrics.** A metric present in every comparable history
  entry but absent from the current one is itself a regression — the
  gated series (``serve_p99_ms``, throughputs, ...) cannot silently drop
  out of the bench and out of gating with it.
- **Absolute ceilings.** A metric may carry a ``ceiling`` alongside its
  value/unit (``bench.py`` stamps one on ``serve_p99_ms`` when
  ``TPU_ML_SERVE_P99_GATE_MS`` is set); crossing it in the unit's worse
  direction is a regression regardless of history — and since ceilings
  ride the entry itself, ``--bless`` cannot wave one through.

Blessing an intentional perf change: ``--bless`` truncates the ledger to
its last entry, making the new numbers the baseline history (see
CONTRIBUTING.md for the workflow).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

# runnable straight from a checkout (matches the other tools/ CLIs)
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

DEFAULT_LAST = 5
DEFAULT_THRESHOLD = 0.35  # relative move considered a regression

# units where a LOWER value is better; every other unit (rows/s, queries/s,
# cosine, ...) reads higher-is-better
_LOWER_IS_BETTER_UNITS = ("seconds", "s", "ms", "bytes")


def load_ledger(path: str) -> list[dict]:
    entries: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "perf_ledger":
                entries.append(rec)
    return entries


def lower_is_better(unit: str) -> bool:
    return unit.strip().lower() in _LOWER_IS_BETTER_UNITS


def tuning_signature(entry: dict) -> str:
    """Canonical form of an entry's autotuner configuration.

    Entries written before the ``tuning`` field existed — and entries from
    default-config runs, which omit it — normalize to the same ``"{}"``
    signature, so pre-autotuner history keeps judging default runs."""
    return json.dumps(entry.get("tuning") or {}, sort_keys=True)


def compare(
    current: dict,
    history: list[dict],
    threshold: float,
) -> tuple[list[dict], list[str]]:
    """(regressions, notes) of the current entry vs the history median.

    A regression is a metric whose value moved more than ``threshold``
    (relative) in the worse direction for its unit, crossed its declared
    absolute ``ceiling``, or vanished from the current entry despite being
    present in every history entry. Notes cover metrics with no usable
    history (new metric, zero baseline).
    """
    regressions: list[dict] = []
    notes: list[str] = []
    current_metrics = current.get("metrics") or {}
    # a gated metric must not silently drop out of the bench: present in
    # every comparable history entry + absent now = regression
    for name in sorted(
        set.intersection(
            *(set(e.get("metrics") or {}) for e in history)
        ) - set(current_metrics)
        if history else ()
    ):
        regressions.append({
            "metric": name,
            "unit": "",
            "value": None,
            "baseline_median": None,
            "ratio": None,
            "n_history": len(history),
            "vanished": True,
        })
    for name, cur in sorted(current_metrics.items()):
        try:
            value = float(cur.get("value"))
        except (TypeError, ValueError):
            continue
        unit = str(cur.get("unit", ""))
        ceiling = cur.get("ceiling")
        if isinstance(ceiling, (int, float)):
            beyond = (
                value > float(ceiling) if lower_is_better(unit)
                else value < float(ceiling)
            )
            if beyond:
                regressions.append({
                    "metric": name,
                    "unit": unit,
                    "value": value,
                    "baseline_median": float(ceiling),
                    "ratio": value / ceiling if ceiling else float("inf"),
                    "n_history": 0,
                    "ceiling": True,
                })
                continue
        past = []
        for entry in history:
            m = (entry.get("metrics") or {}).get(name)
            if m is None:
                continue
            try:
                past.append(float(m.get("value")))
            except (TypeError, ValueError):
                continue
        if not past:
            notes.append(f"{name}: no history (new metric)")
            continue
        baseline = statistics.median(past)
        if baseline == 0:
            notes.append(f"{name}: zero baseline, skipped")
            continue
        ratio = value / baseline
        worse = ratio > 1.0 + threshold if lower_is_better(unit) \
            else ratio < 1.0 - threshold
        if worse:
            regressions.append({
                "metric": name,
                "unit": unit,
                "value": value,
                "baseline_median": baseline,
                "ratio": ratio,
                "n_history": len(past),
            })
    return regressions, notes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Flag bench regressions against the perf-ledger history"
    )
    ap.add_argument("path", help="PERF_LEDGER.jsonl (appended by bench.py)")
    ap.add_argument(
        "--last", type=int, default=DEFAULT_LAST, metavar="N",
        help=f"history window: median of the last N prior entries "
             f"(default {DEFAULT_LAST})",
    )
    ap.add_argument(
        "--threshold", type=float, default=DEFAULT_THRESHOLD,
        help=f"relative move in the worse direction that counts as a "
             f"regression (default {DEFAULT_THRESHOLD})",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="exit 2 when any metric regressed (the CI gate)",
    )
    ap.add_argument(
        "--bless", action="store_true",
        help="accept the current numbers: truncate the ledger to its last "
             "entry so future runs compare against the new baseline",
    )
    args = ap.parse_args(argv)

    try:
        entries = load_ledger(args.path)
    except OSError as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 1
    if not entries:
        print(f"perf-sentinel: no ledger entries in {args.path} — pass")
        return 0

    current = entries[-1]
    # never judge a smoke run against full-shape history or vice versa,
    # never cross-compare runs under different tuning configs, and never
    # let autotuner search trials (transient, intentionally varied
    # geometry) into the baseline median
    history = [
        e for e in entries[:-1]
        if bool(e.get("smoke")) == bool(current.get("smoke"))
        and not e.get("search_trial")
        and tuning_signature(e) == tuning_signature(current)
    ]
    if args.last > 0:
        history = history[-args.last:]

    if args.bless:
        with open(args.path, "w", encoding="utf-8") as f:
            f.write(json.dumps(current, sort_keys=True) + "\n")
        print(
            f"perf-sentinel: blessed — ledger truncated to the latest entry "
            f"({len(entries) - 1} historical entries dropped)"
        )
        return 0

    regressions, notes = compare(current, history, args.threshold)
    if not history:
        # a declared absolute ceiling rides the entry itself, so it gates
        # even a fresh ledger (and right after --bless); history-relative
        # notes are meaningless without comparable history
        regressions = [r for r in regressions if r.get("ceiling")]
        notes = []
        if not regressions:
            print(
                "perf-sentinel: fresh ledger (no comparable history) — pass"
            )
            return 0
    for note in notes:
        print(f"  note: {note}")
    if not regressions:
        print(
            f"perf-sentinel: OK — {len(current.get('metrics') or {})} "
            f"metrics within {args.threshold:.0%} of the median of "
            f"{len(history)} prior runs"
        )
        return 0

    print(
        f"perf-sentinel: {len(regressions)} regression(s) beyond "
        f"{args.threshold:.0%} vs the median of {len(history)} prior runs:"
    )
    for r in regressions:
        if r.get("vanished"):
            print(
                f"  REGRESSION {r['metric']}: present in all "
                f"{r['n_history']} comparable history entries but missing "
                "from the current entry — the gated series dropped out of "
                "the bench"
            )
            continue
        if r.get("ceiling"):
            bound = "ceiling" if lower_is_better(r["unit"]) else "floor"
            print(
                f"  REGRESSION {r['metric']}: {r['value']:g} {r['unit']} "
                f"crossed the declared absolute {bound} "
                f"{r['baseline_median']:g} ({r['ratio']:.2f}x)"
            )
            continue
        direction = "slower" if lower_is_better(r["unit"]) else "lower"
        print(
            f"  REGRESSION {r['metric']}: {r['value']:g} {r['unit']} vs "
            f"median {r['baseline_median']:g} "
            f"({r['ratio']:.2f}x, {direction}; n={r['n_history']})"
        )
    print(
        "  intentional? bless the new baseline: "
        f"python tools/perf_sentinel.py {args.path} --bless"
    )
    return 2 if args.strict else 0


if __name__ == "__main__":
    raise SystemExit(main())
