#!/usr/bin/env python
"""Drive a serve endpoint (single server or fleet router) with thousands
of concurrent UDS connections and report fleet-wide p50/p99 and q/s.

Usage::

    python -m tools.serve_loadgen --socket /tmp/router.sock --model lin \
        --connections 500 --duration 5 --wire fast --rows 4 --cols 6

Importable as ``run_load(...)`` — the bench fleet stage calls it in
process and stamps the result on the perf ledger.

Design: one OS thread, a ``selectors`` event loop, closed-loop load —
every connection keeps exactly one request in flight, so ``connections``
IS the concurrency and the measured latency is honest queueing latency
(an open-loop generator would smear queue buildup into the tail). Each
connection speaks either the JSON UDS wire or the fast lane; ``mixed``
alternates per connection so one run exercises both. Request frames are
packed once and reused verbatim — the generator does no per-request
encode work, so the measured tail belongs to the server, not the client.

The soft fd limit is raised toward the hard limit when ``connections``
needs it (500 client conns + the router's upstream sockets blow through
the usual 1024 default).
"""

from __future__ import annotations

import argparse
import json
import resource
import selectors
import socket
import struct
import sys
import time

# frame constants mirrored from serving.fastlane (kept in sync by the
# parity test there) — mirroring keeps this tool importable and its
# request loop free of repo imports that would book telemetry
_MAGIC = struct.pack(">I", 0xF5A57A4E)
# v2 request struct ends with the trace tail (trace_id u64, span_id u32,
# origin_us u64); the loadgen sends it zeroed — untraced — and lets the
# router/server mint sampled contexts at admission
_REQ_STRUCT = struct.Struct(">BBHIIQIQ")
_RESP_STRUCT = struct.Struct(">BBHIII")
_FASTLANE_VERSION = 2
_FLAG_ERROR = 0x01


def _raise_nofile(need: int) -> None:
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < need:
        resource.setrlimit(
            resource.RLIMIT_NOFILE, (min(need, hard), hard)
        )


def pack_fast_request(model: str, rows: int, cols: int, payload: bytes) -> bytes:
    name = model.encode("utf-8")
    return b"".join((
        _MAGIC,
        _REQ_STRUCT.pack(_FASTLANE_VERSION, 0, len(name), rows, cols, 0, 0, 0),
        name,
        payload,
    ))


def pack_json_request(model: str, rows: int, cols: int, payload: bytes) -> bytes:
    header = json.dumps({
        "model": model,
        "wire": "binary",
        "accept": "binary",
        "shape": [rows, cols],
        "payload_bytes": len(payload),
    }).encode("utf-8")
    return len(header).to_bytes(4, "big") + header + payload


class _Conn:
    """One closed-loop connection: send the canned frame, parse one
    response (incrementally — the loop never blocks), repeat."""

    __slots__ = (
        "sock", "frame", "wire", "outview", "inbuf", "need", "stage",
        "header_len", "payload_len", "sent_at", "latencies", "failures",
    )

    def __init__(self, path: str, frame: bytes, wire: str):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(path)
        self.sock.setblocking(False)
        self.frame = frame
        self.wire = wire
        self.outview = memoryview(b"")
        self.inbuf = b""
        self.need = 4
        self.stage = "head"
        self.header_len = 0
        self.payload_len = 0
        self.sent_at = 0.0
        self.latencies: list[float] = []
        self.failures = 0

    def begin_request(self, now: float) -> None:
        self.outview = memoryview(self.frame)
        self.inbuf = b""
        self.need = 4
        self.stage = "head"
        self.sent_at = now

    def on_writable(self) -> bool:
        """Push pending request bytes; True when fully sent."""
        while self.outview:
            n = self.sock.send(self.outview)
            self.outview = self.outview[n:]
        return not self.outview

    def on_readable(self) -> bool:
        """Consume response bytes; True when one full response landed."""
        chunk = self.sock.recv(65536)
        if not chunk:
            raise EOFError("server closed connection")
        self.inbuf += chunk
        while len(self.inbuf) >= self.need:
            if self.stage == "head":
                head = self.inbuf[:4]
                self.inbuf = self.inbuf[4:]
                if head == _MAGIC:
                    self.stage = "fast_struct"
                    self.need = _RESP_STRUCT.size
                else:
                    self.header_len = int.from_bytes(head, "big")
                    self.stage = "json_header"
                    self.need = self.header_len
            elif self.stage == "fast_struct":
                raw = self.inbuf[:_RESP_STRUCT.size]
                self.inbuf = self.inbuf[_RESP_STRUCT.size:]
                _v, flags, _status, _r, _c, plen = _RESP_STRUCT.unpack(raw)
                if flags & _FLAG_ERROR:
                    self.failures += 1
                self.payload_len = plen
                self.stage = "payload"
                self.need = plen
            elif self.stage == "json_header":
                header = json.loads(self.inbuf[:self.header_len])
                self.inbuf = self.inbuf[self.header_len:]
                if not header.get("ok", True):
                    self.failures += 1
                self.payload_len = int(header.get("payload_bytes", 0))
                self.stage = "payload"
                self.need = self.payload_len
            elif self.stage == "payload":
                self.inbuf = self.inbuf[self.payload_len:]
                self.latencies.append(time.perf_counter() - self.sent_at)
                self.stage = "head"
                self.need = 4
                return True
        return False

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q / 100.0 * len(sorted_vals)))
    return sorted_vals[idx]


def run_load(
    socket_path: str,
    model: str,
    *,
    connections: int = 64,
    duration_s: float = 5.0,
    wire: str = "fast",
    rows: int = 4,
    cols: int = 6,
    seed: int = 0,
) -> dict:
    """Closed-loop load against ``socket_path``; returns the measurement
    dict (overall + per-wire p50/p99 in ms, q/s, failure count)."""
    if wire not in ("fast", "json", "mixed"):
        raise ValueError(f"unknown wire {wire!r}")
    _raise_nofile(connections * 4 + 256)

    # deterministic payload without numpy: a fixed f32 ramp scaled by the
    # seed, identical for every request (the server's work is shape-, not
    # value-, dependent)
    vals = [((seed + i) % 97) / 97.0 for i in range(rows * cols)]
    payload = struct.pack(f"<{rows * cols}f", *vals)
    frames = {
        "fast": pack_fast_request(model, rows, cols, payload),
        "json": pack_json_request(model, rows, cols, payload),
    }

    sel = selectors.DefaultSelector()
    conns: list[_Conn] = []
    try:
        for i in range(connections):
            w = wire if wire != "mixed" else ("fast" if i % 2 == 0 else "json")
            conn = _Conn(socket_path, frames[w], w)
            conns.append(conn)
        t_start = time.perf_counter()
        deadline = t_start + duration_s
        for conn in conns:
            conn.begin_request(time.perf_counter())
            sel.register(conn.sock, selectors.EVENT_WRITE, conn)
        in_flight = len(conns)
        disconnects = 0
        while in_flight > 0:
            now = time.perf_counter()
            for key, events in sel.select(timeout=1.0):
                conn: _Conn = key.data
                try:
                    if events & selectors.EVENT_WRITE:
                        if conn.on_writable():
                            sel.modify(conn.sock, selectors.EVENT_READ, conn)
                    if events & selectors.EVENT_READ:
                        if conn.on_readable():
                            if now < deadline:
                                conn.begin_request(time.perf_counter())
                                sel.modify(
                                    conn.sock, selectors.EVENT_WRITE, conn
                                )
                            else:
                                sel.unregister(conn.sock)
                                in_flight -= 1
                except (OSError, EOFError, BlockingIOError) as e:
                    if isinstance(e, BlockingIOError):
                        continue
                    disconnects += 1
                    sel.unregister(conn.sock)
                    conn.close()
                    in_flight -= 1
            if time.perf_counter() > deadline + 30.0:
                # straggler guard: a wedged server must not hang the tool
                disconnects += in_flight
                break
        elapsed = time.perf_counter() - t_start
    finally:
        for conn in conns:
            conn.close()
        sel.close()

    by_wire: dict[str, dict] = {}
    all_lat: list[float] = []
    failures = disconnects
    for w in ("fast", "json"):
        lat = sorted(
            v for c in conns if c.wire == w for v in c.latencies
        )
        failures += sum(c.failures for c in conns if c.wire == w)
        if lat:
            by_wire[w] = {
                "count": len(lat),
                "p50_ms": _percentile(lat, 50) * 1e3,
                "p99_ms": _percentile(lat, 99) * 1e3,
            }
        all_lat.extend(lat)
    all_lat.sort()
    return {
        "socket": socket_path,
        "model": model,
        "wire": wire,
        "connections": connections,
        "duration_s": round(elapsed, 3),
        "requests": len(all_lat),
        "failures": failures,
        "qps": round(len(all_lat) / elapsed, 1) if elapsed > 0 else 0.0,
        "p50_ms": round(_percentile(all_lat, 50) * 1e3, 3),
        "p99_ms": round(_percentile(all_lat, 99) * 1e3, 3),
        "by_wire": by_wire,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Closed-loop UDS load generator for the serve runtime"
    )
    ap.add_argument("--socket", required=True, help="UDS path (server or router)")
    ap.add_argument("--model", required=True)
    ap.add_argument("--connections", type=int, default=64)
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument(
        "--wire", choices=("fast", "json", "mixed"), default="fast"
    )
    ap.add_argument("--rows", type=int, default=4)
    ap.add_argument("--cols", type=int, default=6)
    ap.add_argument(
        "--ledger", default="",
        help="append the result as a JSONL record to this perf ledger",
    )
    args = ap.parse_args(argv)
    result = run_load(
        args.socket, args.model,
        connections=args.connections, duration_s=args.duration,
        wire=args.wire, rows=args.rows, cols=args.cols,
    )
    print(json.dumps(result, indent=2))
    if args.ledger:
        record = {"type": "serve_loadgen", "timestamp": time.time()}
        record.update(result)
        with open(args.ledger, "a", encoding="utf-8") as f:
            f.write(json.dumps(record) + "\n")
    return 0 if result["failures"] == 0 and result["requests"] > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
