"""Live health daemon: probes, SLOs and the HTTP exporter from one CLI.

Successor to the retired ``tools/transport_monitor_r5.py``. The old
monitor hand-rolled one concern — a
round-long transport probe loop with an opportunistic bench harvest; this
CLI drives the framework's own :class:`telemetry.health.HealthMonitor`
(device HBM watermarks, bounded transport probes, stream/worker liveness,
resilience signals, windowed SLOs) and keeps the harvest glue on top.

Modes:

* **watch** (default) — start the background monitor (and, with
  ``--port``, the ``/metrics`` + ``/healthz`` HTTP exporter), append one
  JSON rollup line per tick to ``TRANSPORT_LOG_r05.jsonl``, and run the
  opportunistic bench harvest the first time the transport probe comes
  back healthy (same ``BENCH_OPPORTUNISTIC``/``BENCH_DRIFT`` contract and
  ``TPU_ML_MONITOR_*`` knobs as the old monitor)::

      setsid nohup python tools/healthd.py --port 9100 &

* **--once** — single foreground poll, rollup JSON on stdout, exit code
  by state: 0 while serving, 2 once any component is FAILING. With
  ``--strict`` a DEGRADED component or any counted SLO breach also fails
  (exit 1) — the CI gate shape.

Safety notes inherited from the old monitor: bench children get a
generous bound and are stopped with SIGTERM (60 s grace), never an
immediate SIGKILL — hard-killing a JAX process mid-compile is what wedges
the transport for every later process.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from spark_rapids_ml_tpu.utils import knobs  # noqa: E402

LOG_PATH = os.path.join(REPO, "TRANSPORT_LOG_r05.jsonl")
# Output names are env-overridable so a SUPPLEMENTAL harvest instance can
# run after the primary landed (e.g. when new bench extras are added
# mid-round and deserve their own on-chip values: point BENCH_OUT at a
# _r05b file and the "already harvested?" check follows it).
BENCH_OUT = os.path.join(
    REPO,
    os.environ.get(
        knobs.MONITOR_BENCH_OUT.name, "BENCH_OPPORTUNISTIC_r05.json"
    ),
)
DRIFT_OUT = os.path.join(
    REPO, os.environ.get(knobs.MONITOR_DRIFT_OUT.name, "BENCH_DRIFT_r05.jsonl")
)

PROBE_INTERVAL_S = float(os.environ.get(knobs.MONITOR_INTERVAL_S.name, "600"))
PROBE_TIMEOUT_S = float(
    os.environ.get(knobs.MONITOR_PROBE_TIMEOUT_S.name, "120")
)
ROUND_WINDOW_S = float(
    os.environ.get(knobs.MONITOR_WINDOW_S.name, str(11.5 * 3600))
)
N_BENCH_RUNS = int(os.environ.get(knobs.MONITOR_BENCH_RUNS.name, "5"))
BENCH_TIMEOUT_S = float(
    os.environ.get(knobs.MONITOR_BENCH_TIMEOUT_S.name, "3600")
)

START = time.time()


def now_iso() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds"
    )


def append(path: str, record: dict) -> None:
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
        os.fsync(f.fileno())


# -- opportunistic bench harvest (ported from the retired r5 monitor) --------


def run_bench(run_idx: int) -> dict:
    """One full bench run; returns the drift-log record."""
    env = dict(os.environ)
    # The monitor just proved the transport healthy; the bench's own
    # preamble only needs a short re-confirmation window.
    env[knobs.BENCH_PROBE_WINDOW_S.name] = "300"
    start = time.time()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=BENCH_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        # SIGTERM the whole process group, generous grace, never jump
        # straight to SIGKILL (a hard kill mid-compile wedges the tunnel).
        os.killpg(proc.pid, signal.SIGTERM)
        try:
            out, err = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            out, err = proc.communicate()
    took = time.time() - start
    json_line = None
    for line in (out or "").splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            json_line = line
    record = {
        "t": now_iso(),
        "elapsed_s": round(time.time() - START, 1),
        "run": run_idx,
        "rc": proc.returncode,
        "took_s": round(took, 1),
        "json": json.loads(json_line) if json_line else None,
    }
    if proc.returncode != 0 or json_line is None:
        record["stderr_tail"] = (err or "")[-2000:]
    return record


def harvest() -> bool:
    """Run the bench N times; write BENCH_OPPORTUNISTIC on first full rc=0."""
    wrote_primary = False
    for i in range(1, N_BENCH_RUNS + 1):
        rec = run_bench(i)
        append(DRIFT_OUT, rec)
        print(f"[healthd] bench run {i}/{N_BENCH_RUNS}: rc={rec['rc']} "
              f"took={rec['took_s']}s", flush=True)
        if not wrote_primary and rec["rc"] == 0 and rec["json"] is not None:
            payload = dict(rec["json"])
            # bench.py's snapshot-time fallback only trusts a harvest
            # stamped fresh enough to be from the CURRENT round — a
            # committed harvest from a past round must never be re-emitted
            # as this round's measurement
            payload["harvested_at_unix"] = round(time.time(), 1)
            payload["harvested_at"] = now_iso()
            with open(BENCH_OUT, "w") as f:
                json.dump(payload, f, indent=2)
                f.write("\n")
            wrote_primary = True
        if rec["rc"] != 0 and rec["json"] is None and i >= 2 and not wrote_primary:
            # Transport re-wedged mid-harvest; go back to probing.
            return False
    return wrote_primary


# -- CLI ---------------------------------------------------------------------


def _exit_code(rollup: dict, *, strict: bool) -> int:
    state = rollup.get("state", "OK")
    if state == "FAILING":
        return 2
    if strict:
        if state == "DEGRADED":
            return 1
        if (rollup.get("slo") or {}).get("total_breaches", 0):
            return 1
    return 0


def run_once(args) -> int:
    from spark_rapids_ml_tpu.telemetry import health

    mon = health.HealthMonitor(
        interval_s=args.interval,
        probe_mode=args.probe,
        probe_timeout_s=args.probe_timeout,
    )
    try:
        rollup = mon.poll_once()
    finally:
        mon.stop()
    print(json.dumps(rollup, indent=2))
    return _exit_code(rollup, strict=args.strict)


def run_watch(args) -> int:
    from spark_rapids_ml_tpu.telemetry import health, httpd

    mon = health.start_monitor(
        interval_s=args.interval,
        probe_mode=args.probe,
        probe_timeout_s=args.probe_timeout,
    )
    server = None
    if args.port is not None:
        server = httpd.start_http_server(args.port, with_monitor=False)
        print(f"[healthd] exporter at {server.url}", flush=True)
    harvested = args.no_harvest or os.path.exists(BENCH_OUT)
    tick = threading.Event()
    print(
        f"[healthd] start {now_iso()} interval={args.interval}s "
        f"probe={args.probe} window={ROUND_WINDOW_S}s harvested={harvested}",
        flush=True,
    )
    try:
        while time.time() - START < ROUND_WINDOW_S:
            # the monitor thread polls on its own cadence; this loop is the
            # durable on-disk timeline + harvest trigger
            tick.wait(args.interval)
            rollup = mon.rollup() if mon.polls else mon.poll_once()
            transport = rollup["components"].get("transport", {})
            append(LOG_PATH, {
                "t": now_iso(),
                "elapsed_s": round(time.time() - START, 1),
                "state": rollup["state"],
                "components": {
                    c: v["state"] for c, v in rollup["components"].items()
                },
                "slo_breaches": (rollup.get("slo") or {}).get(
                    "total_breaches", 0
                ),
            })
            print(
                f"[healthd] state={rollup['state']} "
                f"transport={transport.get('state', '?')}",
                flush=True,
            )
            if transport.get("state") == "OK" and not harvested:
                append(LOG_PATH, {"t": now_iso(), "event": "harvest_start"})
                harvested = harvest()
                append(LOG_PATH, {
                    "t": now_iso(),
                    "event": "harvest_done",
                    "complete": harvested,
                })
    except KeyboardInterrupt:
        print("[healthd] interrupted", flush=True)
    finally:
        if server is not None:
            httpd.stop_http_server(stop_monitor=False)
        health.stop_monitor()
    print(f"[healthd] window exhausted at {now_iso()}", flush=True)
    return 0


def main(argv=None) -> int:
    from spark_rapids_ml_tpu.telemetry import health

    p = argparse.ArgumentParser(
        description="live health daemon: probes, SLOs, /metrics + /healthz"
    )
    p.add_argument(
        "--once", action="store_true",
        help="poll once, print the rollup JSON, exit by state",
    )
    p.add_argument(
        "--strict", action="store_true",
        help="with --once: DEGRADED or any SLO breach also fails (CI gate)",
    )
    p.add_argument(
        "--port", type=int, default=None,
        help="also serve /metrics,/healthz,/slo,/report on this port "
        "(0 = ephemeral; watch mode only)",
    )
    p.add_argument(
        "--interval", type=float, default=PROBE_INTERVAL_S,
        help=f"poll interval seconds (default {knobs.MONITOR_INTERVAL_S.name} "
        "or 600)",
    )
    p.add_argument(
        "--probe", choices=health.PROBE_MODES, default="subprocess",
        help="transport liveness probe mode (default subprocess, the only "
        "mode safe against a wedged transport poisoning this process)",
    )
    p.add_argument(
        "--probe-timeout", type=float, default=PROBE_TIMEOUT_S,
        help=f"probe deadline seconds (default "
        f"{knobs.MONITOR_PROBE_TIMEOUT_S.name} or 120)",
    )
    p.add_argument(
        "--no-harvest", action="store_true",
        help="watch mode: disable the opportunistic bench harvest",
    )
    args = p.parse_args(argv)
    if args.once:
        return run_once(args)
    return run_watch(args)


if __name__ == "__main__":
    sys.exit(main())
