#!/usr/bin/env python
"""Render ANN vector-search evidence: recall curve, fill skew, speedup.

Usage::

    python tools/ann_report.py /path/to/perf.jsonl [--last N] [--strict]

Reads JSONL (or a single JSON document) and renders every record that
carries ANN evidence — either a perf-ledger entry whose ``ann`` key holds
the blob ``bench.py --smoke`` embeds, or a bare blob written directly.
For each:

- the index geometry and build line (rows, nlist, streamed build rate);
- the bucket-fill distribution vs the packed cap — the cap is the bytes
  EVERY probe gathers, so a skewed tail (p99 far above p50) means most
  probes pay for the fattest cells;
- the headline operating point: serving-native q/s, the exact-KNN q/s
  measured on the same corpus/batch, their ratio (the "what did the
  index buy" number), and recall@k vs the exact oracle;
- the recall-vs-nprobe operating curve — what the next rung of probe
  cost would buy;
- anomaly checks:

  - ``probe-skew`` — bucket-fill p99 exceeds twice the median: the
    quantizer left merged or starved cells, the percentile cap is paying
    for the fat tail, and every probe's gather is correspondingly wider.
    The streamed build's between-pass rebalance (empty-cell reseeding +
    overfull splits) should prevent this; a skewed corpus that defeats
    it wants a larger ``TPU_ML_ANN_SAMPLE_ROWS`` or more ``maxIter``.
  - ``recall-cliff`` — recall at the registered nprobe sits more than
    0.05 below what the sweep reaches at higher nprobe: the operating
    point is under the cliff, and one more probe rung would buy real
    recall (raise ``nprobe`` at registration).
  - ``recall-not-monotone`` — the sweep DECREASES as nprobe grows,
    which a correct top-k merge cannot do: the scan or merge kernel is
    broken, not the tuning.
  - ``recall-below-bar`` — recall@k at the operating point is under
    0.95, the acceptance floor the smoke bench gates on.
  - ``index-no-speedup`` — ann q/s is under 100x the exact baseline:
    the index is not buying its complexity on this geometry.
  - ``query-path-recompile`` — nonzero backend compiles in the timed
    query window: a query landed outside the AOT (bucket, nprobe)
    ladder and paid a synchronous XLA compile on the serve path.
  - ``spill-heavy`` — more than 5% of the corpus overflowed into the
    exact-scan spill list every query must cross; the percentile cap
    (``TPU_ML_ANN_CAP_PERCENTILE``) is mis-sized for the skew.

Exit status: 0 normally; with ``--strict``, 2 when any anomaly fired OR
any record had to be skipped (CI gate). Stdlib-only — renders on hosts
without jax installed.
"""

from __future__ import annotations

import argparse
import json
import sys

RECALL_BAR = 0.95
RATIO_BAR = 100.0
CLIFF_GAP = 0.05
SKEW_FACTOR = 2.0
SPILL_FRACTION_BAR = 0.05


def _table(rows: list[list[str]], header: list[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    def line(cells):
        return "  ".join(str(c).ljust(w) for c, w in zip(cells, widths)).rstrip()
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(header), sep] + [line(r) for r in rows])


def extract_evidence(rec: dict) -> dict | None:
    """Pull the ANN blob out of a record, whatever wrapper it arrived in:
    a perf-ledger entry (``ann`` key), or the bare blob."""
    if isinstance(rec.get("ann"), dict):
        return rec["ann"]
    if rec.get("type") == "ann_evidence" or "ann_recall_at_10" in rec:
        return rec
    return None


def check_anomalies(ev: dict) -> list[str]:
    out: list[str] = []
    fill = ev.get("bucket_fill") or {}
    p50, p99 = fill.get("p50", 0) or 0, fill.get("p99", 0) or 0
    if p50 and p99 > SKEW_FACTOR * p50:
        out.append(
            f"probe-skew: bucket-fill p99 ({p99:g}) is more than "
            f"{SKEW_FACTOR:g}x the median ({p50:g}) — merged or starved "
            "quantizer cells are inflating the packed cap, and every "
            "probe's gather pays for the fat tail; raise "
            "TPU_ML_ANN_SAMPLE_ROWS or maxIter so the between-pass "
            "rebalance can level the cells"
        )
    sweep = ev.get("recall_vs_nprobe") or []
    recalls = [s.get("recall_at_10", 0.0) for s in sweep]
    operating = ev.get("ann_recall_at_10")
    if operating is not None and recalls:
        best = max(recalls)
        if best - operating > CLIFF_GAP:
            at = next(
                (s["nprobe"] for s in sweep
                 if s.get("recall_at_10", 0.0) >= best - 1e-9),
                "?",
            )
            out.append(
                f"recall-cliff: recall at the registered nprobe="
                f"{ev.get('nprobe', '?')} is {operating:.4f} but the sweep "
                f"reaches {best:.4f} at nprobe={at} — the operating point "
                "sits under the cliff; re-register with a higher nprobe"
            )
    drops = [
        (sweep[i - 1], sweep[i])
        for i in range(1, len(sweep))
        if recalls[i] < recalls[i - 1] - 1e-6
    ]
    if drops:
        a, b = drops[0]
        out.append(
            f"recall-not-monotone: recall fell from "
            f"{a['recall_at_10']:.4f} at nprobe={a['nprobe']} to "
            f"{b['recall_at_10']:.4f} at nprobe={b['nprobe']} — widening "
            "the probe set can only add candidates to a correct top-k "
            "merge, so the scan/merge kernel is broken"
        )
    if operating is not None and operating < RECALL_BAR:
        out.append(
            f"recall-below-bar: recall@{ev.get('k', '?')} {operating:.4f} "
            f"is under the {RECALL_BAR} acceptance floor"
        )
    ratio = ev.get("qps_ratio")
    if ratio is not None and ratio < RATIO_BAR:
        out.append(
            f"index-no-speedup: ann q/s is only {ratio:g}x the exact "
            f"brute-force baseline (floor {RATIO_BAR:g}x) — the index is "
            "not buying its complexity on this geometry"
        )
    recompiles = ev.get("ann_recompiles_after_warmup", 0) or 0
    if recompiles:
        out.append(
            f"query-path-recompile: {recompiles:g} backend compile(s) in "
            "the timed query window — a query landed outside the AOT "
            "(bucket, nprobe) ladder and paid a synchronous XLA compile "
            "on the serve path"
        )
    spill = ev.get("spill_fraction", 0.0) or 0.0
    if spill > SPILL_FRACTION_BAR:
        out.append(
            f"spill-heavy: {spill:.1%} of the corpus lives in the exact-"
            "scan spill list every query must cross (floor "
            f"{SPILL_FRACTION_BAR:.0%}); TPU_ML_ANN_CAP_PERCENTILE is "
            "mis-sized for this skew"
        )
    return out


def render_record(rec: dict, out=sys.stdout) -> list[str] | None:
    """Render one record's ANN evidence; returns its anomaly list, or
    None when the record carries none."""
    ev = extract_evidence(rec)
    if ev is None:
        return None
    tag = rec.get("bench") or rec.get("name") or "ann"
    when = rec.get("timestamp") or rec.get("time") or ""
    head = f"\n=== {tag} ann index"
    if when:
        head += f" @ {when}"
    print(head + " ===", file=out)

    print(
        f"geometry: {ev.get('rows', 0):g} rows x "
        f"{ev.get('n_features', 0):g} features, nlist="
        f"{ev.get('nlist', 0):g}, nprobe={ev.get('nprobe', 0):g}, "
        f"k={ev.get('k', 0):g}",
        file=out,
    )
    if ev.get("build_seconds"):
        print(
            f"streamed build: {ev['build_seconds']:g}s "
            f"({ev.get('build_rows_per_s', 0):g} rows/s, corpus never "
            "fully resident)",
            file=out,
        )
    fill = ev.get("bucket_fill") or {}
    if fill:
        print(
            f"bucket fill vs cap {ev.get('bucket_cap', 0):g}: mean "
            f"{fill.get('mean', 0):g}, p50 {fill.get('p50', 0):g}, p99 "
            f"{fill.get('p99', 0):g}, max {fill.get('max', 0):g}; spill "
            f"{ev.get('spill_rows', 0):g} row(s) "
            f"({ev.get('spill_fraction', 0.0):.2%})",
            file=out,
        )
    if ev.get("ann_qps") is not None:
        line = (
            f"throughput: {ev['ann_qps']:g} q/s served vs "
            f"{ev.get('knn_qps', 0):g} q/s exact"
        )
        if ev.get("qps_ratio") is not None:
            line += f" ({ev['qps_ratio']:g}x)"
        line += (
            f", recall@{ev.get('k', 0):g} "
            f"{ev.get('ann_recall_at_10', 0.0):.4f}"
        )
        print(line, file=out)
    sweep = ev.get("recall_vs_nprobe") or []
    if sweep:
        reg = ev.get("nprobe")
        rows = [
            [
                f"{s.get('nprobe', 0):g}"
                + (" *" if s.get("nprobe") == reg else ""),
                f"{s.get('recall_at_10', 0.0):.4f}",
            ]
            for s in sweep
        ]
        print(_table(rows, ["nprobe", "recall@10"]), file=out)
        if reg is not None:
            print("  (* = registered operating point)", file=out)

    anomalies = check_anomalies(ev)
    for a in anomalies:
        print(f"  !! {a}", file=out)
    if not anomalies:
        print("  anomaly checks: ok", file=out)
    return anomalies


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render spark_rapids_ml_tpu ANN index evidence"
    )
    ap.add_argument(
        "path",
        help="perf-ledger JSONL (bench.py --smoke) or bare ANN blob JSON",
    )
    ap.add_argument(
        "--last", type=int, default=0, metavar="N",
        help="only render the last N ANN records",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="exit 2 when any anomaly check fires or a record is skipped",
    )
    args = ap.parse_args(argv)

    records = []
    skipped = 0
    try:
        with open(args.path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 1
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            print("# skipping corrupt line", file=sys.stderr)
            skipped += 1
            continue
        if isinstance(rec, dict) and extract_evidence(rec) is not None:
            records.append(rec)
    if not records:
        print(f"no ann evidence in {args.path}", file=sys.stderr)
        return 1
    if args.last > 0:
        records = records[-args.last:]

    print(f"{len(records)} ann record(s) from {args.path}")
    any_anomaly = False
    for i, rec in enumerate(records):
        try:
            anomalies = render_record(rec)
        except Exception as e:  # noqa: BLE001 — a bad record must not
            # hide the rest of the file
            print(
                f"# skipping unrenderable record {i} "
                f"({type(e).__name__}: {e})",
                file=sys.stderr,
            )
            skipped += 1
            continue
        if anomalies:
            any_anomaly = True
    if skipped:
        print(f"# {skipped} record(s) skipped", file=sys.stderr)
    return 2 if (args.strict and (any_anomaly or skipped)) else 0


if __name__ == "__main__":
    raise SystemExit(main())
