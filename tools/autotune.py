#!/usr/bin/env python
"""Offline autotuner CLI: search, inspect, and bless the tuning cache.

The in-fit search (``TPU_ML_AUTOTUNE=search``) spends its trial budget on
the user's first fit of an unseen shape bucket. This CLI moves that cost
offline: run the same bounded successive-halving search on a synthetic
stream of the production shape, inspect the winner, and ``--bless`` it
into the persistent JSON cache that production fits then consult read-only
(``TPU_ML_AUTOTUNE=cache``, the default mode) — the same
search → inspect → bless workflow as tools/perf_sentinel.py::

    # search the streamed-fold geometry for a 1M x 512 f64 fit
    python -m tools.autotune --n 512 --rows 1048576

    # same, and write the winner into the blessed cache file
    TPU_ML_TUNING_CACHE_PATH=tuning_cache.json \\
        python -m tools.autotune --n 512 --rows 1048576 --bless

    # show every entry the current cache resolves to
    python -m tools.autotune --show

Trials dispatch the real jitted Gram fold (``ops.linalg.gram_fold_step``)
on the current backend, so winners are per-device-kind by construction —
the cache key embeds backend/device, and a cache blessed on CPU never
misleads a TPU fit.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# runnable straight from a checkout (matches the other tools/ CLIs)
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from spark_rapids_ml_tpu import autotune  # noqa: E402
from spark_rapids_ml_tpu.autotune import cache  # noqa: E402
from spark_rapids_ml_tpu.utils import knobs  # noqa: E402

DEFAULT_KERNEL = "stream.fold_step"


def _show(path: str) -> int:
    entries = cache.entries()
    if not entries:
        print("tuning cache is empty" + (f" ({path})" if path else
                                         " (no persistent path set)"))
        return 0
    for key in sorted(entries):
        entry = entries[key]
        config = entry.get("config", {})
        provenance = ", ".join(
            f"{k}={entry[k]}" for k in ("trials", "measured_s") if k in entry
        )
        print(f"{key}")
        print(f"  config: {json.dumps(config, sort_keys=True)}"
              + (f"  ({provenance})" if provenance else ""))
    return 0


def _search(args) -> int:
    import numpy as np

    import jax

    from spark_rapids_ml_tpu.ops import linalg as L
    from spark_rapids_ml_tpu.spark import ingest

    dtype = np.dtype(args.dtype)
    base = args.chunk_rows or ingest.stream_chunk_rows()
    carry = L.init_gram_carry(args.n, dtype)
    measure = autotune.stream_fold_measure(
        L.gram_fold_step(), carry, args.n, dtype, jax.device_put,
        reps=args.reps,
    )
    candidates = autotune.candidate_grid(base)
    key = cache.cache_key(args.kernel, n=args.n, rows=args.rows, dtype=dtype)
    print(f"searching {key}: {len(candidates)} candidate(s), "
          f"budget {args.trials} trial(s)")
    winner, trials = autotune.successive_halving(
        candidates, measure, budget=args.trials
    )
    if winner is None:
        print("no winner: every trial failed — cache left untouched",
              file=sys.stderr)
        return 1
    print(f"winner after {trials} trial(s): {winner.key()}")
    cache.store(key, winner, trials=trials, persist=False)
    if args.bless or args.out:
        path = args.out or cache.cache_path()
        if not path:
            print(
                "error: --bless needs a destination — set "
                f"{knobs.TUNING_CACHE_PATH.name} or pass --out",
                file=sys.stderr,
            )
            return 1
        cache.write_cache(path, cache.entries())
        print(f"blessed: {path} now holds {len(cache.entries())} entry(ies); "
              f"fits with {knobs.TUNING_CACHE_PATH.name}={path} consult it "
              "read-only")
    else:
        print("dry run (in-process only): re-run with --bless to persist")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Search/inspect/bless the spark_rapids_ml_tpu tuning "
        "cache offline"
    )
    ap.add_argument("--kernel", default=DEFAULT_KERNEL,
                    help=f"kernel signature to tune (default {DEFAULT_KERNEL})")
    ap.add_argument("--n", type=int, help="feature width of the target fit")
    ap.add_argument("--rows", type=int, default=None,
                    help="row count of the target fit (bucketed; omit for "
                    "a rows-agnostic entry)")
    ap.add_argument("--dtype", default="float64",
                    help="wire dtype of the target fit (default float64)")
    ap.add_argument("--trials", type=int, default=None,
                    help="trial budget (default "
                    f"{knobs.AUTOTUNE_TRIALS.name} or "
                    f"{autotune.search.DEFAULT_TRIALS})")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed folds per trial (default 3)")
    ap.add_argument("--chunk-rows", type=int, default=None,
                    help="base chunk rows for the candidate grid (default "
                    f"{knobs.STREAM_CHUNK_ROWS.name})")
    ap.add_argument("--out", default=None,
                    help="write the blessed cache to this path instead of "
                    f"{knobs.TUNING_CACHE_PATH.name}")
    ap.add_argument("--bless", action="store_true",
                    help="persist the winner into the blessed cache file")
    ap.add_argument("--show", action="store_true",
                    help="print the current cache entries and exit")
    args = ap.parse_args(argv)

    if args.show:
        return _show(cache.cache_path())
    if args.n is None:
        ap.error("--n is required (or use --show)")
    if args.trials is None:
        args.trials = autotune.trial_budget()
    return _search(args)


if __name__ == "__main__":
    raise SystemExit(main())
