/*
 * Accelerated batch transform for the JVM shim — the dual-path model the
 * reference ships (RapidsPCA.scala:128-161: a GPU columnar UDF for batch
 * inference, with a per-row CPU fallback). The engine here is the
 * Python/JAX/XLA runtime, so the accelerated path crosses the same process
 * boundary the fit does:
 *
 *   1. the dataset (row-id + input column only) is staged to parquet;
 *   2. `python -m spark_rapids_ml_tpu.jvm_bridge transform-pca ...`
 *      streams it batch-by-batch through the device projection and writes
 *      (row-id, projection) parquet back;
 *   3. the projection is joined back on the row id, so every passthrough
 *      column keeps its exact JVM type (no UDT round-trips through foreign
 *      parquet writers).
 *
 * Anything that breaks the batch path — no usable python, a multi-node
 * master without a shared stagingDir — falls back to the stock JVM row
 * projection, mirroring the reference's use_gemm_fallback contract.
 */
package com.nvidia.spark.ml.feature

import java.nio.file.{Files, Path => JPath}
import java.util.Comparator

import scala.sys.process._
import scala.util.control.NonFatal

import org.apache.spark.ml.Model
import org.apache.spark.ml.feature.PCAModel
import org.apache.spark.ml.functions.array_to_vector
import org.apache.spark.ml.linalg.{DenseMatrix, DenseVector}
import org.apache.spark.ml.param.{Param, ParamMap}
import org.apache.spark.ml.util.{Identifiable, MLWritable, MLWriter}
import org.apache.spark.sql.{DataFrame, Dataset}
import org.apache.spark.sql.functions.{col, monotonically_increasing_id}
import org.apache.spark.sql.types.StructType

class TpuPCAModel private[feature] (
    override val uid: String,
    val stock: PCAModel)
  extends Model[TpuPCAModel] with MLWritable {

  private val log = org.slf4j.LoggerFactory.getLogger(classOf[TpuPCAModel])

  def pc: DenseMatrix = stock.pc
  def explainedVariance: DenseVector = stock.explainedVariance
  def getInputCol: String = stock.getInputCol
  def getOutputCol: String = stock.getOutputCol

  /** Python interpreter with spark_rapids_ml_tpu importable. */
  final val pythonExec: Param[String] =
    new Param[String](this, "pythonExec", "python interpreter for the bridge")

  /** Shared staging dir — same contract as PCA.stagingDir: required on
    * multi-node masters, driver-local temp otherwise. */
  final val stagingDir: Param[String] =
    new Param[String](this, "stagingDir", "shared staging dir for the handoff")

  setDefault(pythonExec -> "python3", stagingDir -> "")

  def setPythonExec(value: String): this.type = set(pythonExec, value)
  def setStagingDir(value: String): this.type = set(stagingDir, value)

  override def transform(dataset: Dataset[_]): DataFrame = {
    transformSchema(dataset.schema, logging = true)
    val master = dataset.sparkSession.sparkContext.master
    val canBatch = master.startsWith("local") || $(stagingDir).nonEmpty
    if (!canBatch) {
      log.info("TpuPCAModel: multi-node master without stagingDir — using " +
        "the stock JVM row projection")
      return stock.transform(dataset)
    }
    try transformBatch(dataset.toDF()) catch {
      case NonFatal(e) =>
        log.warn("TpuPCAModel: bridge batch transform failed " +
          s"(${e.getMessage}); falling back to the stock JVM row projection")
        stock.transform(dataset)
    }
  }

  private def transformBatch(df: DataFrame): DataFrame = {
    val spark = df.sparkSession
    val scratch: JPath =
      if ($(stagingDir).nonEmpty) Files.createTempDirectory(
        java.nio.file.Paths.get($(stagingDir)), "tpuml-pca-transform-")
      else Files.createTempDirectory("tpuml-pca-transform-")
    val idCol = "__tpuml_row_id"
    require(!df.columns.contains(idCol),
      s"input already carries the reserved column $idCol")
    val inputDir = scratch.resolve("input").toString
    val modelDir = scratch.resolve("model").toString
    val resultDir = scratch.resolve("result").toString
    // persist BEFORE branching the plan: monotonically_increasing_id is
    // only deterministic on a fixed partitioning, and the id column is
    // evaluated twice (once for the staged write, once for the join)
    val withId = df.withColumn(idCol, monotonically_increasing_id()).persist()
    try {
      withId.select(col(idCol), col(getInputCol))
        .write.mode("overwrite").parquet(inputDir)
      // the stock writer emits the stock Spark ML layout, which the
      // bridge's PCAModel.load auto-detects
      stock.write.overwrite().save(modelDir)
      val cmd = Seq(
        $(pythonExec), "-m", "spark_rapids_ml_tpu.jvm_bridge", "transform-pca",
        "--input", inputDir, "--model", modelDir, "--output", resultDir,
        "--input-col", getInputCol, "--output-col", getOutputCol)
      val exit = Process(cmd).!
      require(exit == 0, s"jvm_bridge transform-pca failed with exit code $exit")
      val proj = spark.read.parquet(resultDir).select(
        col(idCol),
        array_to_vector(col(getOutputCol)).as(getOutputCol))
      val out = withId.join(proj, idCol).drop(idCol)
      // the joined plan lazily reads the scratch parquet and the persisted
      // id frame, so both must outlive this call: release them at JVM exit
      // (Spark's ContextCleaner also reclaims the cache blocks earlier,
      // once the plan becomes unreachable). The staged copy is id + input
      // column + [rows, k] output, not the full dataset.
      sys.addShutdownHook {
        try withId.unpersist(blocking = false) catch { case NonFatal(_) => () }
        Files.walk(scratch).sorted(Comparator.reverseOrder[JPath]())
          .forEach(p => Files.deleteIfExists(p))
      }
      out
    } catch {
      case NonFatal(e) =>
        withId.unpersist()
        Files.walk(scratch).sorted(Comparator.reverseOrder[JPath]())
          .forEach(p => Files.deleteIfExists(p))
        throw e
    }
  }

  override def transformSchema(schema: StructType): StructType =
    stock.transformSchema(schema)

  override def copy(extra: ParamMap): TpuPCAModel = {
    val copied = new TpuPCAModel(uid, stock)
    copyValues(copied, extra).setParent(parent)
  }

  /** Persists as a STOCK PCAModel save — loadable by stock Spark ML
    * anywhere, and re-wrappable here via [[TpuPCAModel.load]]. */
  override def write: MLWriter = stock.write
}

object TpuPCAModel {
  /** Wrap a stock model (e.g. the one `new PCA().fit(df)` returns) with the
    * bridge-accelerated batch transform. */
  def wrap(stock: PCAModel): TpuPCAModel =
    new TpuPCAModel(Identifiable.randomUID("tpu-pca-model"), stock)

  def load(path: String): TpuPCAModel = wrap(PCAModel.load(path))
}
