/*
 * Thin JVM shim over the spark-rapids-ml-tpu Python runtime.
 *
 * Drop-in surface parity target: the reference's
 * com.nvidia.spark.ml.feature.PCA (reference PCA.scala:27-37), whose user
 * story is "change one import and your Scala Spark ML pipeline runs
 * accelerated". The reference could implement that natively in Scala
 * because its engine lives in the executor JVM (spark-rapids plugin +
 * JNI); this framework's engine is the Python/JAX/XLA runtime, so the shim
 * inverts the boundary:
 *
 *   1. write dataset.select(inputCol) to a staging parquet dir
 *      (public API only — no private Arrow hooks);
 *   2. exec `python -m spark_rapids_ml_tpu.jvm_bridge fit-pca ...`
 *      (driver-side; the fit fans out over the host's TPU mesh);
 *   3. the bridge writes the model in STOCK Spark ML layout, so this
 *      class finishes with org.apache.spark.ml.feature.PCAModel.load —
 *      the caller receives a stock Spark PCAModel with JVM-native
 *      transform, persistence, and Pipeline integration. No custom model
 *      class exists on the JVM side at all.
 *
 * Build: `mvn -f jvm/pom.xml package` (needs a JDK + Maven; the Python
 * package must be importable by the `python3` on PATH of the driver).
 * See jvm/README.md for the scope rationale.
 */
package com.nvidia.spark.ml.feature

import java.nio.file.{Files, Path => JPath}
import java.util.Comparator

import scala.sys.process._

import org.apache.spark.ml.Estimator
import org.apache.spark.ml.feature.PCAModel
import org.apache.spark.ml.linalg.VectorUDT
import org.apache.spark.ml.param.{IntParam, BooleanParam, Param, ParamMap, ParamValidators}
import org.apache.spark.ml.param.shared.{HasInputCol, HasOutputCol}
import org.apache.spark.ml.util.{DefaultParamsWritable, DefaultParamsReadable, Identifiable}
import org.apache.spark.sql.Dataset
import org.apache.spark.sql.functions.col
import org.apache.spark.sql.types.{ArrayType, StructField, StructType}

class PCA(override val uid: String)
    extends Estimator[PCAModel]
    with HasInputCol
    with HasOutputCol
    with DefaultParamsWritable {

  def this() = this(Identifiable.randomUID("tpu-pca"))

  /** Number of principal components (reference PCA.scala:31). */
  final val k: IntParam =
    new IntParam(this, "k", "number of principal components", ParamValidators.gt(0))

  /** Matches the reference's meanCentering param (RapidsPCA.scala:40-45) —
    * and actually centers, where the reference's is a TODO stub. */
  final val meanCentering: BooleanParam =
    new BooleanParam(this, "meanCentering", "center data before the covariance")

  /** Decomposition solver: full | randomized | svd | auto. */
  final val solver: Param[String] = new Param[String](
    this, "solver", "decomposition solver",
    ParamValidators.inArray(Array("full", "randomized", "svd", "auto")))

  /** Python interpreter with spark_rapids_ml_tpu importable. */
  final val pythonExec: Param[String] =
    new Param[String](this, "pythonExec", "python interpreter for the bridge")

  /** Staging directory for the parquet handoff. On a MULTI-NODE cluster
    * this MUST be a shared filesystem path visible to every executor AND
    * the driver (NFS mount, fuse-mounted object store, ...); the default
    * (empty = driver-local temp) is only correct under local[*] masters,
    * and fit() fails fast otherwise rather than training on the subset of
    * part files that happened to land on the driver host. */
  final val stagingDir: Param[String] =
    new Param[String](this, "stagingDir", "shared staging dir for the handoff")

  setDefault(meanCentering -> false, solver -> "full", pythonExec -> "python3",
    stagingDir -> "", outputCol -> "pca_features")

  def setInputCol(value: String): this.type = set(inputCol, value)
  def setOutputCol(value: String): this.type = set(outputCol, value)
  def setK(value: Int): this.type = set(k, value)
  def setMeanCentering(value: Boolean): this.type = set(meanCentering, value)
  def setSolver(value: String): this.type = set(solver, value)
  def setPythonExec(value: String): this.type = set(pythonExec, value)
  def setStagingDir(value: String): this.type = set(stagingDir, value)

  override def fit(dataset: Dataset[_]): PCAModel = {
    transformSchema(dataset.schema, logging = true)
    val master = dataset.sparkSession.sparkContext.master
    val sharedStaging = $(stagingDir).nonEmpty
    require(master.startsWith("local") || sharedStaging,
      s"master is $master (multi-node): executors write their parquet part " +
        "files to THEIR local filesystems, so the default driver-local " +
        "staging would silently train on a subset of the data. Call " +
        "setStagingDir(<path on a filesystem shared by all executors and " +
        "the driver>).")
    val scratch: JPath =
      if (sharedStaging) Files.createTempDirectory(
        java.nio.file.Paths.get($(stagingDir)), "tpuml-pca-")
      else Files.createTempDirectory("tpuml-pca-")
    try {
      val inputDir = scratch.resolve("input").toString
      val modelDir = scratch.resolve("model").toString
      dataset.select(col($(inputCol))).write.mode("overwrite").parquet(inputDir)

      val cmd = Seq(
        $(pythonExec), "-m", "spark_rapids_ml_tpu.jvm_bridge", "fit-pca",
        "--input", inputDir, "--output", modelDir,
        "--input-col", $(inputCol), "--output-col", $(outputCol),
        "--k", $(k).toString, "--solver", $(solver), "--layout", "spark") ++
        (if ($(meanCentering)) Seq("--mean-centering") else Seq.empty)
      val exit = Process(cmd).!
      require(exit == 0, s"jvm_bridge fit-pca failed with exit code $exit")

      // The bridge wrote the STOCK Spark ML layout: loading it yields a
      // stock PCAModel — JVM-native transform/persistence/Pipeline for free.
      val model = PCAModel.load(modelDir)
      copyValues(model.setParent(this))
    } finally {
      // the staged parquet is a full copy of the input column — never leak
      // it past the fit
      Files.walk(scratch).sorted(Comparator.reverseOrder[JPath]())
        .forEach(p => Files.deleteIfExists(p))
    }
  }

  override def transformSchema(schema: StructType): StructType = {
    require(schema.fieldNames.contains($(inputCol)),
      s"input column ${$(inputCol)} not found")
    val inType = schema($(inputCol)).dataType
    require(inType.isInstanceOf[VectorUDT] || inType.isInstanceOf[ArrayType],
      s"input column ${$(inputCol)} must be a Vector or ArrayType column, " +
        s"got $inType")
    require(!schema.fieldNames.contains($(outputCol)),
      s"output column ${$(outputCol)} already exists")
    // append outputCol like stock Spark PCA does, so Pipeline.fit's schema
    // chaining sees the column this stage will produce
    StructType(schema.fields :+ StructField($(outputCol), new VectorUDT, false))
  }

  override def copy(extra: ParamMap): PCA = defaultCopy(extra)
}

object PCA extends DefaultParamsReadable[PCA] {
  override def load(path: String): PCA = super.load(path)
}
