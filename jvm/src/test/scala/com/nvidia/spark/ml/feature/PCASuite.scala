/*
 * Differential suite following the reference's PCASuite pattern
 * (PCASuite.scala:42-88): fit the shim and stock Spark ML PCA on the same
 * data and compare components sign-invariantly at abs-tol 1e-5.
 *
 * Runs under `mvn -f jvm/pom.xml test` on a machine with a JDK and a
 * python3 that can import spark_rapids_ml_tpu.
 */
package com.nvidia.spark.ml.feature

import scala.math.abs
import scala.util.Random

import org.apache.spark.ml.feature.{PCA => SparkPCA}
import org.apache.spark.ml.linalg.Vectors
import org.apache.spark.sql.SparkSession
import org.scalatest.funsuite.AnyFunSuite

class PCASuite extends AnyFunSuite {

  private lazy val spark = SparkSession.builder()
    .master("local[4]")
    .appName("spark-rapids-ml-tpu-jvm-suite")
    .getOrCreate()

  test("shim PCA matches stock Spark ML PCA (sign-invariant, 1e-5)") {
    val rng = new Random(11)
    val rows = Seq.fill(300)(
      Tuple1(Vectors.dense(Array.fill(8)(rng.nextGaussian()))))
    import spark.implicits._
    val df = rows.toDF("features").repartition(4)

    val shimModel = new PCA()
      .setInputCol("features").setOutputCol("pca").setK(3)
      .fit(df)
    val stockModel = new SparkPCA()
      .setInputCol("features").setOutputCol("pca").setK(3)
      .fit(df)

    val a = shimModel.pc.toArray
    val b = stockModel.pc.toArray
    assert(a.length == b.length)
    a.zip(b).foreach { case (x, y) =>
      assert(abs(abs(x) - abs(y)) < 1e-5, s"component mismatch: $x vs $y")
    }

    // the shim returns a STOCK PCAModel: transform is JVM-native
    val out = shimModel.transform(df)
    assert(out.columns.contains("pca"))
    assert(out.count() == 300)
  }

  test("TpuPCAModel batch transform matches the stock projection (1e-6)") {
    val rng = new Random(12)
    val rows = Seq.tabulate(250)(i =>
      (i.toLong, Vectors.dense(Array.fill(6)(rng.nextGaussian()))))
    import spark.implicits._
    val df = rows.toDF("id", "features").repartition(3)

    val stockModel = new SparkPCA()
      .setInputCol("features").setOutputCol("pca").setK(3)
      .fit(df)
    val accel = TpuPCAModel.wrap(stockModel)

    val want = stockModel.transform(df)
      .select("id", "pca").as[(Long, org.apache.spark.ml.linalg.Vector)]
      .collect().toMap
    val got = accel.transform(df)
      .select("id", "pca").as[(Long, org.apache.spark.ml.linalg.Vector)]
      .collect().toMap
    assert(got.size == want.size)
    got.foreach { case (id, v) =>
      v.toArray.zip(want(id).toArray).foreach { case (a, b) =>
        assert(abs(a - b) < 1e-6, s"row $id: $a vs $b")
      }
    }
    // passthrough columns keep their types and values
    val cols = accel.transform(df).columns
    assert(cols.sameElements(Array("id", "features", "pca")))
  }
}
