"""Measurement-driven autotuning for the streamed-fit hot path.

Closes the loop the perf ledger opened: instead of hand-guessing
``TPU_ML_STREAM_CHUNK_ROWS``, staging layout, and compute precision, the
tuner measures candidates (:mod:`.search`, bounded successive halving),
remembers winners per (kernel, shape bucket, dtype, device) in a blessable
JSON cache (:mod:`.cache`), and stamps every resolution onto the FitReport
so tuned runs are self-describing. Mixed-precision kernel policies
(:mod:`.policy`) ride the same TuningConfig: bf16 operands with f32
accumulators, and an opt-in int8 distance path for candidate scoring —
accumulator dtypes never change, so donation and checkpoint/resume
semantics are preserved under every policy.

Modes (``TPU_ML_AUTOTUNE``): ``off`` (static knobs, seed behavior),
``cache`` (default — consult blessed winners, never search), ``search``
(additionally search unseen shape buckets on first fit). Offline tuning:
``python tools/autotune.py``.
"""

from spark_rapids_ml_tpu.autotune import cache, policy, search
from spark_rapids_ml_tpu.autotune.cache import (
    cache_key,
    decision_seq,
    decisions_since,
    reset,
    shape_bucket,
)
from spark_rapids_ml_tpu.autotune.policy import (
    FOLD_POLICIES,
    LAYOUTS,
    POLICIES,
    PrecisionPolicy,
    TuningConfig,
    resolve_policy,
    validate_policy,
)
from spark_rapids_ml_tpu.autotune.search import (
    MODES,
    candidate_grid,
    mode,
    resolve,
    stream_fold_measure,
    successive_halving,
    trial_budget,
)

__all__ = [
    "cache",
    "policy",
    "search",
    "cache_key",
    "decision_seq",
    "decisions_since",
    "reset",
    "shape_bucket",
    "FOLD_POLICIES",
    "LAYOUTS",
    "POLICIES",
    "PrecisionPolicy",
    "TuningConfig",
    "resolve_policy",
    "validate_policy",
    "MODES",
    "candidate_grid",
    "mode",
    "resolve",
    "stream_fold_measure",
    "successive_halving",
    "trial_budget",
]
