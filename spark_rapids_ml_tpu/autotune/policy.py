"""Precision policies and tuning configurations — the *vocabulary* of the
autotuner.

A :class:`PrecisionPolicy` names how a kernel's matmuls treat operand and
accumulator dtypes; a :class:`TuningConfig` bundles everything the tuner may
vary for one kernel signature (chunk geometry, staging layout, precision
policy, donation arrangement). Both are plain data: the numeric behavior
lives in the ops kernels, which accept ``policy=`` and branch on the policy
string, and the search/caching machinery (:mod:`.search`, :mod:`.cache`)
only ever moves these objects around.

The invariant every policy must preserve: **accumulators stay in the carry
dtype** (f32/f64). ``bf16_f32acc`` casts only the matmul *operands* to
bfloat16 and forces the MXU to accumulate in f32 via
``preferred_element_type``; ``int8_dist`` quantizes only the distance cross
term of kmeans/knn candidate scoring. The donated-carry fold contract
(tpulint TPL001) and bitwise checkpoint/resume semantics therefore hold
under every policy — a checkpoint written under ``bf16_f32acc`` resumes
bitwise-identically because the carry never changes dtype.

Import-pure apart from :mod:`utils.knobs` (no jax) so the linter, the CLI,
and jax-free worker processes can load it.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass

from spark_rapids_ml_tpu.utils import knobs

PRECISION_POLICY_VAR = knobs.PRECISION_POLICY.name


class PrecisionPolicy(str, enum.Enum):
    """Named mixed-precision kernel policies.

    - ``F32`` — full-precision operands (the matmul ``precision`` knob still
      applies); the seed behavior and the default everywhere.
    - ``BF16_F32ACC`` — matmul operands cast to bfloat16, accumulation
      forced to f32 with ``preferred_element_type``; the result is upcast
      back into the carry dtype. Roughly halves MXU operand bytes (bf16
      tile (16, 128) vs f32 (8, 128)) at ~3 decimal digits of operand
      mantissa.
    - ``INT8_DIST`` — opt-in symmetric int8 quantization of the *distance
      cross term only* (kmeans / knn candidate scoring): int8×int8 matmul
      accumulated in int32, dequantized against f32 norms. Never used for
      Gram/linear accumulation.
    """

    F32 = "f32"
    BF16_F32ACC = "bf16_f32acc"
    INT8_DIST = "int8_dist"


POLICIES: tuple[str, ...] = tuple(p.value for p in PrecisionPolicy)

#: Policies meaningful for accumulation kernels (Gram/moment/linear folds);
#: ``int8_dist`` applies only to distance scoring and is rejected there.
FOLD_POLICIES: tuple[str, ...] = (
    PrecisionPolicy.F32.value,
    PrecisionPolicy.BF16_F32ACC.value,
)

LAYOUTS: tuple[str, ...] = ("row", "col")


def validate_policy(policy: str, *, allowed: tuple[str, ...] = POLICIES) -> str:
    """Canonicalize ``policy`` (str or :class:`PrecisionPolicy`) or raise."""
    value = policy.value if isinstance(policy, PrecisionPolicy) else policy
    if value not in allowed:
        raise ValueError(
            f"precision policy {value!r} must be one of {allowed}"
        )
    return value


def resolve_policy(policy: str | None,
                   *, allowed: tuple[str, ...] = POLICIES) -> str:
    """Resolve an explicit policy, or ``None`` → the process default from
    ``TPU_ML_PRECISION_POLICY`` (default ``f32``).

    Resolution happens *before* any ``lru_cache``'d program builder sees the
    value, so an env change between calls selects a different cached
    program instead of a stale one.
    """
    if policy is None:
        policy = os.environ.get(PRECISION_POLICY_VAR, PrecisionPolicy.F32.value)
    return validate_policy(policy, allowed=allowed)


@dataclass(frozen=True)
class TuningConfig:
    """One point in the tuner's search space for one kernel signature.

    ``chunk_rows=None`` means "keep the static knob" — a config that only
    pins layout/policy. ``donate_carry`` records the donation arrangement
    for the ledger; every shipped fold donates (TPL001), so search grids
    only emit ``True``, but the field keeps tuned ledger entries
    self-describing.
    """

    chunk_rows: int | None = None
    layout: str = "row"
    policy: str = PrecisionPolicy.F32.value
    donate_carry: bool = True

    def __post_init__(self) -> None:
        if self.layout not in LAYOUTS:
            raise ValueError(f"layout {self.layout!r} must be one of {LAYOUTS}")
        validate_policy(self.policy)
        if self.chunk_rows is not None and self.chunk_rows < 1:
            raise ValueError(f"chunk_rows must be >= 1, got {self.chunk_rows}")

    def to_dict(self) -> dict:
        return {
            "chunk_rows": self.chunk_rows,
            "layout": self.layout,
            "policy": self.policy,
            "donate_carry": self.donate_carry,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TuningConfig":
        return cls(
            chunk_rows=d.get("chunk_rows"),
            layout=d.get("layout", "row"),
            policy=d.get("policy", PrecisionPolicy.F32.value),
            donate_carry=bool(d.get("donate_carry", True)),
        )

    def key(self) -> str:
        """Stable compact identity — ledger stamping and sentinel keying."""
        chunk = "knob" if self.chunk_rows is None else str(self.chunk_rows)
        donate = "1" if self.donate_carry else "0"
        return (
            f"chunk={chunk}|layout={self.layout}|policy={self.policy}"
            f"|donate={donate}"
        )
