"""Bounded measurement-driven search over tuning candidates.

Successive halving: every surviving candidate gets one timing trial per
round, the slower half is dropped, and rounds repeat until one candidate
survives or the trial budget (``TPU_ML_AUTOTUNE_TRIALS``) is spent — the
best mean among survivors wins. Timing reuses the existing ledger
machinery: each trial is a ``monotonic()`` wall measurement around the
caller-supplied ``measure(config)`` callable (which dispatches the real
jitted fold, so XLA's per-signature cost model is captured as a side
effect), wrapped in an ``autotune.trial`` span and a fault-injection gate
so chaos plans can kill individual trials.

A trial that raises drops *that candidate only* (``autotune.trial_failures``
counter); if every candidate dies the search returns ``None`` and the
caller falls back to the static knobs — a failed search never poisons the
cache.

:func:`resolve` is the one entry point the hot paths call: cache consult
(``TPU_ML_AUTOTUNE=cache``, the default), opportunistic search on an
unseen shape bucket (``search``), or nothing at all (``off``). Every
resolution is journaled for the FitReport ``tuning`` stamp.
"""

from __future__ import annotations

import logging
import os
import time

from spark_rapids_ml_tpu.autotune import cache
from spark_rapids_ml_tpu.autotune.policy import TuningConfig
from spark_rapids_ml_tpu.resilience import faults
from spark_rapids_ml_tpu.resilience.sites import AUTOTUNE_TRIAL
from spark_rapids_ml_tpu.telemetry import trace_range
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
from spark_rapids_ml_tpu.utils import knobs

logger = logging.getLogger("spark_rapids_ml_tpu")

AUTOTUNE_VAR = knobs.AUTOTUNE.name
AUTOTUNE_TRIALS_VAR = knobs.AUTOTUNE_TRIALS.name

MODES = ("off", "cache", "search")
DEFAULT_MODE = "cache"
DEFAULT_TRIALS = 9


def mode() -> str:
    """Tuner mode from ``TPU_ML_AUTOTUNE`` (unknown values → default)."""
    m = os.environ.get(AUTOTUNE_VAR, DEFAULT_MODE)
    if m not in MODES:
        logger.warning("%s=%r is not one of %s — using %r",
                       AUTOTUNE_VAR, m, MODES, DEFAULT_MODE)
        return DEFAULT_MODE
    return m


def trial_budget() -> int:
    """Total timing-trial budget for one search (min 1)."""
    try:
        return max(1, int(os.environ.get(AUTOTUNE_TRIALS_VAR,
                                         DEFAULT_TRIALS)))
    except ValueError:
        return DEFAULT_TRIALS


def candidate_grid(base_chunk_rows: int, *, floor: int = 8,
                   policy: str = "f32",
                   layouts: tuple[str, ...] = ("row", "col"),
                   ) -> list[TuningConfig]:
    """The default streamed-fold candidate grid: chunk rows at {½×, 1×, 2×}
    the static base × staging layouts, all donated (TPL001). The policy is
    *not* searched — silently trading accuracy for speed is the user's
    call, so it rides in from the resolved global policy unchanged."""
    sizes: list[int] = []
    for mult in (0.5, 1.0, 2.0):
        rows = max(floor, int(base_chunk_rows * mult))
        if rows not in sizes:
            sizes.append(rows)
    return [
        TuningConfig(chunk_rows=rows, layout=layout, policy=policy)
        for rows in sizes
        for layout in layouts
    ]


def _trial(config: TuningConfig, measure) -> float:
    """One timing trial: fault gate, span, wall-clock around ``measure``.

    ``measure(config)`` may return its own seconds measurement (injected
    timings in tests, per-row normalization in real measures); when it
    returns None the trial's wall time is used.
    """
    REGISTRY.counter_inc("autotune.trials")
    with trace_range("autotune.trial"):
        faults.inject(AUTOTUNE_TRIAL)
        t0 = time.monotonic()
        reported = measure(config)
        wall = time.monotonic() - t0
    return float(reported) if reported is not None else wall


def successive_halving(candidates, measure, *, budget: int | None = None,
                       ) -> tuple[TuningConfig | None, int]:
    """Run the search; returns ``(winner, trials_used)``.

    Deterministic given deterministic timings: candidate order breaks ties,
    each round measures every survivor once (budget permitting) and keeps
    the faster half by mean observed seconds.
    """
    if budget is None:
        budget = trial_budget()
    # (config, [seconds...]) for every candidate still alive
    alive: list[tuple[TuningConfig, list[float]]] = [
        (c, []) for c in list(candidates)[:max(1, budget)]
    ]
    trials = 0
    while alive and trials < budget:
        survivors: list[tuple[TuningConfig, list[float]]] = []
        for config, seen in alive:
            if trials >= budget:
                survivors.append((config, seen))
                continue
            trials += 1
            try:
                seen.append(_trial(config, measure))
            except Exception:  # noqa: BLE001 — a dead trial drops only itself
                REGISTRY.counter_inc("autotune.trial_failures")
                logger.warning("autotune trial failed for %s — dropping "
                               "candidate", config.key(), exc_info=True)
                continue
            survivors.append((config, seen))
        alive = survivors
        if len(alive) <= 1:
            break
        measured = [(c, s) for c, s in alive if s]
        if not measured:
            break
        measured.sort(key=lambda cs: sum(cs[1]) / len(cs[1]))
        keep = max(1, (len(measured) + 1) // 2)
        if keep == len(measured):
            break  # field can no longer shrink — winner is decided
        alive = measured[:keep]
    scored = [(c, sum(s) / len(s)) for c, s in alive if s]
    if not scored:
        return None, trials
    winner = min(scored, key=lambda cs: cs[1])
    return winner[0], trials


def search(kernel: str, key: str, candidates, measure,
           *, budget: int | None = None) -> TuningConfig | None:
    """Full search for one cache key: span, counters, cache store on win."""
    REGISTRY.counter_inc("autotune.search_runs")
    with trace_range("autotune.search"):
        winner, trials = successive_halving(candidates, measure,
                                            budget=budget)
    if winner is None:
        logger.warning("autotune search for %s produced no winner — "
                       "falling back to static knobs", key)
        return None
    cache.store(key, winner, trials=trials)
    return winner


def resolve(kernel: str, *, n: int, rows: int | None = None, dtype=None,
            measure=None, candidates=None,
            budget: int | None = None) -> TuningConfig | None:
    """The hot-path entry point: pick a TuningConfig for ``kernel`` at this
    shape, or ``None`` meaning "keep the static knobs".

    - mode ``off``: always ``None``, nothing journaled.
    - mode ``cache``: cache consult only.
    - mode ``search``: cache consult; on a miss, run the bounded search
      when the caller supplied ``measure`` + ``candidates``.
    """
    m = mode()
    if m == "off":
        return None
    key = cache.cache_key(kernel, n=n, rows=rows, dtype=dtype)
    config = cache.lookup(key)
    if config is not None:
        cache.record_decision(kernel=kernel, key=key, source="cache",
                              config=config)
        return config
    if m == "search" and measure is not None and candidates is not None:
        config = search(kernel, key, candidates, measure, budget=budget)
        if config is not None:
            cache.record_decision(kernel=kernel, key=key, source="search",
                                  config=config)
            return config
    cache.record_decision(kernel=kernel, key=key, source="default",
                          config=None)
    return None


def stream_fold_measure(fold_fn, carry, n: int, dtype, put,
                        *, want_y: bool = False, reps: int = 1,
                        seed: int = 0):
    """Build a ``measure(config)`` for the streamed-fold hot path.

    Each trial stages one synthetic host chunk at the candidate's geometry
    (rows × layout), warms the fold once (paying the per-shape compile
    outside the timed window), then times ``reps`` donated folds into a
    throwaway zero carry and reports **seconds per row** so different chunk
    sizes compare fairly. The caller's real carry is never touched — trials
    donate only their own ``zeros_like`` copy.
    """
    import numpy as np  # lazy: keeps this module importable without jax

    def measure(config: TuningConfig) -> float:
        import jax
        import jax.numpy as jnp

        rows = max(1, int(config.chunk_rows or 1))
        order = "F" if config.layout == "col" else "C"
        rng = np.random.default_rng(seed)
        x = np.asarray(rng.standard_normal((rows, n)), dtype=dtype,
                       order=order)
        args = [put(x)]
        if want_y:
            y = np.asarray(rng.standard_normal((rows,)), dtype=dtype)
            args.append(put(y))
        args.append(put(np.ones((rows,), dtype=dtype)))
        trial_carry = jax.tree_util.tree_map(jnp.zeros_like, carry)
        trial_carry = fold_fn(trial_carry, *args)  # warm (compile)
        jax.block_until_ready(trial_carry)
        t0 = time.monotonic()
        for _ in range(max(1, reps)):
            trial_carry = fold_fn(trial_carry, *args)
        jax.block_until_ready(trial_carry)
        return (time.monotonic() - t0) / (max(1, reps) * rows)

    return measure
