"""The persistent tuning cache + per-fit decision journal.

Search winners are remembered per *(kernel signature, shape bucket, wire
dtype, backend/device kind)* — the same bucketing that bounds distinct
compiled shapes (``utils.columnar.bucket_rows``) bounds distinct tuning
entries, so a 100k-row fit and a 120k-row fit of the same width share one
entry. Two tiers:

- **in-process** — every stored winner lands in a lock-guarded dict, so a
  repeat fit in the same process is a pure cache hit (zero search trials).
- **persistent JSON** at ``TPU_ML_TUNING_CACHE_PATH`` (empty = in-process
  only) — the *blessed* tier, written by ``tools/autotune.py`` (or any
  in-process search when the knob points at a file) and loaded lazily on
  first lookup. The blessing workflow mirrors the perf-sentinel one:
  search → inspect → ``--bless`` writes the file that CI and production
  fits then consult read-only (``TPU_ML_AUTOTUNE=cache``).

Every lookup books ``autotune.cache_hits`` / ``autotune.cache_misses``;
every resolution (hit, searched winner, or fallback to the static knobs)
is appended to a bounded decision journal that ``telemetry.report``
drains into the FitReport ``tuning`` field — the report shows *which*
config a fit actually ran with, not which one was configured.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from spark_rapids_ml_tpu.autotune.policy import TuningConfig
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
from spark_rapids_ml_tpu.telemetry.timeline import TIMELINE
from spark_rapids_ml_tpu.utils import knobs

logger = logging.getLogger("spark_rapids_ml_tpu")

TUNING_CACHE_PATH_VAR = knobs.TUNING_CACHE_PATH.name

CACHE_SCHEMA = 1

# decision journal ring bound — aggregate truth stays in the counters
MAX_JOURNAL_EVENTS = 256

_LOCK = threading.Lock()
_CACHE: dict[str, dict] = {}  # key -> {"config": {...}, ...provenance}
_LOADED_PATH: str | None = None  # which file the persistent tier came from
_JOURNAL: list[tuple[int, dict]] = []  # (seq, decision dict)
_SEQ = 0


def cache_path() -> str:
    """The persistent-cache location ('' = in-process only)."""
    return os.environ.get(TUNING_CACHE_PATH_VAR, "")


def shape_bucket(n: int, rows: int | None) -> str:
    """Bucket a fit shape: exact width (it keys the compiled programs) ×
    pow2 row bucket (rows vary run to run; the chunk geometry that wins at
    100k rows wins at 120k)."""
    if rows is None or rows <= 0:
        return f"n{int(n)}/rowsANY"
    bucket = 1
    while bucket < rows:
        bucket <<= 1
    return f"n{int(n)}/rows{bucket}"


def device_kind() -> str:
    """Backend/device identity of the cache key (lazy jax; 'unknown' when
    no backend is reachable — entries still key consistently in-process)."""
    try:
        import jax

        dev = jax.devices()[0]
        return f"{dev.platform}/{dev.device_kind}".replace(" ", "_")
    except Exception:  # noqa: BLE001 — cache must work without a backend
        return "unknown"


def cache_key(kernel: str, *, n: int, rows: int | None = None,
              dtype=None, device: str | None = None) -> str:
    """The full cache key: kernel signature, shape bucket, dtype, device."""
    dt = str(dtype) if dtype is not None else "any"
    dev = device if device is not None else device_kind()
    return f"{kernel}|{shape_bucket(n, rows)}|{dt}|{dev}"


def _ensure_loaded() -> None:
    """Lazily merge the persistent tier under ``_LOCK`` (held by caller).

    In-process entries win over file entries: a search that just ran in
    this process is fresher than the blessed file it may not have written.
    """
    global _LOADED_PATH
    path = cache_path()
    if path == _LOADED_PATH:
        return
    _LOADED_PATH = path
    if not path or not os.path.exists(path):
        return
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        entries = doc.get("entries", {}) if isinstance(doc, dict) else {}
        for key, entry in entries.items():
            if key not in _CACHE and isinstance(entry, dict):
                _CACHE[key] = dict(entry)
    except (OSError, ValueError):
        logger.warning("unreadable tuning cache at %s — ignoring", path,
                       exc_info=True)


def lookup(key: str) -> TuningConfig | None:
    """Consult the cache; books the hit/miss counters."""
    with _LOCK:
        _ensure_loaded()
        entry = _CACHE.get(key)
    if entry is None:
        REGISTRY.counter_inc("autotune.cache_misses")
        return None
    REGISTRY.counter_inc("autotune.cache_hits")
    try:
        return TuningConfig.from_dict(entry.get("config", {}))
    except (TypeError, ValueError):
        logger.warning("malformed tuning-cache entry for %s — ignoring", key)
        return None


def store(key: str, config: TuningConfig, *, measured_s: float | None = None,
          trials: int | None = None, persist: bool = True) -> None:
    """Remember a winner; rewrites the persistent file when a path is set."""
    entry: dict = {"config": config.to_dict()}
    if measured_s is not None:
        entry["measured_s"] = float(measured_s)
    if trials is not None:
        entry["trials"] = int(trials)
    with _LOCK:
        _ensure_loaded()
        _CACHE[key] = entry
        snapshot = {k: dict(v) for k, v in _CACHE.items()}
    if persist and cache_path():
        write_cache(cache_path(), snapshot)


def entries() -> dict[str, dict]:
    """Copy of the merged cache (CLI ``--show``, tests)."""
    with _LOCK:
        _ensure_loaded()
        return {k: dict(v) for k, v in _CACHE.items()}


def write_cache(path: str, cache_entries: dict[str, dict]) -> None:
    """Write the blessed persistent tier (atomic replace, sorted keys)."""
    doc = {
        "type": "tuning_cache",
        "schema": CACHE_SCHEMA,
        "entries": {k: cache_entries[k] for k in sorted(cache_entries)},
    }
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def reset() -> None:
    """Forget the in-process tier, journal, and file-load state (tests,
    bench slope reps)."""
    global _LOADED_PATH, _SEQ
    with _LOCK:
        _CACHE.clear()
        _JOURNAL.clear()
        _LOADED_PATH = None
        _SEQ = 0


# -- decision journal (drained into FitReport.tuning by telemetry.report) --


def record_decision(*, kernel: str, key: str, source: str,
                    config: TuningConfig | None) -> dict:
    """Journal one tuner resolution. ``source`` is ``cache`` (hit),
    ``search`` (fresh winner), or ``default`` (miss → static knobs)."""
    decision = {
        "kernel": kernel,
        "key": key,
        "source": source,
        "cache_hit": source == "cache",
        "config": config.to_dict() if config is not None else None,
    }
    global _SEQ
    with _LOCK:
        _SEQ += 1
        _JOURNAL.append((_SEQ, decision))
        del _JOURNAL[:-MAX_JOURNAL_EVENTS]
    TIMELINE.record_instant("autotune.decision", kernel=kernel, source=source)
    return decision


def decision_seq() -> int:
    """Current journal watermark (``begin_fit`` captures this)."""
    with _LOCK:
        return _SEQ


def decisions_since(seq: int) -> list[dict]:
    """Decisions journaled after ``seq`` (``end_fit`` drains these)."""
    with _LOCK:
        return [dict(d) for s, d in _JOURNAL if s > seq]
