"""Mid-training checkpoint/resume for iterative estimators.

The reference has model persistence only — "no mid-training checkpointing —
training is a single two-phase job" (SURVEY.md §5). Its stretch family is
iterative (Lloyd sweeps over 50M rows, BASELINE.json config 5), where a
preempted job losing every completed iteration is real money on shared TPU
pods, so this framework makes training-state checkpointing a first-class
subsystem rather than inheriting the gap.

Design: a checkpoint is a step-numbered directory holding one ``.npz`` of
named arrays plus a ``state.json`` of scalars. Writes are atomic
(write to ``<dir>/.tmp-<step>``, fsync, ``os.replace``) so a preemption
mid-write can never corrupt the latest durable state — readers only ever see
fully-renamed step directories. Retention keeps the newest ``keep`` steps.
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path

import numpy as np

_STEP_PREFIX = "step-"


class TrainingCheckpointer:
    """Atomic step-numbered checkpoints of training state in one directory.

    >>> ckpt = TrainingCheckpointer(dir)
    >>> ckpt.save(3, {"centers": c}, {"cost": 1.5})
    >>> step, arrays, state = ckpt.latest()
    """

    def __init__(self, directory: str | Path, *, keep: int = 2):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.dir = Path(directory)
        self.keep = keep
        self.dir.mkdir(parents=True, exist_ok=True)

    def _step_dir(self, step: int) -> Path:
        return self.dir / f"{_STEP_PREFIX}{step:09d}"

    def steps(self) -> list[int]:
        out = []
        for p in self.dir.iterdir():
            if p.is_dir() and p.name.startswith(_STEP_PREFIX):
                try:
                    out.append(int(p.name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def save(self, step: int, arrays: dict[str, np.ndarray], state: dict | None = None) -> None:
        # sweep ALL stale staging dirs, not just this step's: a writer killed
        # mid-save (preemption, fault injection) leaves a .tmp-<other-step>
        # orphan that would otherwise accumulate forever
        if self.dir.is_dir():
            for stale in self.dir.iterdir():
                if stale.name.startswith(".tmp-"):
                    shutil.rmtree(stale, ignore_errors=True)
        tmp = self.dir / f".tmp-{step}"
        tmp.mkdir(parents=True)
        np.savez(tmp / "arrays.npz", **{k: np.asarray(v) for k, v in arrays.items()})
        (tmp / "state.json").write_text(json.dumps({"step": step, **(state or {})}))
        # fsync the files then atomically publish the directory
        for f in tmp.iterdir():
            fd = os.open(f, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        final = self._step_dir(step)
        if final.exists():
            shutil.rmtree(final)
        os.replace(tmp, final)
        # fsync the parent directory so the rename itself is durable across
        # power loss, not just the file contents
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._retain()

    def _retain(self) -> None:
        steps = self.steps()
        for s in steps[: -self.keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def load(self, step: int) -> tuple[dict[str, np.ndarray], dict]:
        d = self._step_dir(step)
        with np.load(d / "arrays.npz") as z:
            arrays = {k: z[k] for k in z.files}
        state = json.loads((d / "state.json").read_text())
        return arrays, state

    def latest(self) -> tuple[int, dict[str, np.ndarray], dict] | None:
        """Newest durable checkpoint, or None. Skips any step whose payload
        is unreadable (e.g. a stale dir from a different schema)."""
        for step in reversed(self.steps()):
            try:
                arrays, state = self.load(step)
            except Exception:
                continue
            return step, arrays, state
        return None
