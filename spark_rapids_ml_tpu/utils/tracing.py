"""Back-compat shim — tracing moved to :mod:`spark_rapids_ml_tpu.telemetry`.

``trace_range`` began here as the NVTX-range analog with a 53-line
wall-clock dict; it is now backed by the telemetry registry (thread-safe,
log-scale latency histograms, estimator labels, exception-safe
accounting). Import sites throughout the models/spark layers keep working
through this module; new code should import from
``spark_rapids_ml_tpu.telemetry`` directly.
"""

from __future__ import annotations

import logging

from spark_rapids_ml_tpu.telemetry import metrics, reset_metrics, trace_range

logger = logging.getLogger("spark_rapids_ml_tpu")

__all__ = ["trace_range", "metrics", "reset_metrics", "logger"]
