"""Deprecated: import from :mod:`spark_rapids_ml_tpu.telemetry` instead."""

from spark_rapids_ml_tpu.telemetry import metrics, reset_metrics, trace_range  # noqa: F401
