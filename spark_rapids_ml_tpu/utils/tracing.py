"""Tracing / profiling annotations — the NVTX-range analog.

The reference wraps its two training phases in NVTX ranges visible in Nsight
(``NvtxRange("compute cov", RED)`` / ``NvtxRange("cuSolver SVD", BLUE)``,
RapidsRowMatrix.scala:62,70). On TPU the equivalent surface is xprof /
TensorBoard: ``jax.profiler.TraceAnnotation`` marks host spans and
``jax.named_scope`` tags the traced HLO so the phases are findable in a
device profile. ``trace_range`` layers both, plus wall-clock accounting into
a process-local metrics registry (the observability the reference lacked).

The streamed-fit pipeline (``spark.ingest.stream_fold``) emits three spans
per fit: ``ingest.chunk`` (host-side pull + staging of one inbound chunk),
``fold.dispatch`` (device_put + async fold launch), and ``fold.wait`` (the
single terminal block on the carry). In a profile, ``fold.dispatch`` spans
landing inside device execution of the previous fold are the visible
signature of H2D/compute double buffering.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict

import jax

logger = logging.getLogger("spark_rapids_ml_tpu")

# name -> [total_seconds, call_count]
_METRICS: dict[str, list[float]] = defaultdict(lambda: [0.0, 0])


@contextlib.contextmanager
def trace_range(name: str):
    """Host+device trace span with wall-clock metrics accumulation."""
    start = time.perf_counter()
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield
    elapsed = time.perf_counter() - start
    m = _METRICS[name]
    m[0] += elapsed
    m[1] += 1
    logger.debug("trace %s: %.3fs", name, elapsed)


def metrics() -> dict[str, dict[str, float]]:
    """Snapshot of accumulated phase timings."""
    return {k: {"seconds": v[0], "count": v[1]} for k, v in _METRICS.items()}


def reset_metrics() -> None:
    _METRICS.clear()
