"""Runtime utilities: columnar ingestion, persistence, tracing."""
