"""Columnar data ingestion — the TPU build's ColumnarRdd/ArrayType analog.

The reference gets device-resident columnar input for free from the
spark-rapids plugin: ``ColumnarRdd(df)`` yields cudf Tables on GPU
(RapidsRowMatrix.scala:23,118), and its public API takes an **ArrayType**
column rather than Spark ``Vector`` (README.md:35-37). That columnar engine is
CUDA-only, so this module owns the equivalent data path for TPU:

- accept "ArrayType-column"-shaped data from the containers available here
  (pyarrow Tables/RecordBatches with list columns, pandas DataFrames with
  object columns of arrays, plain ndarrays),
- extract a contiguous row-major [rows, n] block with zero copies whenever
  the Arrow layout allows it (fixed-size-list / list with uniform lengths,
  no nulls),
- bucket-pad row counts so variable-sized partitions map onto a small set of
  static XLA program shapes (TPU: compile once per bucket, not per batch).

``PartitionedDataset`` is the RDD stand-in: an ordered list of columnar
partitions with map/collect helpers, so estimators express "per-partition
kernel + cross-partition reduce" exactly like the reference's
``ColumnarRdd(df).map{...}.reduce(...)`` without depending on Spark.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Iterator, Sequence

import numpy as np

try:  # pyarrow is present in the image, but keep the core importable without it
    import pyarrow as pa
except Exception:  # pragma: no cover
    pa = None


# ---------------------------------------------------------------------------
# Column extraction
# ---------------------------------------------------------------------------


def _from_arrow_column(col) -> np.ndarray:
    """Arrow list/fixed_size_list column → [rows, n] ndarray, zero-copy when
    the child values buffer is contiguous and null-free."""
    if isinstance(col, pa.ChunkedArray):
        if col.num_chunks == 1:
            return _from_arrow_column(col.chunk(0))
        return np.concatenate([_from_arrow_column(c) for c in col.chunks])
    if pa.types.is_fixed_size_list(col.type):
        n = col.type.list_size
        if col.null_count:
            raise ValueError("null rows are not supported in the input column")
        values = col.values.to_numpy(zero_copy_only=False)
        return values.reshape(-1, n)[col.offset : col.offset + len(col)]
    if pa.types.is_list(col.type) or pa.types.is_large_list(col.type):
        if col.null_count:
            raise ValueError("null rows are not supported in the input column")
        offsets = col.offsets.to_numpy(zero_copy_only=False)
        lengths = np.diff(offsets)
        if len(lengths) == 0:
            raise ValueError("empty input column")
        n = int(lengths[0])
        if not np.all(lengths == n):
            raise ValueError("ragged rows: all rows must have equal length")
        values = col.values.to_numpy(zero_copy_only=False)
        return values[offsets[0] : offsets[-1]].reshape(-1, n)
    raise TypeError(f"unsupported Arrow column type for ArrayType input: {col.type}")


def extract_matrix(data: Any, input_col: str | None = None) -> np.ndarray:
    """Extract a row-major [rows, n] float matrix from any supported container.

    Supported: 2-D ndarray / JAX array; pyarrow Table/RecordBatch (list or
    fixed-size-list column named ``input_col``); pandas DataFrame whose
    ``input_col`` holds per-row arrays/lists (the ArrayType shape); and
    sequences of per-row arrays.
    """
    if pa is not None and isinstance(data, (pa.Table, pa.RecordBatch)):
        if input_col is None:
            raise ValueError("input_col is required for Arrow tables")
        return _from_arrow_column(data.column(input_col))
    # pandas without importing it eagerly
    if hasattr(data, "columns") and hasattr(data, "__getitem__") and input_col is not None:
        try:
            series = data[input_col]
        except Exception:
            series = None
        if series is not None and hasattr(series, "to_numpy"):
            rows = series.to_numpy()
            return np.stack([np.asarray(r) for r in rows])
    arr = np.asarray(data)
    if arr.ndim == 2:
        return arr
    if arr.ndim == 1 and arr.dtype == object:
        return np.stack([np.asarray(r) for r in arr])
    raise TypeError(
        f"cannot extract a [rows, n] matrix from {type(data).__name__}"
        + (f" column {input_col!r}" if input_col else "")
    )


def matrix_to_arrow_column(x: np.ndarray):
    """[rows, k] ndarray → Arrow FixedSizeList column (zero-copy values).

    The transform output stays an "ArrayType" column like the reference's
    (RapidsPCA.scala:98-104 builds a cudf LIST column the same way).
    """
    rows, k = x.shape
    values = pa.array(np.ascontiguousarray(x).reshape(-1))
    return pa.FixedSizeListArray.from_arrays(values, k)


def apply_column_transform(dataset: Any, input_col: str | None, output_col: str, fn):
    """Apply a matrix→matrix (or matrix→vector) transform to the input column
    and append the result as ``output_col``, preserving the container type.

    ``fn`` receives a [rows, n] ndarray and returns a [rows, k] ndarray (an
    ArrayType-shaped output column, like the reference's transform —
    RapidsPCA.scala:165) or a [rows] vector (a scalar column, e.g. KMeans
    predictions).
    """
    if pa is not None and isinstance(dataset, (pa.Table, pa.RecordBatch)):
        mat = extract_matrix(dataset, input_col)
        out = np.asarray(fn(mat))
        col = pa.array(out) if out.ndim == 1 else matrix_to_arrow_column(out)
        if isinstance(dataset, pa.RecordBatch):
            dataset = pa.Table.from_batches([dataset])
        return dataset.append_column(output_col, col)
    if hasattr(dataset, "columns") and hasattr(dataset, "assign") and input_col:
        mat = extract_matrix(dataset, input_col)
        out = np.asarray(fn(mat))
        return dataset.assign(**{output_col: list(out) if out.ndim > 1 else out})
    if isinstance(dataset, PartitionedDataset):
        return PartitionedDataset(
            [np.asarray(fn(m)) for m in dataset.matrices()], dataset.input_col
        )
    return np.asarray(fn(extract_matrix(dataset, input_col)))


def extract_vector(data: Any, col: str) -> np.ndarray:
    """Extract a scalar column (labels) as a [rows] float vector."""
    if pa is not None and isinstance(data, (pa.Table, pa.RecordBatch)):
        return np.asarray(data.column(col).to_numpy(zero_copy_only=False), dtype=np.float64)
    if hasattr(data, "columns") and hasattr(data, "__getitem__"):
        series = data[col]
        if hasattr(series, "to_numpy"):
            return np.asarray(series.to_numpy(), dtype=np.float64)
    raise TypeError(f"cannot extract label column {col!r} from {type(data).__name__}")


def labeled_partitions(
    data: Any,
    features_col: str | None,
    label_col: str | None,
    num_partitions: int | None = None,
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Split supervised data into [(X [rows, n], y [rows]), ...] partitions.

    Supported: an (X, y) tuple of arrays, or a table-like container (pandas /
    Arrow) holding an ArrayType features column and a scalar label column —
    the Spark ML ``featuresCol``/``labelCol`` input contract.
    """
    if isinstance(data, tuple) and len(data) == 2:
        x, y = np.asarray(data[0]), np.asarray(data[1], dtype=np.float64)
    else:
        x = extract_matrix(data, features_col)
        y = extract_vector(data, label_col)
    if len(x) != len(y):
        raise ValueError(f"features have {len(x)} rows but labels have {len(y)}")
    if num_partitions and num_partitions > 1:
        return list(
            zip(np.array_split(x, num_partitions), np.array_split(y, num_partitions))
        )
    return [(x, y)]


def pad_labeled(
    x: np.ndarray, y: np.ndarray, *, min_bucket: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Bucket-pad an (X, y) pair; returns (padded_x, padded_y, weights) with
    zero weights marking padded rows."""
    padded, true_rows = pad_rows(x, min_bucket=min_bucket)
    yp = np.zeros(padded.shape[0], dtype=padded.dtype)
    yp[:true_rows] = y
    w = np.zeros(padded.shape[0], dtype=padded.dtype)
    w[:true_rows] = 1.0
    return padded, yp, w


# ---------------------------------------------------------------------------
# Shape bucketing
# ---------------------------------------------------------------------------


def bucket_rows(rows: int, *, min_bucket: int | None = None) -> int:
    """Round a row count up to the next power-of-two bucket.

    XLA compiles one program per distinct shape; padding partitions to
    power-of-two buckets bounds the number of compilations at log₂(max/min)
    while wasting <2x FLOPs worst case. Zero-padding is exact for every
    reduction we run (Gram, column sums, scaler moments): padded rows
    contribute zero, and true counts ride in ``GramStats.count``.
    The bucket floor comes from the runtime config (TPU_ML_MIN_BUCKET).
    """
    if min_bucket is None:
        from spark_rapids_ml_tpu.utils.config import get_config

        min_bucket = get_config().min_bucket
    return max(min_bucket, 1 << math.ceil(math.log2(max(rows, 1))))


def pad_rows(x: np.ndarray, *, min_bucket: int | None = None) -> tuple[np.ndarray, int]:
    """Zero-pad [rows, n] to its row bucket; returns (padded, true_rows)."""
    rows = x.shape[0]
    bucket = bucket_rows(rows, min_bucket=min_bucket)
    if bucket == rows:
        return x, rows
    out = np.zeros((bucket, x.shape[1]), dtype=x.dtype)
    out[:rows] = x
    return out, rows


# ---------------------------------------------------------------------------
# Partitioned dataset (RDD stand-in)
# ---------------------------------------------------------------------------


@dataclass
class PartitionedDataset:
    """An ordered collection of columnar partitions with an input column.

    The minimal RDD-shaped surface the estimators need: per-partition map and
    an ordered collect. Reduction strategy is owned by ``parallel`` (host
    tree-aggregate or mesh psum), not by the dataset.
    """

    partitions: list[Any]
    input_col: str | None = None

    @staticmethod
    def from_any(
        data: Any, input_col: str | None = None, num_partitions: int | None = None
    ) -> "PartitionedDataset":
        """Wrap any supported container; optionally re-split into
        ``num_partitions`` row slices (the test harness's analog of
        ``sc.parallelize(data, 2)`` in PCASuite.scala:55-56)."""
        if isinstance(data, PartitionedDataset):
            return data
        if isinstance(data, (list, tuple)) and data and (
            pa is not None and isinstance(data[0], (pa.Table, pa.RecordBatch))
        ):
            return PartitionedDataset(list(data), input_col)
        x = extract_matrix(data, input_col)
        if num_partitions and num_partitions > 1:
            splits = np.array_split(x, num_partitions)
        else:
            splits = [x]
        return PartitionedDataset(splits, input_col)

    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    def matrices(self) -> Iterator[np.ndarray]:
        for p in self.partitions:
            yield extract_matrix(p, self.input_col)

    def map_matrices(self, fn: Callable[[np.ndarray], Any]) -> list[Any]:
        return [fn(m) for m in self.matrices()]

    def collect_matrix(self) -> np.ndarray:
        mats = list(self.matrices())
        return mats[0] if len(mats) == 1 else np.concatenate(mats)
