"""Columnar data ingestion — the TPU build's ColumnarRdd/ArrayType analog.

The reference gets device-resident columnar input for free from the
spark-rapids plugin: ``ColumnarRdd(df)`` yields cudf Tables on GPU
(RapidsRowMatrix.scala:23,118), and its public API takes an **ArrayType**
column rather than Spark ``Vector`` (README.md:35-37). That columnar engine is
CUDA-only, so this module owns the equivalent data path for TPU:

- accept "ArrayType-column"-shaped data from the containers available here
  (pyarrow Tables/RecordBatches with list columns, pandas DataFrames with
  object columns of arrays, plain ndarrays),
- extract a contiguous row-major [rows, n] block with zero copies whenever
  the Arrow layout allows it (fixed-size-list / list with uniform lengths,
  no nulls),
- bucket-pad row counts so variable-sized partitions map onto a small set of
  static XLA program shapes (TPU: compile once per bucket, not per batch).

``PartitionedDataset`` is the RDD stand-in: an ordered list of columnar
partitions with map/collect helpers, so estimators express "per-partition
kernel + cross-partition reduce" exactly like the reference's
``ColumnarRdd(df).map{...}.reduce(...)`` without depending on Spark.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from spark_rapids_ml_tpu.telemetry.registry import REGISTRY

try:  # pyarrow is present in the image, but keep the core importable without it
    import pyarrow as pa
except Exception:  # pragma: no cover
    pa = None


# ---------------------------------------------------------------------------
# Column extraction
# ---------------------------------------------------------------------------


# pyspark.ml VectorUDT's Arrow/sql layout: struct<type:tinyint, size:int,
# indices:array<int>, values:array<double>> with type 0=sparse, 1=dense
# (pyspark/ml/linalg/__init__.py VectorUDT.sqlType). Accepting it makes the
# "change one import" story real for existing pyspark.ml pipelines, which
# carry Vector columns — the reference documents ArrayType as its one
# deviation (README.md:35-37); here both work.
_VECTOR_UDT_FIELDS = ("type", "size", "indices", "values")


def _is_vector_udt_struct(typ) -> bool:
    if not pa.types.is_struct(typ):
        return False
    names = {typ.field(i).name for i in range(typ.num_fields)}
    return names.issuperset(_VECTOR_UDT_FIELDS)


def _from_vector_struct_column(col) -> np.ndarray:
    """VectorUDT struct column → dense [rows, n]; dense rows reshape in one
    step, sparse rows scatter by their indices."""
    if col.null_count:
        raise ValueError("null rows are not supported in the input column")
    fields = {
        col.type.field(i).name: flat
        for i, flat in enumerate(col.flatten())
    }
    tcode = np.asarray(fields["type"].to_numpy(zero_copy_only=False))
    values = fields["values"]
    val_np = np.asarray(values.values.to_numpy(zero_copy_only=False))
    offsets = np.asarray(values.offsets.to_numpy(zero_copy_only=False))
    lengths = np.diff(offsets)
    if np.all(tcode == 1):  # all dense: uniform-length list → one reshape
        n = int(lengths[0]) if len(lengths) else 0
        if not np.all(lengths == n):
            raise ValueError("ragged rows: all rows must have equal length")
        return val_np[offsets[0] : offsets[-1]].reshape(-1, n)
    sizes = np.asarray(
        fields["size"].to_numpy(zero_copy_only=False), dtype=np.float64
    )
    dims = np.where(tcode == 1, lengths, sizes)
    n = int(dims[0]) if len(dims) else 0
    if not np.all(dims == n):
        raise ValueError("ragged rows: all rows must have equal length")
    indices = fields["indices"]
    idx_np = np.asarray(indices.values.to_numpy(zero_copy_only=False))
    idx_offsets = np.asarray(indices.offsets.to_numpy(zero_copy_only=False))
    rows = len(tcode)
    out = np.zeros((rows, n), dtype=np.float64)
    dense = tcode == 1
    # fully vectorized, no per-row Python loop (executor hot path): the flat
    # values buffer concatenates every row's list, so one repeat-mask splits
    # dense from sparse values; the indices buffer holds ONLY sparse rows'
    # entries (dense rows' lists are null → zero length), so it is already
    # the flat column-id vector and its per-row lengths give the row ids.
    flat_vals = val_np[offsets[0] : offsets[-1]]
    sparse_mask = np.repeat(~dense, lengths)
    if dense.any():
        out[dense] = flat_vals[~sparse_mask].reshape(-1, n)
    if (~dense).any():
        col_ids = idx_np[idx_offsets[0] : idx_offsets[-1]]
        row_ids = np.repeat(np.arange(rows), np.diff(idx_offsets))
        out[row_ids, col_ids] = flat_vals[sparse_mask]
    return out


def row_vector_to_ndarray(value: Any) -> np.ndarray:
    """One driver-side row value of a features column → [n] ndarray.

    Handles the three shapes a collected row can carry: a plain
    list/ndarray (ArrayType), a pyspark.ml Vector (``toArray``), or the
    VectorUDT struct as a mapping (localspark / raw Arrow collect)."""
    if hasattr(value, "toArray"):  # pyspark.ml DenseVector / SparseVector
        return np.asarray(value.toArray(), dtype=np.float64)
    if isinstance(value, dict) and set(value).issuperset(_VECTOR_UDT_FIELDS):
        from spark_rapids_ml_tpu.utils.persistence import struct_to_vector

        return struct_to_vector(value)
    return np.asarray(value, dtype=np.float64)


def feature_dim(value: Any) -> int:
    """Feature count of one driver-side row value (``_infer_n``'s helper) —
    without densifying a sparse vector."""
    if hasattr(value, "size") and not isinstance(value, (list, tuple, np.ndarray)):
        return int(value.size)  # pyspark.ml Vector
    if isinstance(value, dict) and set(value).issuperset(_VECTOR_UDT_FIELDS):
        return (
            len(value["values"]) if value["type"] == 1 else int(value["size"])
        )
    return len(value)


def _from_arrow_column(col) -> np.ndarray:
    """Arrow list/fixed_size_list column → [rows, n] ndarray, zero-copy when
    the child values buffer is contiguous and null-free."""
    if isinstance(col, pa.ChunkedArray):
        if col.num_chunks == 1:
            return _from_arrow_column(col.chunk(0))
        return np.concatenate([_from_arrow_column(c) for c in col.chunks])
    if isinstance(col, pa.ExtensionArray):
        # Arrow ships UDTs as extension arrays over their storage type;
        # VectorUDT's storage is the struct handled below
        return _from_arrow_column(col.storage)
    if _is_vector_udt_struct(col.type):
        return _from_vector_struct_column(col)
    if pa.types.is_fixed_size_list(col.type):
        n = col.type.list_size
        if col.null_count:
            raise ValueError("null rows are not supported in the input column")
        values = col.values.to_numpy(zero_copy_only=False)
        return values.reshape(-1, n)[col.offset : col.offset + len(col)]
    if pa.types.is_list(col.type) or pa.types.is_large_list(col.type):
        if col.null_count:
            raise ValueError("null rows are not supported in the input column")
        offsets = col.offsets.to_numpy(zero_copy_only=False)
        lengths = np.diff(offsets)
        if len(lengths) == 0:
            raise ValueError("empty input column")
        n = int(lengths[0])
        if not np.all(lengths == n):
            raise ValueError("ragged rows: all rows must have equal length")
        values = col.values.to_numpy(zero_copy_only=False)
        return values[offsets[0] : offsets[-1]].reshape(-1, n)
    raise TypeError(f"unsupported Arrow column type for ArrayType input: {col.type}")


def is_spark_dataframe(obj: Any) -> bool:
    """True for a pyspark DataFrame or a localspark one — the ONE module-
    prefix check every layer (estimators, tuning) shares."""
    mod = type(obj).__module__ or ""
    return mod.startswith("pyspark.") or mod.startswith(
        "spark_rapids_ml_tpu.localspark"
    )


def extract_matrix(data: Any, input_col: str | None = None) -> np.ndarray:
    """Extract a row-major [rows, n] float matrix from any supported container.

    Supported: 2-D ndarray / JAX array; pyarrow Table/RecordBatch (list or
    fixed-size-list column named ``input_col``); pandas DataFrame whose
    ``input_col`` holds per-row arrays/lists (the ArrayType shape); and
    sequences of per-row arrays.

    This is the Arrow-collect measuring point: every extraction books its
    rows/bytes into the telemetry registry (``columnar.rows`` /
    ``columnar.bytes``), so in-core fits report throughput the same way
    streamed ones do.
    """
    out = _extract_matrix(data, input_col)
    REGISTRY.counter_inc("columnar.rows", out.shape[0])
    REGISTRY.counter_inc(
        "columnar.bytes", getattr(out, "nbytes", out.size * 8)
    )
    return out


def _extract_matrix(data: Any, input_col: str | None) -> np.ndarray:
    if pa is not None and isinstance(data, (pa.Table, pa.RecordBatch)):
        if input_col is None:
            raise ValueError("input_col is required for Arrow tables")
        return _from_arrow_column(data.column(input_col))
    # pandas without importing it eagerly
    if hasattr(data, "columns") and hasattr(data, "__getitem__") and input_col is not None:
        try:
            series = data[input_col]
        except Exception:
            series = None
        if series is not None and hasattr(series, "to_numpy"):
            rows = series.to_numpy()
            return np.stack([np.asarray(r) for r in rows])
    arr = np.asarray(data)
    if arr.ndim == 2:
        return arr
    if arr.ndim == 1 and arr.dtype == object:
        return np.stack([np.asarray(r) for r in arr])
    raise TypeError(
        f"cannot extract a [rows, n] matrix from {type(data).__name__}"
        + (f" column {input_col!r}" if input_col else "")
    )


def matrix_to_arrow_column(x: np.ndarray):
    """[rows, k] ndarray → Arrow FixedSizeList column (zero-copy values).

    The transform output stays an "ArrayType" column like the reference's
    (RapidsPCA.scala:98-104 builds a cudf LIST column the same way).
    """
    rows, k = x.shape
    values = pa.array(np.ascontiguousarray(x).reshape(-1))
    return pa.FixedSizeListArray.from_arrays(values, k)


def apply_column_transform(dataset: Any, input_col: str | None, output_col: str, fn):
    """Apply a matrix→matrix (or matrix→vector) transform to the input column
    and append the result as ``output_col``, preserving the container type.

    ``fn`` receives a [rows, n] ndarray and returns a [rows, k] ndarray (an
    ArrayType-shaped output column, like the reference's transform —
    RapidsPCA.scala:165) or a [rows] vector (a scalar column, e.g. KMeans
    predictions).
    """
    if pa is not None and isinstance(dataset, (pa.Table, pa.RecordBatch)):
        mat = extract_matrix(dataset, input_col)
        out = np.asarray(fn(mat))
        col = pa.array(out) if out.ndim == 1 else matrix_to_arrow_column(out)
        if isinstance(dataset, pa.RecordBatch):
            dataset = pa.Table.from_batches([dataset])
        return dataset.append_column(output_col, col)
    if hasattr(dataset, "columns") and hasattr(dataset, "assign") and input_col:
        mat = extract_matrix(dataset, input_col)
        out = np.asarray(fn(mat))
        return dataset.assign(**{output_col: list(out) if out.ndim > 1 else out})
    if isinstance(dataset, PartitionedDataset):
        return PartitionedDataset(
            [np.asarray(fn(m)) for m in dataset.matrices()], dataset.input_col
        )
    return np.asarray(fn(extract_matrix(dataset, input_col)))


def append_columns(dataset: Any, columns) -> Any:
    """Append precomputed output columns ([(name, ndarray)], 1-D scalar or
    2-D array-valued) to a column-bearing container, preserving its type —
    the multi-output sibling of ``apply_column_transform``."""
    if pa is not None and isinstance(dataset, (pa.Table, pa.RecordBatch)):
        if isinstance(dataset, pa.RecordBatch):
            dataset = pa.Table.from_batches([dataset])
        for name, out in columns:
            out = np.asarray(out)
            col = pa.array(out) if out.ndim == 1 else matrix_to_arrow_column(out)
            dataset = dataset.append_column(name, col)
        return dataset
    if hasattr(dataset, "columns") and hasattr(dataset, "assign"):
        return dataset.assign(
            **{
                name: (list(np.asarray(out)) if np.asarray(out).ndim > 1 else np.asarray(out))
                for name, out in columns
            }
        )
    raise TypeError(
        f"cannot append named columns to {type(dataset).__name__}"
    )


def has_named_columns(dataset: Any) -> bool:
    """True for containers whose transform output carries named columns
    (arrow tables/batches, pandas and pandas-likes) — the inputs where
    appending more than one output column is meaningful."""
    if pa is not None and isinstance(dataset, (pa.Table, pa.RecordBatch)):
        return True
    return hasattr(dataset, "columns") and hasattr(dataset, "assign")


def extract_column_values(dataset: Any, col: str) -> np.ndarray:
    """A column as a 1-D string/float array, or a 2-D float matrix for
    array-valued columns — numeric shapes ride the zero-copy extractors;
    only genuinely-string columns take the Python-object path. Shared by
    the feature-engineering and text stages."""
    if pa is not None and isinstance(dataset, (pa.Table, pa.RecordBatch)):
        typ = dataset.schema.field(col).type
        if pa.types.is_list(typ) or pa.types.is_fixed_size_list(typ):
            return extract_matrix(dataset, col)
        if pa.types.is_string(typ) or pa.types.is_large_string(typ):
            return np.asarray(dataset.column(col).to_pylist())
        return extract_vector(dataset, col)
    if hasattr(dataset, "columns") and hasattr(dataset, "__getitem__"):
        series = dataset[col]
        first = series.iloc[0] if len(series) else None
        if isinstance(first, (list, tuple, np.ndarray)):
            return extract_matrix(dataset, col)
        arr = (
            series.to_numpy()
            if hasattr(series, "to_numpy")
            else np.asarray(series)
        )
        if np.issubdtype(arr.dtype, np.number):
            return extract_vector(dataset, col)
        return arr
    raise TypeError(
        f"cannot extract column {col!r} from {type(dataset).__name__}"
    )


def extract_vector(data: Any, col: str) -> np.ndarray:
    """Extract a scalar column (labels) as a [rows] float vector."""
    if pa is not None and isinstance(data, (pa.Table, pa.RecordBatch)):
        return np.asarray(data.column(col).to_numpy(zero_copy_only=False), dtype=np.float64)
    if hasattr(data, "columns") and hasattr(data, "__getitem__"):
        series = data[col]
        if hasattr(series, "to_numpy"):
            return np.asarray(series.to_numpy(), dtype=np.float64)
    raise TypeError(f"cannot extract label column {col!r} from {type(data).__name__}")


def labeled_partitions(
    data: Any,
    features_col: str | None,
    label_col: str | None,
    num_partitions: int | None = None,
    weight_col: str | None = None,
) -> list[tuple[np.ndarray, np.ndarray, np.ndarray | None]]:
    """Split supervised data into [(X, y, w-or-None), ...] partitions.

    Supported: an (X, y) or (X, y, w) tuple of arrays, or a table-like
    container (pandas / Arrow) holding an ArrayType features column, a
    scalar label column, and optionally a scalar ``weight_col`` — the Spark
    ML ``featuresCol``/``labelCol``/``weightCol`` input contract. Instance
    weights must be non-negative.
    """
    w = None
    if isinstance(data, tuple) and len(data) in (2, 3):
        x, y = np.asarray(data[0]), np.asarray(data[1], dtype=np.float64)
        if len(data) == 3 and data[2] is not None:
            w = data[2]
    else:
        x = extract_matrix(data, features_col)
        y = extract_vector(data, label_col)
        if weight_col:
            w = extract_vector(data, weight_col)
    if len(x) != len(y):
        raise ValueError(f"features have {len(x)} rows but labels have {len(y)}")
    if w is not None:
        w = validate_weights(w, len(x))
    n_split = num_partitions if num_partitions and num_partitions > 1 else 1
    xs = np.array_split(x, n_split)
    ys = np.array_split(y, n_split)
    ws = np.array_split(w, n_split) if w is not None else [None] * n_split
    return list(zip(xs, ys, ws))


def float_dtype_for(dtype) -> np.dtype:
    """The dtype side-vectors (labels, weights) should use for a feature
    matrix: the matrix's own dtype when floating, else f64 — assigning
    fractional values into an integer-dtype buffer would silently floor
    them."""
    return dtype if np.issubdtype(dtype, np.floating) else np.dtype(np.float64)


def validate_weights(
    w: Any, n_rows: int | None = None, *, allow_all_zero: bool = False
) -> np.ndarray:
    """Spark weightCol contract checks, enforced in ONE place: 1-D,
    length-matched, non-negative, not all zero."""
    w = np.asarray(w, dtype=np.float64).reshape(-1)
    if n_rows is not None and len(w) != n_rows:
        raise ValueError(f"dataset has {n_rows} rows but weights have {len(w)}")
    if (w < 0).any():
        raise ValueError("instance weights must be non-negative")
    if not allow_all_zero and not (w > 0).any():
        raise ValueError("all instance weights are zero")
    return w


def resolve_partition_weights(
    dataset: Any,
    mats: list[np.ndarray],
    weight_col: str | None = None,
    sample_weight: Any | None = None,
) -> list[np.ndarray] | None:
    """Resolve instance weights into per-partition slices aligned with
    ``mats`` (the materialized partition matrices, in order), or None when
    the fit is unweighted.

    Sources, in precedence order: the ``sample_weight`` array argument
    (sklearn-style), then ``weight_col`` extracted from the container —
    whole-container extraction, falling back to per-partition extraction for
    pre-partitioned table lists.
    """
    if sample_weight is None and not weight_col:
        return None
    total_rows = sum(len(m) for m in mats)
    if sample_weight is not None:
        sw = validate_weights(sample_weight, total_rows)
    else:
        try:
            sw = extract_vector(dataset, weight_col)
        except TypeError:
            if isinstance(dataset, PartitionedDataset):
                slices = [
                    validate_weights(
                        extract_vector(p, weight_col), len(m), allow_all_zero=True
                    )
                    for p, m in zip(dataset.partitions, mats)
                ]
                if not any((s > 0).any() for s in slices):
                    raise ValueError("all instance weights are zero")
                return slices
            raise
        sw = validate_weights(sw, total_rows)
    out, off = [], 0
    for m in mats:
        out.append(sw[off : off + len(m)])
        off += len(m)
    return out


def standardize_host(
    mat: np.ndarray, mean: np.ndarray | None, std: np.ndarray | None
) -> np.ndarray:
    """(x − μ)/σ on host rows with StandardScaler's zero-variance rule
    (σ=0 features pass through unscaled) — the ONE implementation every
    standardize-fit transform path shares (model local path, row fallback,
    and the worker-side Arrow transform). No-op when mean is None."""
    if mean is None:
        return mat
    safe = np.where(std > 0, std, 1.0)
    return (mat - mean[None, :].astype(mat.dtype)) / safe[None, :].astype(
        mat.dtype
    )


def pad_labeled(
    x: np.ndarray,
    y: np.ndarray,
    weights: np.ndarray | None = None,
    *,
    min_bucket: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Bucket-pad an (X, y[, w]) group; returns (padded_x, padded_y, w) where
    the weight vector is zero on padded rows and carries the instance
    weights (1.0 when none were given) on true rows — so the padding mask
    and Spark-style instance weighting ride one vector through the kernels."""
    padded, true_rows = pad_rows(x, min_bucket=min_bucket)
    dtype = float_dtype_for(padded.dtype)
    yp = np.zeros(padded.shape[0], dtype=dtype)
    yp[:true_rows] = y
    w = np.zeros(padded.shape[0], dtype=dtype)
    w[:true_rows] = 1.0 if weights is None else weights
    return padded, yp, w


# ---------------------------------------------------------------------------
# Shape bucketing
# ---------------------------------------------------------------------------


def pad_labeled_batch(x, y, w=None):
    """(padded_x, yv, wv, true_rows): the full-batch trainer marshalling —
    row-bucketed X with a label vector and a pad-masking weight vector
    (instance weights on true rows, 0.0 on padding). Shared by every
    optimizer that trains on one concatenated batch (MLP, FM, ...)."""
    fdt = float_dtype_for(x.dtype)
    padded, true_rows = pad_rows(np.asarray(x).astype(fdt, copy=False))
    wv = np.zeros(padded.shape[0], fdt)
    wv[:true_rows] = 1.0 if w is None else w
    yv = np.zeros(padded.shape[0], fdt)
    yv[:true_rows] = y
    return padded, yv, wv, true_rows


def bucket_rows(rows: int, *, min_bucket: int | None = None) -> int:
    """Round a row count up to the next power-of-two bucket.

    XLA compiles one program per distinct shape; padding partitions to
    power-of-two buckets bounds the number of compilations at log₂(max/min)
    while wasting <2x FLOPs worst case. Zero-padding is exact for every
    reduction we run (Gram, column sums, scaler moments): padded rows
    contribute zero, and true counts ride in ``GramStats.count``.
    The bucket floor comes from the runtime config (TPU_ML_MIN_BUCKET).
    """
    if min_bucket is None:
        from spark_rapids_ml_tpu.utils.config import get_config

        min_bucket = get_config().min_bucket
    return max(min_bucket, 1 << math.ceil(math.log2(max(rows, 1))))


def pad_rows(x: np.ndarray, *, min_bucket: int | None = None) -> tuple[np.ndarray, int]:
    """Zero-pad [rows, n] to its row bucket; returns (padded, true_rows)."""
    rows = x.shape[0]
    bucket = bucket_rows(rows, min_bucket=min_bucket)
    if bucket == rows:
        return x, rows
    out = np.zeros((bucket, x.shape[1]), dtype=x.dtype)
    out[:rows] = x
    return out, rows


# ---------------------------------------------------------------------------
# Partitioned dataset (RDD stand-in)
# ---------------------------------------------------------------------------


@dataclass
class PartitionedDataset:
    """An ordered collection of columnar partitions with an input column.

    The minimal RDD-shaped surface the estimators need: per-partition map and
    an ordered collect. Reduction strategy is owned by ``parallel`` (host
    tree-aggregate or mesh psum), not by the dataset.
    """

    partitions: list[Any]
    input_col: str | None = None

    @staticmethod
    def from_any(
        data: Any, input_col: str | None = None, num_partitions: int | None = None
    ) -> "PartitionedDataset":
        """Wrap any supported container; optionally re-split into
        ``num_partitions`` row slices (the test harness's analog of
        ``sc.parallelize(data, 2)`` in PCASuite.scala:55-56)."""
        if isinstance(data, PartitionedDataset):
            return data
        if isinstance(data, (list, tuple)) and data and (
            pa is not None and isinstance(data[0], (pa.Table, pa.RecordBatch))
        ):
            return PartitionedDataset(list(data), input_col)
        # unbooked extraction: the telemetry rows/bytes counters fire when
        # partitions are consumed (matrices()), so wrapping must not count
        # the same rows a second time
        x = _extract_matrix(data, input_col)
        if num_partitions and num_partitions > 1:
            splits = np.array_split(x, num_partitions)
        else:
            splits = [x]
        return PartitionedDataset(splits, input_col)

    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    def est_rows(self) -> int | None:
        """Total row count from partition metadata alone — no matrix
        extraction, so the streamed-fit cutover can be decided without
        materializing anything. None when a partition's size isn't knowable
        cheaply (callers fall back to the resident path)."""
        total = 0
        for p in self.partitions:
            nr = getattr(p, "num_rows", None)
            if nr is None and isinstance(p, np.ndarray):
                nr = p.shape[0]
            if nr is None and isinstance(p, (list, tuple)):
                nr = len(p)
            if nr is None:
                return None
            total += int(nr)
        return total

    def est_feature_dim(self) -> int | None:
        """Feature dimension from the first partition's metadata (2-D
        ndarray partitions only — anything else returns None and the caller
        keeps the resident path)."""
        if not self.partitions:
            return None
        p = self.partitions[0]
        if isinstance(p, np.ndarray) and p.ndim == 2:
            return int(p.shape[1])
        return None

    def matrices(self) -> Iterator[np.ndarray]:
        for p in self.partitions:
            yield extract_matrix(p, self.input_col)

    def map_matrices(self, fn: Callable[[np.ndarray], Any]) -> list[Any]:
        return [fn(m) for m in self.matrices()]

    def collect_matrix(self) -> np.ndarray:
        mats = list(self.matrices())
        return mats[0] if len(mats) == 1 else np.concatenate(mats)


def use_streamed_fit(ds: PartitionedDataset) -> bool:
    """Streamed-fit cutover for core-model (non-Spark) fits: True when the
    partition metadata alone proves the resident array would exceed
    ``TPU_ML_STREAM_FIT_MAX_RESIDENT_BYTES``. Unknown sizes keep the
    resident path — streaming is an optimization, never a behavior gamble."""
    rows = ds.est_rows()
    n = ds.est_feature_dim()
    if rows is None or n is None:
        return False
    from spark_rapids_ml_tpu.spark.ingest import use_streamed_fit as _cutover

    return _cutover(rows, n)
