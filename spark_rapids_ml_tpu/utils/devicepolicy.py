"""Worker device-assignment policy: who owns the accelerator on a host.

The reference's executor topology gives every executor JVM its own GPU and a
one-singleton-per-process native loader (JniRAPIDSML.java:27-58) — device
ownership is decided by Spark's resource scheduling before any task code
runs. On TPU hosts the equivalent decision must be made *by us*, because a
JAX process claims its accelerator at interpreter start (a PJRT plugin
registered from `sitecustomize`/`.pth` hooks), **before** any framework code
executes. Two consequences this module owns:

1. ``JAX_PLATFORMS=cpu`` in a child's environment is NOT sufficient to keep
   it off the accelerator: a site-installed bootstrap can register and dial
   the device plugin at interpreter start regardless, and when another
   process (the driver) already holds the single chip the child blocks
   indefinitely waiting for a grant — an unbounded hang, observed in
   practice, not an error.
2. Therefore the policy is enforced in TWO places: the *parent* scrubs the
   known accelerator-bootstrap trigger variables from the child environment
   (so the plugin never registers), and the *child* runs a bounded-time
   device probe that fail-fasts with a diagnosable error if it still ended
   up on the wrong platform or cannot initialize at all.

Default policy — **one device owner per host**: the driver process owns the
accelerator; worker subprocesses run the JAX CPU backend. This matches the
single-chip topology of a TPU host where N Python workers cannot share the
chip the way N CUDA contexts share a GPU. Opt out by constructing
``LocalSparkSession(worker_platform=None)`` (workers inherit the parent
environment untouched — appropriate when each worker host has its own
accelerator, i.e. a real multi-host cluster).
"""

from __future__ import annotations

import os
from typing import Mapping

# Environment variables whose mere presence makes an interpreter-start hook
# register an accelerator PJRT plugin (and potentially dial/claim the
# device). Scrubbed from worker environments under the "cpu" policy.
# Extensible without a code change via TPU_ML_WORKER_SCRUB_VARS (comma-sep).
ACCELERATOR_BOOTSTRAP_VARS: tuple[str, ...] = (
    "PALLAS_AXON_POOL_IPS",   # axon PJRT bootstrap trigger
    "AXON_POOL_SVC_OVERRIDE",
    "AXON_LOOPBACK_RELAY",
    "TPU_WORKER_HOSTNAMES",
    "TPU_WORKER_ID",
    "TPU_VISIBLE_DEVICES",
)

# Env contract between the session (parent) and worker (child):
PLATFORM_VAR = "TPU_ML_WORKER_PLATFORM"          # expected jax platform name
PROBE_VAR = "TPU_ML_WORKER_PROBE"                # "1": probe at worker startup
PROBE_TIMEOUT_VAR = "TPU_ML_WORKER_PROBE_TIMEOUT"  # seconds, float
DEFAULT_PROBE_TIMEOUT = 60.0

# Exit code a worker uses for a failed device probe; distinguishable in the
# driver's WorkerException from a plan-function crash.
PROBE_EXIT_CODE = 17


def scrub_vars() -> tuple[str, ...]:
    extra = tuple(
        v.strip()
        for v in os.environ.get("TPU_ML_WORKER_SCRUB_VARS", "").split(",")
        if v.strip()
    )
    return ACCELERATOR_BOOTSTRAP_VARS + extra


def worker_env(platform: str | None = "cpu") -> dict[str, str | None]:
    """Environment overrides for a worker subprocess under ``platform``.

    A value of ``None`` means *remove the variable* from the inherited
    environment (the caller applies this — see LocalSparkSession._Worker).
    ``platform=None`` returns no overrides: the child inherits everything,
    including accelerator ownership.
    """
    if platform is None:
        return {}
    env: dict[str, str | None] = {v: None for v in scrub_vars()}
    env["JAX_PLATFORMS"] = platform
    env[PLATFORM_VAR] = platform
    # The startup probe initializes JAX inside the worker, which costs ~1s
    # and forecloses pre-init jax.config choices by plan functions — so it
    # is armed only where the risk it guards against exists: hosts whose
    # parent environment carries an accelerator bootstrap trigger. On clean
    # CPU hosts workers keep their cold-interpreter fidelity.
    if any(v in os.environ for v in scrub_vars()):
        env[PROBE_VAR] = "1"
    return env


def apply_overrides(
    base: Mapping[str, str], overrides: Mapping[str, str | None]
) -> dict[str, str]:
    """Merge ``overrides`` into a copy of ``base``; ``None`` deletes."""
    env = dict(base)
    for key, value in overrides.items():
        if value is None:
            env.pop(key, None)
        else:
            env[key] = value
    return env


class DevicePolicyError(RuntimeError):
    """The worker process could not honor its assigned device platform."""


def probe_platform(
    expected: str | None = None, timeout: float | None = None
) -> str:
    """Initialize JAX and verify the backend platform, in bounded time.

    Runs ``jax.devices()`` on a daemon thread and waits at most ``timeout``
    seconds. Three failure modes, all raising :class:`DevicePolicyError`
    (instead of the unbounded hang that motivates this module):

    - the probe does not complete in time (an interpreter-start plugin is
      blocking on a device grant another process holds);
    - JAX initialization raised;
    - the initialized platform differs from ``expected``.

    Returns the platform name on success. ``expected``/``timeout`` default
    from the TPU_ML_WORKER_* env contract.
    """
    import threading

    if expected is None:
        expected = os.environ.get(PLATFORM_VAR) or None
    if timeout is None:
        raw = os.environ.get(PROBE_TIMEOUT_VAR, str(DEFAULT_PROBE_TIMEOUT))
        try:
            timeout = float(raw)
        except ValueError as e:
            raise DevicePolicyError(
                f"{PROBE_TIMEOUT_VAR}={raw!r} is not a number of seconds"
            ) from e
    result: dict[str, str] = {}

    def _probe() -> None:
        try:
            import jax

            result["platform"] = jax.devices()[0].platform
        except BaseException as e:  # noqa: BLE001 - reported to the parent
            result["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_probe, name="tpu-ml-device-probe", daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise DevicePolicyError(
            f"device probe did not complete within {timeout}s: JAX backend "
            "initialization is blocked — most likely an accelerator plugin "
            "registered at interpreter start is waiting for a device another "
            "process owns. Scrub the bootstrap variables from the worker "
            f"environment (see devicepolicy.ACCELERATOR_BOOTSTRAP_VARS / "
            f"TPU_ML_WORKER_SCRUB_VARS) or raise {PROBE_TIMEOUT_VAR}."
        )
    if "error" in result:
        raise DevicePolicyError(
            f"JAX failed to initialize in the worker: {result['error']}"
        )
    platform = result.get("platform", "<unknown>")
    if expected is not None and platform != expected:
        raise DevicePolicyError(
            f"worker was assigned platform {expected!r} but JAX initialized "
            f"{platform!r}. Under the one-device-owner-per-host policy the "
            "driver owns the accelerator and workers must run on CPU; a "
            "site-level bootstrap overrode the worker's JAX_PLATFORMS. "
            "Remove the bootstrap trigger from the worker environment or run "
            "the session with worker_platform=None to hand workers the device."
        )
    return platform
