"""Worker device-assignment policy: who owns the accelerator on a host.

The reference's executor topology gives every executor JVM its own GPU and a
one-singleton-per-process native loader (JniRAPIDSML.java:27-58) — device
ownership is decided by Spark's resource scheduling before any task code
runs. On TPU hosts the equivalent decision must be made *by us*, because a
JAX process claims its accelerator at interpreter start (a PJRT plugin
registered from `sitecustomize`/`.pth` hooks), **before** any framework code
executes. Two consequences this module owns:

1. ``JAX_PLATFORMS=cpu`` in a child's environment is NOT sufficient to keep
   it off the accelerator: a site-installed bootstrap can register and dial
   the device plugin at interpreter start regardless, and when another
   process (the driver) already holds the single chip the child blocks
   indefinitely waiting for a grant — an unbounded hang, observed in
   practice, not an error.
2. Therefore the policy is enforced in TWO places: the *parent* scrubs the
   known accelerator-bootstrap trigger variables from the child environment
   (so the plugin never registers), and the *child* runs a bounded-time
   device probe that fail-fasts with a diagnosable error if it still ended
   up on the wrong platform or cannot initialize at all.

Default policy — **one device owner per host**: the driver process owns the
accelerator; worker subprocesses run the JAX CPU backend. This matches the
single-chip topology of a TPU host where N Python workers cannot share the
chip the way N CUDA contexts share a GPU. Opt out by constructing
``LocalSparkSession(worker_platform=None)`` (workers inherit the parent
environment untouched — appropriate when each worker host has its own
accelerator, i.e. a real multi-host cluster).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Mapping

from spark_rapids_ml_tpu.utils import knobs

# Environment variables whose mere presence makes an interpreter-start hook
# register an accelerator PJRT plugin (and potentially dial/claim the
# device). Scrubbed from worker environments under the "cpu" policy.
# Extensible without a code change via TPU_ML_WORKER_SCRUB_VARS (comma-sep).
ACCELERATOR_BOOTSTRAP_VARS: tuple[str, ...] = (
    "PALLAS_AXON_POOL_IPS",   # axon PJRT bootstrap trigger
    "AXON_POOL_SVC_OVERRIDE",
    "AXON_LOOPBACK_RELAY",
    "TPU_WORKER_HOSTNAMES",
    "TPU_WORKER_ID",
    "TPU_VISIBLE_DEVICES",
)

# Env contract between the session (parent) and worker (child):
PLATFORM_VAR = knobs.WORKER_PLATFORM.name        # expected jax platform name
PROBE_VAR = knobs.WORKER_PROBE.name              # "1": probe at worker startup
PROBE_TIMEOUT_VAR = knobs.WORKER_PROBE_TIMEOUT.name  # seconds, float
DEFAULT_PROBE_TIMEOUT = 60.0

# Exit code a worker uses for a failed device probe; distinguishable in the
# driver's WorkerException from a plan-function crash.
PROBE_EXIT_CODE = 17


def scrub_vars() -> tuple[str, ...]:
    extra = tuple(
        v.strip()
        for v in os.environ.get(knobs.WORKER_SCRUB_VARS.name, "").split(",")
        if v.strip()
    )
    return ACCELERATOR_BOOTSTRAP_VARS + extra


def worker_env(platform: str | None = "cpu") -> dict[str, str | None]:
    """Environment overrides for a worker subprocess under ``platform``.

    A value of ``None`` means *remove the variable* from the inherited
    environment (the caller applies this — see LocalSparkSession._Worker).
    ``platform=None`` returns no overrides: the child inherits everything,
    including accelerator ownership.
    """
    if platform is None:
        return {}
    env: dict[str, str | None] = {v: None for v in scrub_vars()}
    env["JAX_PLATFORMS"] = platform
    env[PLATFORM_VAR] = platform
    # The startup probe initializes JAX inside the worker, which costs ~1s
    # and forecloses pre-init jax.config choices by plan functions — so it
    # is armed only where the risk it guards against exists: hosts whose
    # parent environment carries an accelerator bootstrap trigger. On clean
    # CPU hosts workers keep their cold-interpreter fidelity.
    if any(v in os.environ for v in scrub_vars()):
        env[PROBE_VAR] = "1"
    return env


def apply_overrides(
    base: Mapping[str, str], overrides: Mapping[str, str | None]
) -> dict[str, str]:
    """Merge ``overrides`` into a copy of ``base``; ``None`` deletes."""
    env = dict(base)
    for key, value in overrides.items():
        if value is None:
            env.pop(key, None)
        else:
            env[key] = value
    return env


class DevicePolicyError(RuntimeError):
    """The worker process could not honor its assigned device platform."""


def use_platform(platform: str, *, probe_timeout: float | None = None) -> str:
    """Driver-side platform selection that actually wins, with a bounded
    first-touch probe.

    ``JAX_PLATFORMS=<p>`` in the environment is NOT a reliable way for the
    *driver* process to choose its backend: an interpreter-start bootstrap
    (sitecustomize/.pth) can call ``jax.config.update("jax_platforms", ...)``
    AFTER the env var was read, silently overriding it — observed in
    practice: a driver that asked for ``cpu`` still dialed the accelerator
    plugin at its first ``device_put`` and, when the device transport was
    unhealthy, hung indefinitely rather than erroring. This helper is the
    in-process counterpart of the worker-side scrub+probe:

    1. re-asserts ``jax.config.update("jax_platforms", platform)`` — an
       explicit late update wins over any interpreter-start hook;
    2. runs the bounded :func:`probe_platform` so a wedged transport
       surfaces as a diagnosable :class:`DevicePolicyError` within
       ``probe_timeout`` seconds instead of an unbounded hang;
    3. if a backend was ALREADY initialized on the wrong platform (the
       bootstrap dialed at interpreter start, or this is a late call) —
       where the config update alone is a no-op — it drops the stale
       backend set via ``jax.extend.backend.clear_backends`` and probes
       once more. Arrays created before the switch stay on their original
       client.

    Returns the platform of ``jax.devices()[0]``. For a comma fallback
    list ("axon,cpu") any entry may legitimately win and plugins may
    canonicalize device ``.platform`` differently, so only single-platform
    requests pin the probe's expected name.
    """
    import jax

    jax.config.update("jax_platforms", platform)
    expected = platform if "," not in platform else None
    try:
        return probe_platform(expected=expected, timeout=probe_timeout)
    except DevicePolicyError as first_err:
        try:
            from jax.extend.backend import clear_backends

            clear_backends()
        except Exception:  # noqa: BLE001 - keep the original diagnosis
            raise first_err from None
        return probe_platform(expected=expected, timeout=probe_timeout)


# Default sentinel for probe_platform's ``expected``: resolve from the
# TPU_ML_WORKER_PLATFORM env contract. Pass ``expected=None`` to accept
# whatever platform initializes (bounded-time init check only) — an env
# var must not be able to re-enable the check the caller opted out of.
FROM_ENV = object()


def probe_platform(
    expected: object = FROM_ENV, timeout: float | None = None
) -> str:
    """Initialize JAX and verify the backend platform, in bounded time.

    Runs ``jax.devices()`` on a daemon thread and waits at most ``timeout``
    seconds. Three failure modes, all raising :class:`DevicePolicyError`
    (instead of the unbounded hang that motivates this module):

    - the probe does not complete in time (an interpreter-start plugin is
      blocking on a device grant another process holds);
    - JAX initialization raised;
    - the initialized platform differs from ``expected``.

    Returns the platform name on success. ``expected`` defaults from the
    TPU_ML_WORKER_PLATFORM env var (:data:`FROM_ENV`); ``None`` means any
    platform is acceptable. ``timeout`` defaults from the env contract.
    """
    import threading

    if expected is FROM_ENV:
        expected = os.environ.get(PLATFORM_VAR) or None
    if timeout is None:
        raw = os.environ.get(PROBE_TIMEOUT_VAR, str(DEFAULT_PROBE_TIMEOUT))
        try:
            timeout = float(raw)
        except ValueError as e:
            raise DevicePolicyError(
                f"{PROBE_TIMEOUT_VAR}={raw!r} is not a number of seconds"
            ) from e
    result: dict[str, str] = {}

    def _probe() -> None:
        try:
            import jax

            result["platform"] = jax.devices()[0].platform
        except BaseException as e:  # noqa: BLE001 - reported to the parent
            result["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_probe, name="tpu-ml-device-probe", daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise DevicePolicyError(
            f"device probe did not complete within {timeout}s: JAX backend "
            "initialization is blocked — most likely an accelerator plugin "
            "registered at interpreter start is waiting on a device grant "
            "another process owns, or the device transport is unhealthy. "
            "In a worker process: scrub the bootstrap variables from its "
            "environment (devicepolicy.ACCELERATOR_BOOTSTRAP_VARS / "
            "TPU_ML_WORKER_SCRUB_VARS). In a driver process: check device "
            "health, or select a working platform via "
            "devicepolicy.use_platform(). To wait longer, pass a larger "
            f"timeout (workers: the {PROBE_TIMEOUT_VAR} env var)."
        )
    if "error" in result:
        raise DevicePolicyError(
            f"JAX failed to initialize in this process: {result['error']}"
        )
    platform = result.get("platform", "<unknown>")
    if expected is not None and platform != expected:
        _raise_platform_mismatch(expected, platform)
    return platform


def _raise_platform_mismatch(expected: object, platform: str) -> None:
    raise DevicePolicyError(
        f"this process was assigned platform {expected!r} but JAX "
        f"initialized {platform!r} — an interpreter-start bootstrap "
        "overrode the platform choice, or a backend was already "
        "initialized. In a worker under the one-device-owner-per-host "
        "policy: remove the bootstrap trigger from the worker "
        "environment, or run the session with worker_platform=None to "
        "hand workers the device. In a driver: select the platform via "
        "devicepolicy.use_platform(), which also swaps an "
        "already-initialized backend."
    )


# Self-bounded child program for subprocess probes: runs the daemon-thread
# probe and exits on its own (os._exit so a stuck atexit/daemon thread can
# never keep the child alive). The parent therefore never has to SIGKILL a
# probing child — important because hard-killing a process mid-device-
# handshake is exactly the failure mode that wedges the transport for every
# later process on this host.
_SUBPROBE_PROGRAM = """\
import os, sys
from spark_rapids_ml_tpu.utils import devicepolicy as _dp
try:
    p = _dp.probe_platform(expected=None, timeout=float(sys.argv[1]))
    sys.stdout.write(p)
    sys.stdout.flush()
    os._exit(0)
except BaseException as e:
    sys.stderr.write(f"{type(e).__name__}: {e}")
    sys.stderr.flush()
    os._exit(_dp.PROBE_EXIT_CODE)
"""


def probe_transport_subprocess(
    timeout: float = 120.0,
    env_overrides: Mapping[str, str | None] | None = None,
) -> tuple[bool, str]:
    """Probe device-transport health in a THROWAWAY child interpreter.

    An in-process :func:`probe_platform` that times out leaves a daemon
    thread permanently blocked inside backend initialization — the process
    is poisoned and cannot retry (a second ``jax.devices()`` joins the same
    stuck init). A subprocess probe is repeatable: each attempt gets a
    fresh interpreter, and a wedged attempt costs nothing but the child.

    Returns ``(ok, detail)`` where ``detail`` is the platform name on
    success or the child's diagnostic on failure. Never raises for probe
    failure — callers drive retry loops off the boolean.

    ``env_overrides`` shapes the child environment (``None`` values delete,
    :func:`apply_overrides` semantics) — e.g. ``worker_env("cpu")`` probes
    CPU-backend health without touching the accelerator at all; the default
    (no overrides) probes whatever platform the host's bootstrap selects,
    i.e. the accelerator transport itself.
    """
    env = apply_overrides(os.environ, env_overrides or {})
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SUBPROBE_PROGRAM, str(timeout)],
            env=env,
            capture_output=True,
            text=True,
            # grace over the child's own bound: import + thread-join slack.
            # The child self-terminates at `timeout`; this outer bound only
            # fires if the child's MAIN thread is stuck (not observed), and
            # uses SIGKILL only then.
            timeout=timeout + 60.0,
        )
    except subprocess.TimeoutExpired:
        return False, (
            f"probe child did not exit within {timeout + 60.0}s (its own "
            f"bound is {timeout}s) — child main thread stuck"
        )
    if proc.returncode == 0 and proc.stdout:
        return True, proc.stdout.strip()
    return False, (proc.stderr or f"probe child exited rc={proc.returncode}").strip()


def wait_for_transport(
    *,
    window: float = 3600.0,
    attempt_timeout: float = 120.0,
    backoff_start: float = 30.0,
    backoff_max: float = 300.0,
    log: Callable[[str], None] | None = None,
    probe: Callable[..., tuple[bool, str]] | None = None,
) -> str:
    """Wait (bounded) for the device transport to become healthy.

    Retries :func:`probe_transport_subprocess` with exponential backoff
    until one succeeds or ``window`` seconds elapse. Rationale: the
    transport on shared-accelerator hosts wedges *transiently* (observed:
    hours-long outages that clear on their own), and a benchmark snapshot
    should tolerate that rather than publish rc=1 with no numbers — the
    round-3 failure mode. Returns the platform name; raises
    :class:`DevicePolicyError` with the per-attempt log if the window
    expires.

    The backoff schedule comes from the shared
    ``resilience.retry.RetryPolicy`` (jitter disabled so the emitted plan
    stays human-predictable) and every sleep is counted as
    ``retry.attempts{site=transport}`` in telemetry.
    """
    from spark_rapids_ml_tpu.resilience.retry import RetryPolicy
    from spark_rapids_ml_tpu.telemetry.registry import REGISTRY

    emit = log or (lambda m: print(m, file=sys.stderr, flush=True))
    do_probe = probe or probe_transport_subprocess
    policy = RetryPolicy(
        max_attempts=1 << 30,  # bounded by the window, not a count
        backoff_s=backoff_start,
        multiplier=2.0,
        max_backoff_s=backoff_max,
        jitter=0.0,
        deadline_s=window,
    )
    deadline = time.monotonic() + window
    attempts: list[str] = []
    attempt = 0
    while True:
        attempt += 1
        start = time.monotonic()
        ok, detail = do_probe(timeout=attempt_timeout)
        took = time.monotonic() - start
        if ok:
            emit(
                f"[transport] attempt {attempt} ok in {took:.1f}s: "
                f"platform={detail}"
            )
            return detail
        attempts.append(f"attempt {attempt} ({took:.1f}s): {detail.splitlines()[0][:160]}")
        backoff = policy.sleep_s(attempt)
        remaining = deadline - time.monotonic()
        if remaining <= backoff:
            raise DevicePolicyError(
                f"device transport did not become healthy within "
                f"{window:.0f}s ({attempt} attempts):\n  "
                + "\n  ".join(attempts)
            )
        emit(
            f"[transport] attempt {attempt} failed ({took:.1f}s); retrying "
            f"in {backoff:.0f}s ({remaining:.0f}s left in window): "
            f"{detail.splitlines()[0][:160]}"
        )
        REGISTRY.counter_inc("retry.attempts", site="transport")
        time.sleep(backoff)
