"""Model persistence — params JSON + parquet data, reference layout.

The reference persists models as Spark ML does (RapidsPCA.scala:193-229):
``path/metadata`` holds a params JSON (class, uid, timestamp, param map) and
``path/data`` holds a 1-partition parquet of the model payload. We keep that
exact on-disk shape — ``metadata.json`` + ``data.parquet`` — with ndarray
payloads stored as flattened parquet columns plus shape metadata, so saved
models are inspectable with stock Arrow tooling.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

import numpy as np

try:
    import pyarrow as pa
    import pyarrow.parquet as pq
except Exception:  # pragma: no cover
    pa = None
    pq = None

_LIBRARY_VERSION_KEY = "libraryVersion"


def _jsonable(v: Any) -> Any:
    if isinstance(v, (np.integer, np.floating)):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def save_metadata(path: str | Path, instance, extra: dict | None = None) -> None:
    """DefaultParamsWriter.saveMetadata analog (RapidsPCA.scala:196)."""
    from spark_rapids_ml_tpu import __version__

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    state = instance._paramState()
    meta = {
        "class": f"{type(instance).__module__}.{type(instance).__qualname__}",
        "timestamp": int(time.time() * 1000),
        _LIBRARY_VERSION_KEY: __version__,
        "uid": instance.uid,
        "paramMap": {k: _jsonable(v) for k, v in state["paramMap"].items()},
        "defaultParamMap": {k: _jsonable(v) for k, v in state["defaultParamMap"].items()},
    }
    if extra:
        meta.update(extra)
    (path / "metadata.json").write_text(json.dumps(meta, indent=2))


def load_metadata(path: str | Path) -> dict:
    return json.loads((Path(path) / "metadata.json").read_text())


def save_arrays(path: str | Path, arrays: dict[str, np.ndarray]) -> None:
    """Write named ndarrays as one single-row-group parquet file — the analog
    of the reference's ``repartition(1).write.parquet`` (RapidsPCA.scala:197-199)."""
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    cols, names, shapes = [], [], {}
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        shapes[name] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
        cols.append(pa.array(arr.reshape(-1)))
        names.append(name)
    table = pa.table(
        {n: pa.array([c.to_numpy(zero_copy_only=False)]) for n, c in zip(names, cols)}
    )
    table = table.replace_schema_metadata({"tpu_ml_shapes": json.dumps(shapes)})
    pq.write_table(table, path / "data.parquet")


def load_arrays(path: str | Path) -> dict[str, np.ndarray]:
    table = pq.read_table(Path(path) / "data.parquet")
    shapes = json.loads(table.schema.metadata[b"tpu_ml_shapes"].decode())
    out = {}
    for name in table.column_names:
        flat = np.asarray(table.column(name).to_pylist()[0])
        info = shapes[name]
        out[name] = flat.astype(info["dtype"]).reshape(info["shape"])
    return out
