"""Model persistence — params JSON + parquet data, in two layouts.

**Native layout** (the fast local format): ``path/metadata.json`` (params
JSON: class, uid, timestamp, param map — the DefaultParamsWriter shape,
RapidsPCA.scala:196) + ``path/data.parquet`` (one single-row-group parquet
of flattened ndarray payloads + shape metadata), inspectable with stock
Arrow tooling.

**Spark ML layout** (cluster interop): the exact on-disk shape stock
``pyspark.ml`` reads and writes (RapidsPCA.scala:193-229 persists through
the same DefaultParamsWriter/Reader machinery) — ``path/metadata/
part-00000`` holding ONE line of JSON plus ``_SUCCESS``, and ``path/data/``
a parquet directory whose rows carry the model payload as Spark UDT structs
(MatrixUDT/VectorUDT) with the Spark schema recorded under the
``org.apache.spark.sql.parquet.row.metadata`` key so Spark's reader
reconstructs ``DenseMatrix``/``DenseVector`` columns. A PCAModel saved here
with ``layout="spark"`` loads in stock ``pyspark.ml`` via ``PCAModel.load``
and vice versa.

All paths accept fsspec URLs (``s3://…``, ``gs://…``, ``hdfs://…``,
``file://…``) when fsspec is importable; plain paths use the local
filesystem either way.
"""

from __future__ import annotations

import io
import json
import posixpath
import time
from pathlib import Path
from typing import Any

import numpy as np

try:
    import pyarrow as pa
    import pyarrow.parquet as pq
except Exception:  # pragma: no cover
    pa = None
    pq = None

try:
    import fsspec
except Exception:  # pragma: no cover - fsspec ships in supported images
    fsspec = None

_LIBRARY_VERSION_KEY = "libraryVersion"


# ---------------------------------------------------------------------------
# Filesystem facade: pathlib locally, fsspec for URLs
# ---------------------------------------------------------------------------


class _FS:
    """The handful of filesystem operations persistence needs, dispatched to
    fsspec for URL paths and pathlib otherwise — one place, so every save/
    load path (native and Spark layout) is remote-capable."""

    def __init__(self, path: str | Path):
        s = str(path)
        if "://" in s:
            if fsspec is None:
                raise ImportError(
                    f"path {s!r} looks remote but fsspec is not installed; "
                    "pip install fsspec (plus the protocol's driver, e.g. "
                    "s3fs/gcsfs) or use a local path"
                )
            self.fs, self.root = fsspec.core.url_to_fs(s)
        else:
            self.fs, self.root = None, s

    def join(self, *parts: str) -> str:
        return posixpath.join(self.root, *parts)

    def exists(self, rel: str = "") -> bool:
        p = self.join(rel) if rel else self.root
        return self.fs.exists(p) if self.fs else Path(p).exists()

    def mkdirs(self, rel: str = "") -> None:
        p = self.join(rel) if rel else self.root
        if self.fs:
            self.fs.makedirs(p, exist_ok=True)
        else:
            Path(p).mkdir(parents=True, exist_ok=True)

    def rmtree(self) -> None:
        if self.fs:
            if self.fs.exists(self.root):
                self.fs.rm(self.root, recursive=True)
        else:
            import shutil

            if Path(self.root).exists():
                shutil.rmtree(self.root)

    def write_text(self, rel: str, text: str) -> None:
        p = self.join(rel)
        if self.fs:
            with self.fs.open(p, "w") as f:
                f.write(text)
        else:
            Path(p).write_text(text)

    def read_text(self, rel: str) -> str:
        p = self.join(rel)
        if self.fs:
            with self.fs.open(p, "r") as f:
                return f.read()
        return Path(p).read_text()

    def listdir(self, rel: str = "") -> list[str]:
        p = self.join(rel) if rel else self.root
        if self.fs:
            return [posixpath.basename(f) for f in self.fs.ls(p, detail=False)]
        return [f.name for f in Path(p).iterdir()]

    def write_parquet(self, rel: str, table) -> None:
        p = self.join(rel)
        if self.fs:
            buf = io.BytesIO()
            pq.write_table(table, buf)
            with self.fs.open(p, "wb") as f:
                f.write(buf.getvalue())
        else:
            pq.write_table(table, p)

    def read_parquet(self, rel: str):
        p = self.join(rel)
        if self.fs:
            with self.fs.open(p, "rb") as f:
                return pq.read_table(io.BytesIO(f.read()))
        return pq.read_table(p)


# ---------------------------------------------------------------------------
# Native layout
# ---------------------------------------------------------------------------


def _jsonable(v: Any) -> Any:
    if isinstance(v, (np.integer, np.floating)):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def save_metadata(path: str | Path, instance, extra: dict | None = None) -> None:
    """DefaultParamsWriter.saveMetadata analog (RapidsPCA.scala:196)."""
    from spark_rapids_ml_tpu import __version__

    fs = _FS(path)
    fs.mkdirs()
    state = instance._paramState()
    meta = {
        "class": f"{type(instance).__module__}.{type(instance).__qualname__}",
        "timestamp": int(time.time() * 1000),
        _LIBRARY_VERSION_KEY: __version__,
        "uid": instance.uid,
        "paramMap": {k: _jsonable(v) for k, v in state["paramMap"].items()},
        "defaultParamMap": {k: _jsonable(v) for k, v in state["defaultParamMap"].items()},
    }
    if extra:
        meta.update(extra)
    fs.write_text("metadata.json", json.dumps(meta, indent=2))


def load_metadata(path: str | Path) -> dict:
    return json.loads(_FS(path).read_text("metadata.json"))


def save_arrays(path: str | Path, arrays: dict[str, np.ndarray]) -> None:
    """Write named ndarrays as one single-row-group parquet file — the analog
    of the reference's ``repartition(1).write.parquet`` (RapidsPCA.scala:197-199)."""
    fs = _FS(path)
    fs.mkdirs()
    cols, names, shapes = [], [], {}
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        shapes[name] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
        cols.append(pa.array(arr.reshape(-1)))
        names.append(name)
    table = pa.table(
        {n: pa.array([c.to_numpy(zero_copy_only=False)]) for n, c in zip(names, cols)}
    )
    table = table.replace_schema_metadata({"tpu_ml_shapes": json.dumps(shapes)})
    fs.write_parquet("data.parquet", table)


def load_arrays(path: str | Path) -> dict[str, np.ndarray]:
    table = _FS(path).read_parquet("data.parquet")
    shapes = json.loads(table.schema.metadata[b"tpu_ml_shapes"].decode())
    out = {}
    for name in table.column_names:
        flat = np.asarray(table.column(name).to_pylist()[0])
        info = shapes[name]
        out[name] = flat.astype(info["dtype"]).reshape(info["shape"])
    return out


# ---------------------------------------------------------------------------
# Spark ML layout — stock pyspark.ml interop
# ---------------------------------------------------------------------------
#
# Spark's DefaultParamsWriter writes path/metadata/part-00000 as a single
# JSON line; model payloads go to path/data/ as parquet whose columns are
# Spark UDTs. Spark's parquet reader reconstructs UDT columns only when the
# file carries the Spark schema JSON under this key:
_SPARK_ROW_METADATA_KEY = "org.apache.spark.sql.parquet.row.metadata"

_VECTOR_SQL_FIELDS = [
    {"name": "type", "type": "byte", "nullable": False, "metadata": {}},
    {"name": "size", "type": "integer", "nullable": True, "metadata": {}},
    {
        "name": "indices",
        "type": {"type": "array", "elementType": "integer", "containsNull": False},
        "nullable": True,
        "metadata": {},
    },
    {
        "name": "values",
        "type": {"type": "array", "elementType": "double", "containsNull": False},
        "nullable": True,
        "metadata": {},
    },
]

_MATRIX_SQL_FIELDS = [
    {"name": "type", "type": "byte", "nullable": False, "metadata": {}},
    {"name": "numRows", "type": "integer", "nullable": False, "metadata": {}},
    {"name": "numCols", "type": "integer", "nullable": False, "metadata": {}},
    {
        "name": "colPtrs",
        "type": {"type": "array", "elementType": "integer", "containsNull": False},
        "nullable": True,
        "metadata": {},
    },
    {
        "name": "rowIndices",
        "type": {"type": "array", "elementType": "integer", "containsNull": False},
        "nullable": True,
        "metadata": {},
    },
    {
        "name": "values",
        "type": {"type": "array", "elementType": "double", "containsNull": False},
        "nullable": True,
        "metadata": {},
    },
    {"name": "isTransposed", "type": "boolean", "nullable": False, "metadata": {}},
]


def _vector_udt_json() -> dict:
    return {
        "type": "udt",
        "class": "org.apache.spark.ml.linalg.VectorUDT",
        "pyClass": "pyspark.ml.linalg.VectorUDT",
        "sqlType": {"type": "struct", "fields": _VECTOR_SQL_FIELDS},
    }


def _matrix_udt_json() -> dict:
    return {
        "type": "udt",
        "class": "org.apache.spark.ml.linalg.MatrixUDT",
        "pyClass": "pyspark.ml.linalg.MatrixUDT",
        "sqlType": {"type": "struct", "fields": _MATRIX_SQL_FIELDS},
    }


def _dense_vector_struct(values: np.ndarray) -> "pa.StructArray":
    """One dense pyspark.ml.linalg VectorUDT row as its sql struct."""
    values = np.asarray(values, dtype=np.float64).reshape(-1)
    return pa.StructArray.from_arrays(
        [
            pa.array([1], pa.int8()),
            pa.array([None], pa.int32()),
            pa.array([None], pa.list_(pa.int32())),
            pa.array([values.tolist()], pa.list_(pa.float64())),
        ],
        names=["type", "size", "indices", "values"],
    )


def _dense_matrix_struct(mat: np.ndarray) -> "pa.StructArray":
    """One dense MatrixUDT row: Spark DenseMatrix stores values
    COLUMN-major with isTransposed=false (pyspark.ml.linalg.DenseMatrix)."""
    mat = np.asarray(mat, dtype=np.float64)
    rows, cols = mat.shape
    return pa.StructArray.from_arrays(
        [
            pa.array([1], pa.int8()),
            pa.array([rows], pa.int32()),
            pa.array([cols], pa.int32()),
            pa.array([None], pa.list_(pa.int32())),
            pa.array([None], pa.list_(pa.int32())),
            pa.array([mat.flatten(order="F").tolist()], pa.list_(pa.float64())),
            pa.array([False], pa.bool_()),
        ],
        names=["type", "numRows", "numCols", "colPtrs", "rowIndices", "values", "isTransposed"],
    )


def struct_to_vector(row: dict) -> np.ndarray:
    """A collected VectorUDT struct row (dict) → dense [n] ndarray."""
    if row["type"] == 1:
        return np.asarray(row["values"], dtype=np.float64)
    out = np.zeros(int(row["size"]), dtype=np.float64)
    out[np.asarray(row["indices"], dtype=np.int64)] = row["values"]
    return out


def struct_to_matrix(row: dict) -> np.ndarray:
    """A collected MatrixUDT struct row (dict) → dense [rows, cols] ndarray.

    Sparse (type 0) follows Spark's SparseMatrix layout: CSC normally, CSR
    when ``isTransposed`` (colPtrs become row pointers, rowIndices become
    column indices — pyspark.ml.linalg.SparseMatrix docs)."""
    rows, cols = int(row["numRows"]), int(row["numCols"])
    values = np.asarray(row["values"], dtype=np.float64)
    if row["type"] == 0:
        ptrs = np.asarray(row["colPtrs"], dtype=np.int64)
        idx = np.asarray(row["rowIndices"], dtype=np.int64)
        if row.get("isTransposed"):  # CSR: build the transpose as CSC, flip
            out = np.zeros((cols, rows))
            major = rows
        else:  # CSC
            out = np.zeros((rows, cols))
            major = cols
        for c in range(major):
            sl = slice(ptrs[c], ptrs[c + 1])
            out[idx[sl], c] = values[sl]
        return out.T if row.get("isTransposed") else out
    if row.get("isTransposed"):
        return values.reshape(rows, cols)  # row-major when transposed
    return values.reshape(cols, rows).T  # column-major


def save_spark_ml_metadata(
    path: str | Path,
    *,
    class_name: str,
    uid: str,
    param_map: dict,
    default_param_map: dict | None = None,
    spark_version: str = "3.5.0",
) -> None:
    """Write ``path/metadata/part-00000`` + ``_SUCCESS`` the way Spark's
    DefaultParamsWriter does: ONE line of compact JSON."""
    fs = _FS(path)
    fs.mkdirs("metadata")
    meta = {
        "class": class_name,
        "timestamp": int(time.time() * 1000),
        "sparkVersion": spark_version,
        "uid": uid,
        "paramMap": {k: _jsonable(v) for k, v in param_map.items()},
        "defaultParamMap": {
            k: _jsonable(v) for k, v in (default_param_map or {}).items()
        },
    }
    fs.write_text("metadata/part-00000", json.dumps(meta, separators=(",", ":")))
    fs.write_text("metadata/_SUCCESS", "")


def load_spark_ml_metadata(path: str | Path) -> dict:
    """Parse ``path/metadata/part-*`` (Spark may shard, but DefaultParamsWriter
    writes one part; take the first non-empty line found)."""
    fs = _FS(path)
    parts = sorted(
        f for f in fs.listdir("metadata") if f.startswith("part-")
    )
    if not parts:
        raise FileNotFoundError(f"no metadata part files under {path}/metadata")
    for part in parts:
        text = fs.read_text(f"metadata/{part}").strip()
        if text:
            return json.loads(text.splitlines()[0])
    raise ValueError(f"metadata part files under {path}/metadata are empty")


def save_spark_ml_data(
    path: str | Path, columns: dict[str, "pa.StructArray"], spark_schema: dict
) -> None:
    """Write ``path/data/part-00000…parquet`` (+ ``_SUCCESS``) with the Spark
    row-metadata schema key so stock Spark reconstructs the UDT columns."""
    fs = _FS(path)
    fs.mkdirs("data")
    table = pa.table(dict(columns))
    table = table.replace_schema_metadata(
        {_SPARK_ROW_METADATA_KEY: json.dumps(spark_schema, separators=(",", ":"))}
    )
    fs.write_parquet("data/part-00000-tpu-ml.snappy.parquet", table)
    fs.write_text("data/_SUCCESS", "")


def load_spark_ml_data(path: str | Path) -> "pa.Table":
    """Read every parquet part under ``path/data`` into one Arrow table."""
    fs = _FS(path)
    parts = sorted(
        f
        for f in fs.listdir("data")
        if f.endswith(".parquet") and not f.startswith(("_", "."))
    )
    if not parts:
        raise FileNotFoundError(f"no parquet part files under {path}/data")
    tables = [fs.read_parquet(f"data/{p}") for p in parts]
    return pa.concat_tables(tables) if len(tables) > 1 else tables[0]


def save_spark_ml_vector_model(
    path: str | Path,
    *,
    class_name: str,
    uid: str,
    params: dict,
    vectors: dict,
) -> None:
    """Persist the common Spark-ML model shape ``Row(<vector fields...>)``
    plus DefaultParamsWriter metadata — one writer for every model whose
    data row is an ordered set of dense vectors (the scaler family:
    std/mean, originalMin/originalMax, maxAbs). ``vectors`` order IS the
    stock reader's column order."""
    save_spark_ml_metadata(
        path, class_name=class_name, uid=uid, param_map=params
    )
    save_spark_ml_data(
        path,
        {name: _dense_vector_struct(v) for name, v in vectors.items()},
        {
            "type": "struct",
            "fields": [
                {
                    "name": name,
                    "type": _vector_udt_json(),
                    "nullable": True,
                    "metadata": {},
                }
                for name in vectors
            ],
        },
    )


def is_spark_ml_layout(path: str | Path) -> bool:
    """True when ``path`` holds a Spark-ML-layout save (metadata/ dir with
    part files) rather than the native metadata.json layout."""
    fs = _FS(path)
    if fs.exists("metadata.json"):
        return False
    return fs.exists("metadata")
