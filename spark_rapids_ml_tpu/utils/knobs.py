"""THE canonical inventory of ``TPU_ML_*`` environment knobs.

Every environment variable the framework (package, bench, tools) reads is
declared here once — name, type, default, one-line doc, and the module that
consumes it. Consumers re-export the env-var *name* from their declaration
here (``FAULT_PLAN_VAR = knobs.FAULT_PLAN.name`` style) instead of minting
their own string literal; ``tools/tpulint.py`` rule TPL006 rejects any
``TPU_ML_*`` literal outside this module, so an undeclared knob cannot
ship, and ``python -m tools.tpulint --list-knobs`` renders this inventory
(the README knob table is generated from it and drift-checked in CI).

This module is import-pure on purpose: no jax, no package siblings — the
linter, the README generator, and every consumer (including jax-free worker
ingestion processes) can import it with zero side effects.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str          # the TPU_ML_* environment variable
    type: str          # "int" | "float" | "str" | "path" | "flag" | "enum"
    default: str       # rendered default ("" = unset/disabled)
    doc: str           # one-line meaning, README-table ready
    module: str        # the consuming module (dotted path or tool file)


_DECLARATIONS = (
    # -- core runtime (utils.config caches these in RuntimeConfig) ----------
    Knob("TPU_ML_MIN_BUCKET", "int", "128",
         "row-bucket floor for static-shape padding (bounds distinct "
         "compiled shapes)", "utils.config"),
    Knob("TPU_ML_MAX_WORKERS", "int", "4",
         "partition executor thread pool size", "utils.config"),
    Knob("TPU_ML_TASK_RETRIES", "int", "3",
         "per-task retry budget (the `spark.task.maxFailures` analog)",
         "utils.config"),
    Knob("TPU_ML_DEFAULT_PRECISION", "enum", "highest",
         "`highest`/`high`/`default` matmul precision for Gram/projection "
         "kernels", "utils.config"),
    Knob("TPU_ML_STREAM_FIT_MAX_RESIDENT_BYTES", "int", str(1 << 31),
         "device-footprint cutover above which DataFrame fits stream "
         "chunk-wise instead of materializing", "utils.config"),
    Knob("TPU_ML_COMPILE_CACHE", "path",
         "~/.cache/spark_rapids_ml_tpu/xla",
         "persistent XLA compilation cache dir (empty string disables)",
         "utils.config"),
    Knob("TPU_ML_LOG_LEVEL", "str", "",
         "package logger level (name or number) set at import",
         "spark_rapids_ml_tpu"),
    # -- telemetry ----------------------------------------------------------
    Knob("TPU_ML_TELEMETRY_PATH", "path", "",
         "JSONL sink for per-fit/transform telemetry reports (empty "
         "disables)", "utils.config"),
    Knob("TPU_ML_TIMELINE_PATH", "path", "",
         "JSONL sink for flight-recorder timelines (empty disables)",
         "utils.config"),
    Knob("TPU_ML_TIMELINE_EVENTS", "int", "4096",
         "flight-recorder ring-buffer capacity (0 disables)",
         "telemetry.timeline"),
    Knob("TPU_ML_PROGRESS", "float", "",
         "emit a live streamed-fit heartbeat to stderr every N seconds "
         "(unset = off)", "spark.ingest"),
    Knob("TPU_ML_PEAK_TFLOPS", "float", "197.0",
         "device peak for the cost model's roofline denominator (default "
         "= TPU v5e bf16)", "telemetry.costmodel"),
    # -- resilience ---------------------------------------------------------
    Knob("TPU_ML_RETRY_MAX_ATTEMPTS", "int", "4",
         "shared retry-policy attempt budget per call site", "utils.config"),
    Knob("TPU_ML_RETRY_DEADLINE_S", "int", "300",
         "wall-clock ceiling across one call's retries (0 = unbounded)",
         "utils.config"),
    Knob("TPU_ML_STREAM_CHECKPOINT_EVERY_CHUNKS", "int", "64",
         "checkpoint the streamed-fit carry every K full chunks (with a "
         "checkpoint_dir)", "utils.config"),
    Knob("TPU_ML_FOLD_WAIT_TIMEOUT_S", "int", "600",
         "bound on the streamed fit's terminal device wait (0 = unbounded)",
         "utils.config"),
    Knob("TPU_ML_NONFINITE_POLICY", "enum", "raise",
         "`raise`/`skip`/`allow` for non-finite input rows in streamed "
         "fits", "utils.config"),
    Knob("TPU_ML_FAULT_PLAN", "str", "",
         "`site:kind:nth[:arg]` comma list of deterministic synthetic "
         "faults (chaos tests only — never production)",
         "resilience.faults"),
    # -- elastic stage scheduler (resilience.supervisor + localspark) -------
    Knob("TPU_ML_HEDGE_FACTOR", "float", "4.0",
         "speculatively re-dispatch a partition once its runtime exceeds "
         "this multiple of the completed-partition p50 (0 disables "
         "hedging)", "resilience.supervisor"),
    Knob("TPU_ML_HEDGE_FLOOR_S", "float", "1.0",
         "minimum straggler runtime before a hedge may fire (keeps tiny "
         "tasks from hedging on scheduler noise)", "resilience.supervisor"),
    Knob("TPU_ML_BARRIER_RETRIES", "int", "1",
         "barrier-stage epoch retries after an infrastructure rank failure "
         "(fresh workers per epoch; plan errors never retry)",
         "localspark.session"),
    Knob("TPU_ML_WORKER_BREAKER_THRESHOLD", "int", "3",
         "consecutive crashes after which a worker slot's circuit breaker "
         "opens and the slot is quarantined", "resilience.supervisor"),
    Knob("TPU_ML_WORKER_RESPAWN_BACKOFF_S", "float", "0.05",
         "base of the exponential backoff between respawns of a crashed "
         "worker slot", "resilience.supervisor"),
    Knob("TPU_ML_WORKER_SLOT", "int", "",
         "slot index the supervisor stamps into each worker's environment "
         "(diagnostics and slot-targeted chaos plans; never set manually)",
         "resilience.supervisor"),
    Knob("TPU_ML_ADMISSION_POLICY", "enum", "refuse",
         "`off`/`refuse`/`degrade`: what begin_fit does while the live "
         "health monitor reports FAILING — admit anyway, raise "
         "AdmissionRefused, or force the CPU-degraded fallback path",
         "telemetry.health"),
    # -- ingestion / streaming (spark.ingest) -------------------------------
    Knob("TPU_ML_MESH_LOCAL_WIRE_DTYPE", "enum", "float64",
         "wire dtype for mesh-local ingestion staging (`float32` halves "
         "the footprint)", "spark.ingest"),
    Knob("TPU_ML_MESH_LOCAL_MAX_BYTES", "int", "",
         "hard cap on mesh-local resident ingestion bytes (unset = "
         "uncapped)", "spark.ingest"),
    Knob("TPU_ML_MESH_LOCAL_ARROW_MAX_BYTES", "int", str(1 << 30),
         "Arrow-batch staging cutover for mesh-local ingestion",
         "spark.ingest"),
    Knob("TPU_ML_STREAM_CHUNK_ROWS", "int", "65536",
         "streamed-fit chunk size in rows", "spark.ingest"),
    Knob("TPU_ML_STREAM_CHUNK_FLOOR", "int", "8",
         "smallest chunk the OOM bisection may produce", "spark.ingest"),
    # -- worker device policy (localspark session <-> worker contract) ------
    Knob("TPU_ML_BARRIER_TIMEOUT_S", "float", "120",
         "barrier-stage rendezvous timeout", "localspark.session"),
    Knob("TPU_ML_WORKER_PLATFORM", "str", "",
         "jax platform a worker must initialize (env contract with the "
         "session)", "utils.devicepolicy"),
    Knob("TPU_ML_WORKER_PROBE", "flag", "",
         "`1`: workers run a bounded-time device probe at startup",
         "utils.devicepolicy"),
    Knob("TPU_ML_WORKER_PROBE_TIMEOUT", "float", "60.0",
         "seconds the worker device probe may take before failing",
         "utils.devicepolicy"),
    Knob("TPU_ML_WORKER_SCRUB_VARS", "str", "",
         "extra comma-separated env vars scrubbed from cpu-policy worker "
         "environments", "utils.devicepolicy"),
    # -- bench / perf ledger ------------------------------------------------
    Knob("TPU_ML_PERF_LEDGER_PATH", "path", "PERF_LEDGER.jsonl",
         "persistent perf ledger bench runs append to (empty disables)",
         "bench.py"),
    Knob("TPU_ML_PERF_SENTINEL", "flag", "",
         "`1`: bench runs tools/perf_sentinel.py --strict after appending "
         "the ledger entry", "bench.py"),
    Knob("TPU_ML_BENCH_PROBE_WINDOW_S", "float", "3600",
         "window the bench preamble waits for a healthy device transport",
         "bench.py"),
    Knob("TPU_ML_BENCH_PROBE_TIMEOUT", "float", "120",
         "per-attempt timeout of the bench device probe", "bench.py"),
    Knob("TPU_ML_OPPORTUNISTIC_MAX_AGE_S", "float", str(14 * 3600),
         "max age of an opportunistic bench harvest before it is ignored",
         "bench.py"),
    # -- autotune (spark_rapids_ml_tpu.autotune) ----------------------------
    Knob("TPU_ML_AUTOTUNE", "enum", "cache",
         "`off`/`cache`/`search` tuner mode: ignore the tuning cache, "
         "consult it read-only, or search unseen shape buckets on first "
         "fit", "autotune.search"),
    Knob("TPU_ML_AUTOTUNE_TRIALS", "int", "9",
         "total timing-trial budget of one successive-halving search",
         "autotune.search"),
    Knob("TPU_ML_TUNING_CACHE_PATH", "path", "",
         "persistent JSON tuning cache of blessed search winners (empty = "
         "in-process only)", "autotune.cache"),
    Knob("TPU_ML_PRECISION_POLICY", "enum", "f32",
         "`f32`/`bf16_f32acc`/`int8_dist` mixed-precision kernel policy "
         "default (accumulators stay f32)", "autotune.policy"),
    # -- ANN vector search (spark_rapids_ml_tpu.ann + ops.ivf) --------------
    Knob("TPU_ML_ANN_CAP_PERCENTILE", "float", "99.0",
         "IVF bucket-cap percentile over cluster sizes; members beyond the "
         "cap land on the exact spill list (100 = pad every bucket to the "
         "largest cluster)", "ops.ivf"),
    Knob("TPU_ML_ANN_SAMPLE_ROWS", "int", "32768",
         "row budget of the sampled kmeans|| coarse-quantizer training set "
         "for streamed IVF index builds (0 = train on the full stream)",
         "ann.index"),
    # -- warm-path serving runtime (spark_rapids_ml_tpu.serving) ------------
    Knob("TPU_ML_SERVE_COMPILE_CACHE_DIR", "path", "",
         "persistent XLA cache dir for AOT-compiled serve kernels (fresh "
         "processes warm from disk; empty = share TPU_ML_COMPILE_CACHE)",
         "serving.registry"),
    Knob("TPU_ML_SERVE_MIN_BUCKET", "int", "8",
         "serve-path row-bucket floor (smaller than the fit-path "
         "TPU_ML_MIN_BUCKET so single-row scoring pads less)",
         "serving.buckets"),
    Knob("TPU_ML_SERVE_MAX_BATCH_ROWS", "int", "4096",
         "largest serve row bucket; caps one micro-batched dispatch and "
         "bounds the AOT-compiled signature ladder", "serving.buckets"),
    Knob("TPU_ML_SERVE_MAX_DELAY_US", "float", "2000",
         "micro-batcher coalescing window CEILING: a queued request waits "
         "at most this long for same-(model,bucket) company before dispatch "
         "(the adaptive window shrinks below it under load)",
         "serving.batcher"),
    Knob("TPU_ML_SERVE_ADAPTIVE_WINDOW", "flag", "1",
         "`1`: the coalescing window tracks the observed device dispatch "
         "time (drain latency ~= device time); `0`: fixed "
         "TPU_ML_SERVE_MAX_DELAY_US window", "serving.batcher"),
    Knob("TPU_ML_SERVE_UDS_PATH", "path", "",
         "Unix-domain-socket path for the framing-free serve listener "
         "(empty = UDS transport off; co-located callers skip HTTP "
         "entirely)", "serving.server"),
    Knob("TPU_ML_SERVE_HBM_BUDGET_BYTES", "int", "",
         "byte budget of the HBM fleet manager for resident model params "
         "(unset = live device bytes_limit x TPU_ML_HEALTH_HBM_WATERMARK; "
         "cold models page to host beyond it)", "serving.hbm"),
    Knob("TPU_ML_SERVE_P99_GATE_MS", "float", "",
         "absolute serve_p99_ms ceiling bench stamps on the ledger entry "
         "for tools/perf_sentinel.py to enforce (unset = relative history "
         "gating only; also gates fleet_p99_ms in the fleet bench stage)",
         "bench.py"),
    Knob("TPU_ML_SERVE_HEDGE_FLOOR_US", "float", "2000",
         "serve-scale floor (microseconds) of the hedged-dispatch "
         "threshold: a micro-batch is re-issued when the primary dispatch "
         "exceeds max(this, TPU_ML_HEDGE_FACTOR x device-time EWMA); "
         "TPU_ML_HEDGE_FACTOR=0 disables serve hedging too",
         "serving.batcher"),
    Knob("TPU_ML_SERVE_FLEET_REPLICAS", "int", "0",
         "replica count of the multi-process serve fleet (0 = fleet off; "
         "each replica is a UDS server process with its own AOT cache "
         "warmed from TPU_ML_SERVE_COMPILE_CACHE_DIR)", "serving.fleet"),
    Knob("TPU_ML_SERVE_FLEET_SOCKET_DIR", "path", "",
         "directory for fleet replica + router UDS sockets (empty = a "
         "fresh tempdir per fleet; must be short enough for AF_UNIX's "
         "~100-byte path limit)", "serving.fleet"),
    Knob("TPU_ML_SERVE_DRAIN_TIMEOUT_S", "float", "30",
         "rolling drain bound: max seconds the fleet router waits for a "
         "draining replica's in-flight requests to reach zero before the "
         "replica is restarted anyway", "serving.fleet"),
    # -- distributed tracing (telemetry.tracectx) ---------------------------
    Knob("TPU_ML_TRACE_SAMPLE", "float", "1.0",
         "fraction of admitted serve requests that mint a trace context "
         "(carried over HTTP/UDS/fastlane and stitched fleet-wide; 0 "
         "disables request tracing)", "telemetry.tracectx"),
    Knob("TPU_ML_TRACE_EXEMPLARS", "int", "4",
         "slowest-request exemplars (value + trace_id) retained per "
         "latency-histogram series and surfaced in serving evidence "
         "(0 disables exemplar capture)", "telemetry.tracectx"),
    # -- closed-loop model refresh (spark_rapids_ml_tpu.refresh) ------------
    Knob("TPU_ML_REFRESH_INTERVAL_S", "float", "30",
         "seconds between refresh-daemon cycles (fold pending deltas, "
         "checkpoint, attempt a hot-swap)", "refresh.daemon"),
    Knob("TPU_ML_REFRESH_MIN_ROWS", "int", "1",
         "delta rows that must fold before the daemon finalizes a "
         "candidate and attempts a swap", "refresh.daemon"),
    Knob("TPU_ML_REFRESH_CHECKPOINT_DIR", "path", "",
         "directory for the refresh daemon's durable carry checkpoints "
         "(atomic npz; empty = memory-only, no restart survival)",
         "refresh.daemon"),
    Knob("TPU_ML_SWAP_SHADOW_ROWS", "int", "256",
         "held-back sample rows the shadow-scoring gate scores a swap "
         "candidate against the live model on (0 disables the gate)",
         "refresh.daemon"),
    Knob("TPU_ML_SWAP_SHADOW_TOLERANCE", "float", "0.25",
         "max relative divergence between candidate and live outputs on "
         "the shadow sample before the swap is refused", "serving.registry"),
    Knob("TPU_ML_SWAP_PROBATION_S", "float", "60",
         "post-swap probation window: an SLO burn inside it rolls back to "
         "the prior version (which stays HBM-resident until probation "
         "clears)", "refresh.daemon"),
    # -- transport monitor / health daemon (tools/healthd.py) ---------------
    Knob("TPU_ML_MONITOR_BENCH_OUT", "path", "BENCH_OPPORTUNISTIC_r05.json",
         "opportunistic bench output file (relative to the repo)",
         "tools/healthd.py"),
    Knob("TPU_ML_MONITOR_DRIFT_OUT", "path", "BENCH_DRIFT_r05.jsonl",
         "transport-monitor drift log (relative to the repo)",
         "tools/healthd.py"),
    Knob("TPU_ML_MONITOR_INTERVAL_S", "float", "600",
         "seconds between transport probes", "tools/healthd.py"),
    Knob("TPU_ML_MONITOR_PROBE_TIMEOUT_S", "float", "120",
         "per-probe timeout of the transport monitor",
         "tools/healthd.py"),
    Knob("TPU_ML_MONITOR_WINDOW_S", "float", str(11.5 * 3600),
         "total monitoring window before the monitor gives up",
         "tools/healthd.py"),
    Knob("TPU_ML_MONITOR_BENCH_RUNS", "int", "5",
         "bench repetitions per opportunistic harvest",
         "tools/healthd.py"),
    Knob("TPU_ML_MONITOR_BENCH_TIMEOUT_S", "float", "3600",
         "timeout of one opportunistic bench run",
         "tools/healthd.py"),
    # -- live health monitor (telemetry.health) -----------------------------
    Knob("TPU_ML_HEALTH_INTERVAL_S", "float", "5.0",
         "seconds between HealthMonitor poll cycles", "telemetry.health"),
    Knob("TPU_ML_HEALTH_PROBE", "enum", "inline",
         "`off`/`inline`/`subprocess` transport liveness probe mode of the "
         "health monitor", "telemetry.health"),
    Knob("TPU_ML_HEALTH_PROBE_TIMEOUT_S", "float", "20.0",
         "deadline of one health-monitor liveness probe", "telemetry.health"),
    Knob("TPU_ML_HEALTH_HBM_WATERMARK", "float", "0.92",
         "bytes_in_use/bytes_limit fraction above which the device "
         "component degrades", "telemetry.health"),
    Knob("TPU_ML_HEALTH_STALE_S", "float", "60.0",
         "stream-heartbeat / worker-trailer staleness threshold",
         "telemetry.health"),
    Knob("TPU_ML_HEALTH_FAILING_AFTER", "int", "3",
         "consecutive degraded polls before a component turns FAILING",
         "telemetry.health"),
    Knob("TPU_ML_HEALTH_RETRY_STORM", "int", "8",
         "retry.attempts delta per poll window that flags a retry storm",
         "telemetry.health"),
    # -- sliding-window SLOs (telemetry.slo) --------------------------------
    Knob("TPU_ML_SLO", "str", "",
         "comma list of `series:pNN:ceiling_s` latency objectives and "
         "`counter:min_rate:floor_per_s` throughput floors (empty = rolling "
         "percentiles only)", "telemetry.slo"),
    Knob("TPU_ML_SLO_WINDOW_S", "float", "300",
         "sliding evaluation window of the SLO engine", "telemetry.slo"),
    Knob("TPU_ML_SLO_BURN", "int", "2",
         "consecutive breached evaluations before slo.breach fires (burn "
         "rate)", "telemetry.slo"),
    # -- HTTP exporter (telemetry.httpd) ------------------------------------
    Knob("TPU_ML_HTTP_PORT", "int", "",
         "serve /metrics,/healthz,/slo,/report on this port (0 = ephemeral; "
         "unset = exporter off)", "telemetry.httpd"),
)

KNOBS: dict[str, Knob] = {k.name: k for k in _DECLARATIONS}

if len(KNOBS) != len(_DECLARATIONS):  # pragma: no cover - declaration bug
    raise RuntimeError("duplicate TPU_ML_* knob declaration")

# Named handles for consumers that re-export the env-var name locally
# (keeps call sites grep-able while the literal lives only here).
MIN_BUCKET = KNOBS["TPU_ML_MIN_BUCKET"]
MAX_WORKERS = KNOBS["TPU_ML_MAX_WORKERS"]
TASK_RETRIES = KNOBS["TPU_ML_TASK_RETRIES"]
DEFAULT_PRECISION = KNOBS["TPU_ML_DEFAULT_PRECISION"]
STREAM_FIT_MAX_RESIDENT_BYTES = KNOBS["TPU_ML_STREAM_FIT_MAX_RESIDENT_BYTES"]
COMPILE_CACHE = KNOBS["TPU_ML_COMPILE_CACHE"]
LOG_LEVEL = KNOBS["TPU_ML_LOG_LEVEL"]
TELEMETRY_PATH = KNOBS["TPU_ML_TELEMETRY_PATH"]
TIMELINE_PATH = KNOBS["TPU_ML_TIMELINE_PATH"]
TIMELINE_EVENTS = KNOBS["TPU_ML_TIMELINE_EVENTS"]
PROGRESS = KNOBS["TPU_ML_PROGRESS"]
PEAK_TFLOPS = KNOBS["TPU_ML_PEAK_TFLOPS"]
RETRY_MAX_ATTEMPTS = KNOBS["TPU_ML_RETRY_MAX_ATTEMPTS"]
RETRY_DEADLINE_S = KNOBS["TPU_ML_RETRY_DEADLINE_S"]
STREAM_CHECKPOINT_EVERY_CHUNKS = KNOBS["TPU_ML_STREAM_CHECKPOINT_EVERY_CHUNKS"]
FOLD_WAIT_TIMEOUT_S = KNOBS["TPU_ML_FOLD_WAIT_TIMEOUT_S"]
NONFINITE_POLICY = KNOBS["TPU_ML_NONFINITE_POLICY"]
FAULT_PLAN = KNOBS["TPU_ML_FAULT_PLAN"]
HEDGE_FACTOR = KNOBS["TPU_ML_HEDGE_FACTOR"]
HEDGE_FLOOR_S = KNOBS["TPU_ML_HEDGE_FLOOR_S"]
BARRIER_RETRIES = KNOBS["TPU_ML_BARRIER_RETRIES"]
WORKER_BREAKER_THRESHOLD = KNOBS["TPU_ML_WORKER_BREAKER_THRESHOLD"]
WORKER_RESPAWN_BACKOFF_S = KNOBS["TPU_ML_WORKER_RESPAWN_BACKOFF_S"]
WORKER_SLOT = KNOBS["TPU_ML_WORKER_SLOT"]
ADMISSION_POLICY = KNOBS["TPU_ML_ADMISSION_POLICY"]
MESH_LOCAL_WIRE_DTYPE = KNOBS["TPU_ML_MESH_LOCAL_WIRE_DTYPE"]
MESH_LOCAL_MAX_BYTES = KNOBS["TPU_ML_MESH_LOCAL_MAX_BYTES"]
MESH_LOCAL_ARROW_MAX_BYTES = KNOBS["TPU_ML_MESH_LOCAL_ARROW_MAX_BYTES"]
STREAM_CHUNK_ROWS = KNOBS["TPU_ML_STREAM_CHUNK_ROWS"]
STREAM_CHUNK_FLOOR = KNOBS["TPU_ML_STREAM_CHUNK_FLOOR"]
BARRIER_TIMEOUT_S = KNOBS["TPU_ML_BARRIER_TIMEOUT_S"]
WORKER_PLATFORM = KNOBS["TPU_ML_WORKER_PLATFORM"]
WORKER_PROBE = KNOBS["TPU_ML_WORKER_PROBE"]
WORKER_PROBE_TIMEOUT = KNOBS["TPU_ML_WORKER_PROBE_TIMEOUT"]
WORKER_SCRUB_VARS = KNOBS["TPU_ML_WORKER_SCRUB_VARS"]
PERF_LEDGER_PATH = KNOBS["TPU_ML_PERF_LEDGER_PATH"]
PERF_SENTINEL = KNOBS["TPU_ML_PERF_SENTINEL"]
BENCH_PROBE_WINDOW_S = KNOBS["TPU_ML_BENCH_PROBE_WINDOW_S"]
BENCH_PROBE_TIMEOUT = KNOBS["TPU_ML_BENCH_PROBE_TIMEOUT"]
OPPORTUNISTIC_MAX_AGE_S = KNOBS["TPU_ML_OPPORTUNISTIC_MAX_AGE_S"]
AUTOTUNE = KNOBS["TPU_ML_AUTOTUNE"]
AUTOTUNE_TRIALS = KNOBS["TPU_ML_AUTOTUNE_TRIALS"]
TUNING_CACHE_PATH = KNOBS["TPU_ML_TUNING_CACHE_PATH"]
PRECISION_POLICY = KNOBS["TPU_ML_PRECISION_POLICY"]
ANN_CAP_PERCENTILE = KNOBS["TPU_ML_ANN_CAP_PERCENTILE"]
ANN_SAMPLE_ROWS = KNOBS["TPU_ML_ANN_SAMPLE_ROWS"]
SERVE_COMPILE_CACHE_DIR = KNOBS["TPU_ML_SERVE_COMPILE_CACHE_DIR"]
SERVE_MIN_BUCKET = KNOBS["TPU_ML_SERVE_MIN_BUCKET"]
SERVE_MAX_BATCH_ROWS = KNOBS["TPU_ML_SERVE_MAX_BATCH_ROWS"]
SERVE_MAX_DELAY_US = KNOBS["TPU_ML_SERVE_MAX_DELAY_US"]
SERVE_ADAPTIVE_WINDOW = KNOBS["TPU_ML_SERVE_ADAPTIVE_WINDOW"]
SERVE_UDS_PATH = KNOBS["TPU_ML_SERVE_UDS_PATH"]
SERVE_HBM_BUDGET_BYTES = KNOBS["TPU_ML_SERVE_HBM_BUDGET_BYTES"]
SERVE_P99_GATE_MS = KNOBS["TPU_ML_SERVE_P99_GATE_MS"]
SERVE_HEDGE_FLOOR_US = KNOBS["TPU_ML_SERVE_HEDGE_FLOOR_US"]
SERVE_FLEET_REPLICAS = KNOBS["TPU_ML_SERVE_FLEET_REPLICAS"]
SERVE_FLEET_SOCKET_DIR = KNOBS["TPU_ML_SERVE_FLEET_SOCKET_DIR"]
SERVE_DRAIN_TIMEOUT_S = KNOBS["TPU_ML_SERVE_DRAIN_TIMEOUT_S"]
TRACE_SAMPLE = KNOBS["TPU_ML_TRACE_SAMPLE"]
TRACE_EXEMPLARS = KNOBS["TPU_ML_TRACE_EXEMPLARS"]
REFRESH_INTERVAL_S = KNOBS["TPU_ML_REFRESH_INTERVAL_S"]
REFRESH_MIN_ROWS = KNOBS["TPU_ML_REFRESH_MIN_ROWS"]
REFRESH_CHECKPOINT_DIR = KNOBS["TPU_ML_REFRESH_CHECKPOINT_DIR"]
SWAP_SHADOW_ROWS = KNOBS["TPU_ML_SWAP_SHADOW_ROWS"]
SWAP_SHADOW_TOLERANCE = KNOBS["TPU_ML_SWAP_SHADOW_TOLERANCE"]
SWAP_PROBATION_S = KNOBS["TPU_ML_SWAP_PROBATION_S"]
MONITOR_BENCH_OUT = KNOBS["TPU_ML_MONITOR_BENCH_OUT"]
MONITOR_DRIFT_OUT = KNOBS["TPU_ML_MONITOR_DRIFT_OUT"]
MONITOR_INTERVAL_S = KNOBS["TPU_ML_MONITOR_INTERVAL_S"]
MONITOR_PROBE_TIMEOUT_S = KNOBS["TPU_ML_MONITOR_PROBE_TIMEOUT_S"]
MONITOR_WINDOW_S = KNOBS["TPU_ML_MONITOR_WINDOW_S"]
MONITOR_BENCH_RUNS = KNOBS["TPU_ML_MONITOR_BENCH_RUNS"]
MONITOR_BENCH_TIMEOUT_S = KNOBS["TPU_ML_MONITOR_BENCH_TIMEOUT_S"]
HEALTH_INTERVAL_S = KNOBS["TPU_ML_HEALTH_INTERVAL_S"]
HEALTH_PROBE = KNOBS["TPU_ML_HEALTH_PROBE"]
HEALTH_PROBE_TIMEOUT_S = KNOBS["TPU_ML_HEALTH_PROBE_TIMEOUT_S"]
HEALTH_HBM_WATERMARK = KNOBS["TPU_ML_HEALTH_HBM_WATERMARK"]
HEALTH_STALE_S = KNOBS["TPU_ML_HEALTH_STALE_S"]
HEALTH_FAILING_AFTER = KNOBS["TPU_ML_HEALTH_FAILING_AFTER"]
HEALTH_RETRY_STORM = KNOBS["TPU_ML_HEALTH_RETRY_STORM"]
SLO = KNOBS["TPU_ML_SLO"]
SLO_WINDOW_S = KNOBS["TPU_ML_SLO_WINDOW_S"]
SLO_BURN = KNOBS["TPU_ML_SLO_BURN"]
HTTP_PORT = KNOBS["TPU_ML_HTTP_PORT"]


def markdown_table() -> str:
    """The README knob table, generated (see tools/tpulint.py
    --list-knobs --markdown and the --check-readme drift gate)."""
    lines = [
        "| knob | type | default | meaning | read by |",
        "|------|------|---------|---------|---------|",
    ]
    for k in _DECLARATIONS:
        default = f"`{k.default}`" if k.default else "unset"
        lines.append(
            f"| `{k.name}` | {k.type} | {default} | {k.doc} | `{k.module}` |"
        )
    return "\n".join(lines)
