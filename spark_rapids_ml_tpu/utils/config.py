"""Runtime configuration — the env/conf tier of the config system.

The reference's config is three-tier (SURVEY.md §5): (1) per-estimator ML
Params, (2) Spark runtime confs (``spark.rapids.sql.enabled``, GPU resource
amounts), (3) build-time flags. Tier 1 lives in ``models.params``. This
module is tier 2 for the TPU build — process-level knobs read from
``TPU_ML_*`` environment variables once at first use, overridable in code:

- ``TPU_ML_MIN_BUCKET``      (int, default 128)  — row-bucket floor for
  static-shape padding (utils.columnar.bucket_rows).
- ``TPU_ML_MAX_WORKERS``     (int, default 4)    — partition executor pool.
- ``TPU_ML_TASK_RETRIES``    (int, default 3)    — per-task retry budget
  (the ``spark.task.maxFailures`` analog).
- ``TPU_ML_DEFAULT_PRECISION`` ('highest'|'high'|'default') — estimator-level
  default for the Gram/projection matmul precision.
- ``TPU_ML_STREAM_FIT_MAX_RESIDENT_BYTES`` (int, default 2**31) — cutover
  for the out-of-core streamed fit: DataFrame fits whose estimated device
  footprint (rows × n × wire-dtype bytes) exceeds this stream chunk-wise
  through the donated-carry fold pipeline (spark.ingest.stream_fold) at
  O(chunk + n²) device memory instead of materializing the full resident
  array. Small data keeps the resident path — it is still fastest when it
  fits.
- ``TPU_ML_COMPILE_CACHE``   (path, default ``~/.cache/spark_rapids_ml_tpu/
  xla``; empty string disables) — persistent XLA compilation cache shared by
  every process of a deployment. In-process executable reuse is handled by
  the ``lru_cache``d program builders in ``parallel/``; this cache is what
  saves the barrier-stage/executor WORKER processes (fresh interpreter per
  job) and repeated driver runs from paying the multi-second XLA compile on
  every fit.
- ``TPU_ML_TELEMETRY_PATH``  (path, default ``''`` = disabled) — JSONL sink
  for per-fit telemetry reports (``telemetry.export``). Each completed
  ``fit()`` appends one ``fit_report`` record; render with
  ``python tools/trace_report.py <path>``.
- ``TPU_ML_TIMELINE_PATH``   (path, default ``''`` = disabled) — JSONL sink
  for per-fit flight-recorder timelines (``telemetry.timeline``): one
  ``timeline`` record of raw span/instant events per outermost ``fit()``.
  May point at the same file as ``TPU_ML_TELEMETRY_PATH`` (readers filter
  by record type). Export to Perfetto-loadable Chrome trace JSON with
  ``python tools/trace_timeline.py <path> --out trace.json``.
- ``TPU_ML_TIMELINE_EVENTS`` (int, default 4096; 0 disables; read directly
  by ``telemetry.timeline``, not cached here) — ring-buffer capacity of
  the flight recorder. Old events fall off; aggregate truth stays in the
  metrics registry.
- ``TPU_ML_PROGRESS`` (float seconds, default unset = off; read directly
  by ``spark.ingest.stream_fold``) — emit a live progress heartbeat line
  to stderr every N seconds during a streamed fit: rows done, rows/s,
  current chunk size, retries/bisections so far.
- ``TPU_ML_RETRY_MAX_ATTEMPTS`` (int, default 4) — attempt budget for the
  shared retry policy (``resilience.retry.RetryPolicy.from_config``):
  classified-transient failures at the data-movement/compute choke points
  retry up to this many total attempts.
- ``TPU_ML_RETRY_DEADLINE_S`` (int, default 300; 0 = unbounded) — wall
  deadline across one call's retries; once exceeded, no further attempt
  is made.
- ``TPU_ML_STREAM_CHECKPOINT_EVERY_CHUNKS`` (int, default 64) — with a
  ``checkpoint_dir``, the streamed fit durably checkpoints its carry +
  chunk cursor every this many chunks so a preempted fit resumes instead
  of restarting.
- ``TPU_ML_FOLD_WAIT_TIMEOUT_S`` (int, default 600; 0 = unbounded) — bound
  on the streamed fit's terminal device wait; a wedged device surfaces as
  a diagnosable ``FoldHangTimeout`` instead of blocking forever.
- ``TPU_ML_NONFINITE_POLICY`` ('raise'|'skip'|'allow', default 'raise') —
  streamed-fit handling of non-finite input rows: fail the fit, drop and
  count them (``rows.nonfinite_skipped``), or skip the scan entirely.
- ``TPU_ML_FAULT_PLAN`` (read by ``resilience.faults``, not cached here) —
  deterministic fault-injection plan for chaos testing; see the Resilience
  README section. Never set in production.
- ``TPU_ML_LOG_LEVEL``       (logging level name or number, default unset) —
  sets the ``spark_rapids_ml_tpu`` logger level at package import. The
  package attaches only a ``logging.NullHandler``; output routing stays the
  application's choice.
- ``TPU_ML_PEAK_TFLOPS`` (float, default 197.0 = TPU v5e bf16 peak; read
  directly by ``telemetry.costmodel``) — device peak for the cost model's
  roofline-utilization denominator stamped into Fit/TransformReports.
- ``TPU_ML_PERF_LEDGER_PATH`` (path, default ``PERF_LEDGER.jsonl`` next to
  ``bench.py``; empty string disables; read directly by ``bench.py``) —
  persistent perf ledger each bench run appends its metrics + cost-model
  numbers to; compared across runs by ``tools/perf_sentinel.py``.
- ``TPU_ML_PERF_SENTINEL`` (``1`` to enable; read directly by ``bench.py``)
  — after appending the ledger entry, the bench runs
  ``tools/perf_sentinel.py --strict`` on it and fails on regressions
  beyond the threshold — the opt-in CI perf gate for ``bench --smoke``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from spark_rapids_ml_tpu.utils import knobs

VALID_PRECISIONS = ("highest", "high", "default")
VALID_NONFINITE_POLICIES = ("raise", "skip", "allow")

# config fields whose values are strings (everything else is int-typed)
_STR_KEYS = (
    "default_precision",
    "telemetry_path",
    "timeline_path",
    "nonfinite_policy",
)


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        raise ValueError(
            f"{name}={os.environ[name]!r} is not an integer"
        ) from None


def _precision_env() -> str:
    v = os.environ.get(knobs.DEFAULT_PRECISION.name, "highest")
    if v not in VALID_PRECISIONS:
        raise ValueError(
            f"{knobs.DEFAULT_PRECISION.name}={v!r} must be one of "
            f"{VALID_PRECISIONS}"
        )
    return v


def _nonfinite_env() -> str:
    v = os.environ.get(knobs.NONFINITE_POLICY.name, "raise")
    if v not in VALID_NONFINITE_POLICIES:
        raise ValueError(
            f"{knobs.NONFINITE_POLICY.name}={v!r} must be one of "
            f"{VALID_NONFINITE_POLICIES}"
        )
    return v


@dataclass
class RuntimeConfig:
    min_bucket: int = field(
        default_factory=lambda: _int_env(knobs.MIN_BUCKET.name, 128)
    )
    max_workers: int = field(
        default_factory=lambda: _int_env(knobs.MAX_WORKERS.name, 4)
    )
    task_retries: int = field(
        default_factory=lambda: _int_env(knobs.TASK_RETRIES.name, 3)
    )
    default_precision: str = field(default_factory=_precision_env)
    stream_fit_max_resident_bytes: int = field(
        default_factory=lambda: _int_env(
            knobs.STREAM_FIT_MAX_RESIDENT_BYTES.name, 1 << 31
        )
    )
    telemetry_path: str = field(
        default_factory=lambda: os.environ.get(knobs.TELEMETRY_PATH.name, "")
    )
    timeline_path: str = field(
        default_factory=lambda: os.environ.get(knobs.TIMELINE_PATH.name, "")
    )
    retry_max_attempts: int = field(
        default_factory=lambda: _int_env(knobs.RETRY_MAX_ATTEMPTS.name, 4)
    )
    retry_deadline_s: int = field(
        default_factory=lambda: _int_env(knobs.RETRY_DEADLINE_S.name, 300)
    )
    stream_checkpoint_every_chunks: int = field(
        default_factory=lambda: _int_env(
            knobs.STREAM_CHECKPOINT_EVERY_CHUNKS.name, 64
        )
    )
    fold_wait_timeout_s: int = field(
        default_factory=lambda: _int_env(knobs.FOLD_WAIT_TIMEOUT_S.name, 600)
    )
    nonfinite_policy: str = field(default_factory=_nonfinite_env)


_config: RuntimeConfig | None = None
_compile_cache_enabled = False


def enable_compilation_cache() -> str | None:
    """Point JAX at the persistent XLA compilation cache (idempotent).

    Returns the cache directory, or None when disabled
    (``TPU_ML_COMPILE_CACHE=''``) or when this JAX build rejects the
    options. Safe to call before or after backend initialization; callers
    invoke it lazily right before the first compile-heavy path (estimator
    fits, SPMD workers) so importing the package stays side-effect free.
    """
    global _compile_cache_enabled
    cache_dir = os.environ.get(
        knobs.COMPILE_CACHE.name,
        os.path.join(
            os.path.expanduser("~"), ".cache", "spark_rapids_ml_tpu", "xla"
        ),
    )
    if not cache_dir:
        return None
    if _compile_cache_enabled:
        return cache_dir
    try:
        import jax

        if getattr(jax.config, "jax_compilation_cache_dir", None):
            # an embedding application (or the test harness) already chose a
            # cache location — respect it
            _compile_cache_enabled = True
            return jax.config.jax_compilation_cache_dir
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except (ImportError, OSError, AttributeError, ValueError):
        return None
    _compile_cache_enabled = True
    # Tuning knobs are best-effort per-knob: a JAX build that lacks or
    # rejects one must not leave the just-applied cache dir looking like an
    # external choice on the next call (half-applied-state trap).
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.5),
        # cache regardless of backend: the CPU fallback deployments (worker
        # ingestion processes, tests) recompile just as painfully
        ("jax_persistent_cache_enable_xla_caches", "all"),
    ):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):
            pass
    return cache_dir


def get_config() -> RuntimeConfig:
    global _config
    if _config is None:
        _config = RuntimeConfig()
    return _config


def set_config(**overrides) -> RuntimeConfig:
    """Override runtime knobs in code (tests, notebooks)."""
    cfg = get_config()
    for k, v in overrides.items():
        if not hasattr(cfg, k):
            raise KeyError(f"unknown config key {k!r}")
        if k == "default_precision" and v not in VALID_PRECISIONS:
            raise ValueError(
                f"default_precision={v!r} must be one of {VALID_PRECISIONS}"
            )
        if k == "nonfinite_policy" and v not in VALID_NONFINITE_POLICIES:
            raise ValueError(
                f"nonfinite_policy={v!r} must be one of "
                f"{VALID_NONFINITE_POLICIES}"
            )
        if k in _STR_KEYS:
            if not isinstance(v, str):
                raise TypeError(f"{k} must be a str, got {type(v).__name__}")
        elif not isinstance(v, int):
            raise TypeError(f"{k} must be an int, got {type(v).__name__}")
        setattr(cfg, k, v)
    return cfg
