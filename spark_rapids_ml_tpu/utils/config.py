"""Runtime configuration — the env/conf tier of the config system.

The reference's config is three-tier (SURVEY.md §5): (1) per-estimator ML
Params, (2) Spark runtime confs (``spark.rapids.sql.enabled``, GPU resource
amounts), (3) build-time flags. Tier 1 lives in ``models.params``. This
module is tier 2 for the TPU build — process-level knobs read from
``TPU_ML_*`` environment variables once at first use, overridable in code:

- ``TPU_ML_MIN_BUCKET``      (int, default 128)  — row-bucket floor for
  static-shape padding (utils.columnar.bucket_rows).
- ``TPU_ML_MAX_WORKERS``     (int, default 4)    — partition executor pool.
- ``TPU_ML_TASK_RETRIES``    (int, default 3)    — per-task retry budget
  (the ``spark.task.maxFailures`` analog).
- ``TPU_ML_DEFAULT_PRECISION`` ('highest'|'high'|'default') — estimator-level
  default for the Gram/projection matmul precision.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


VALID_PRECISIONS = ("highest", "high", "default")


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        raise ValueError(
            f"{name}={os.environ[name]!r} is not an integer"
        ) from None


def _precision_env() -> str:
    v = os.environ.get("TPU_ML_DEFAULT_PRECISION", "highest")
    if v not in VALID_PRECISIONS:
        raise ValueError(
            f"TPU_ML_DEFAULT_PRECISION={v!r} must be one of {VALID_PRECISIONS}"
        )
    return v


@dataclass
class RuntimeConfig:
    min_bucket: int = field(default_factory=lambda: _int_env("TPU_ML_MIN_BUCKET", 128))
    max_workers: int = field(default_factory=lambda: _int_env("TPU_ML_MAX_WORKERS", 4))
    task_retries: int = field(default_factory=lambda: _int_env("TPU_ML_TASK_RETRIES", 3))
    default_precision: str = field(default_factory=_precision_env)


_config: RuntimeConfig | None = None


def get_config() -> RuntimeConfig:
    global _config
    if _config is None:
        _config = RuntimeConfig()
    return _config


def set_config(**overrides) -> RuntimeConfig:
    """Override runtime knobs in code (tests, notebooks)."""
    cfg = get_config()
    for k, v in overrides.items():
        if not hasattr(cfg, k):
            raise KeyError(f"unknown config key {k!r}")
        if k == "default_precision" and v not in VALID_PRECISIONS:
            raise ValueError(
                f"default_precision={v!r} must be one of {VALID_PRECISIONS}"
            )
        if k != "default_precision" and not isinstance(v, int):
            raise TypeError(f"{k} must be an int, got {type(v).__name__}")
        setattr(cfg, k, v)
    return cfg
