"""Drop-in clustering namespace mirroring ``pyspark.ml.clustering``."""

from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel  # noqa: F401

__all__ = ["KMeans", "KMeansModel"]
