"""Drop-in clustering namespace mirroring ``pyspark.ml.clustering`` (plus
``DBSCAN``, which spark-rapids-ml exposes from its clustering module)."""

from spark_rapids_ml_tpu.models.dbscan import DBSCAN, DBSCANModel  # noqa: F401
from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel  # noqa: F401

__all__ = ["DBSCAN", "DBSCANModel", "KMeans", "KMeansModel"]
