"""IVF-Flat approximate k-NN device kernels.

The spark-rapids-ml family exposes ``approximate_nearest_neighbors`` with
cuML's ivfflat algorithm: cluster the corpus (KMeans), store each
cluster's members contiguously, and answer queries by scanning only the
``nprobe`` nearest clusters. This module is that algorithm TPU-first:

- the coarse quantizer IS this package's KMeans (ops/kmeans.py);
- cluster buckets are a dense padded [nlist, cap, n] tensor with a
  validity mask — XLA-friendly static shapes instead of CSR indirection.
  ``cap`` is a *percentile* of the cluster sizes (TPU_ML_ANN_CAP_PERCENTILE,
  default 99), not the largest cluster: one hot cluster no longer inflates
  the whole tensor. Members beyond the cap land on an exact **spill list**
  that every query scans unconditionally — nothing is ever dropped, so
  recall loss comes only from probing, never from indexing;
- search probes clusters one at a time under a Python-static ``nprobe``
  loop, blocked over query rows: each step gathers the probed buckets for
  one query tile ([block, cap, n] — the tile stays cache/VMEM-resident
  across its scoring, instead of one monolithic [q, cap, n] gather round-
  tripping through memory) and scores it with a batched matmul
  (``einsum('qn,qcn->qc')``), merging into a running top-k with the same
  tournament primitive exact k-NN uses (ops/neighbors.merge_topk). The
  spill list is scored with one reused [q, n]×[n, spill] MXU matmul;
- the distance cross terms honor the autotune ``PrecisionPolicy``
  vocabulary exactly like exact k-NN (ops/neighbors._block_scores):
  ``bf16_f32acc`` casts operands to bfloat16 with f32 MXU accumulation,
  ``int8_dist`` runs the symmetric per-tensor int8 quantized cross term.
  Norms always stay full precision. Observed parity vs the f32 kernel on
  unit-scale data: bf16 distances agree to ~1e-2 relative, int8 to ~5e-2
  (tests/test_ivf.py pins both tolerances).

Honest TPU note (why the default stays exact brute force): the MXU makes
the full [q, rows] distance matmul so cheap that IVF's flop savings only
beat the gather overhead at large corpus sizes; below that, exact k-NN is
both faster AND exact. ivfflat is here for API + recall parity with the
reference family, and because at ~10⁷+ rows the memory story flips.

With ``nprobe == nlist`` every cluster (and the spill list) is scanned,
so f32 results must equal exact brute-force k-NN (the tests assert this).
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from spark_rapids_ml_tpu.autotune.policy import PrecisionPolicy
from spark_rapids_ml_tpu.ops.linalg import (
    DEFAULT_PRECISION,
    DEFAULT_POLICY,
    int8_quantized_matmul,
    policy_matmul,
)
from spark_rapids_ml_tpu.ops.neighbors import merge_topk
from spark_rapids_ml_tpu.utils import knobs

ANN_CAP_PERCENTILE_VAR = knobs.ANN_CAP_PERCENTILE.name

# query rows per probe-scan tile: the gathered [block, cap, n] slab plus
# its [block, cap] scores stay cache/VMEM-resident through the cross term
# and merge, and 128 rows keeps the MXU tile shape happy
_SCAN_BLOCK_ROWS = 128


class IvfBuckets(NamedTuple):
    """One packed IVF index: dense per-cluster buckets + exact spill list.

    ``bucket_ids``/``spill_ids`` hold 0-based global item positions with
    −1 on padding slots. ``spill_items`` is [spill_pad, n] (zero rows when
    no cluster overflowed its cap) and is scanned by every query — spilled
    members cost one reused matmul, not a recall hole.
    """

    bucket_items: np.ndarray  # [nlist, cap, n]
    bucket_ids: np.ndarray    # [nlist, cap] int32, −1 = pad
    cap: int
    spill_items: np.ndarray   # [spill_pad, n]
    spill_ids: np.ndarray     # [spill_pad] int32, −1 = pad


def bucket_cap(counts: np.ndarray, cap_percentile: float) -> int:
    """The dense-bucket capacity for observed cluster sizes: the
    ``cap_percentile``-th percentile (ceil), floored at 1. 100 degenerates
    to the legacy pad-to-largest-cluster packing (empty spill)."""
    if not 0.0 < cap_percentile <= 100.0:
        raise ValueError(
            f"cap_percentile={cap_percentile} must be in (0, 100]"
        )
    if cap_percentile >= 100.0:
        return max(1, int(counts.max()))
    return max(1, int(np.ceil(np.percentile(counts, cap_percentile))))


def build_ivf_buckets(
    items: np.ndarray, labels: np.ndarray, nlist: int,
    *, cap_percentile: float | None = None,
) -> IvfBuckets:
    """Host-side packing of an assigned corpus into :class:`IvfBuckets`.

    Every item is stored — the first ``cap`` members of each cluster (in
    stable corpus order) fill the dense [nlist, cap, n] tensor; overflow
    beyond the cap goes to the spill list, padded to a power of two so
    rebuilt indexes of similar skew reuse compiled search programs. With
    the default 99th-percentile cap a single hot cluster costs O(its own
    size) spill rows instead of inflating every bucket (the former
    cap = largest-cluster packing made a 100:1-skewed corpus allocate
    ~100x the corpus footprint in padding).
    """
    if cap_percentile is None:
        cap_percentile = float(
            os.environ.get(
                ANN_CAP_PERCENTILE_VAR, knobs.ANN_CAP_PERCENTILE.default
            )
        )
    counts = np.bincount(labels, minlength=nlist)
    cap = bucket_cap(counts, cap_percentile)
    n = items.shape[1]
    bucket_items = np.zeros((nlist, cap, n), dtype=items.dtype)
    bucket_ids = np.full((nlist, cap), -1, dtype=np.int32)
    # fully vectorized packing (no per-item Python at the 10⁷-row scale
    # this index targets): sort by label, position = rank within cluster
    order = np.argsort(labels, kind="stable")
    sorted_labels = labels[order]
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(order)) - starts[sorted_labels]
    dense = pos < cap
    bucket_items[sorted_labels[dense], pos[dense]] = items[order[dense]]
    bucket_ids[sorted_labels[dense], pos[dense]] = order[dense]
    spill = order[~dense]
    spill_pad = 0 if spill.size == 0 else 1 << (int(spill.size) - 1).bit_length()
    spill_items = np.zeros((spill_pad, n), dtype=items.dtype)
    spill_ids = np.full(spill_pad, -1, dtype=np.int32)
    spill_items[: spill.size] = items[spill]
    spill_ids[: spill.size] = spill
    return IvfBuckets(bucket_items, bucket_ids, cap, spill_items, spill_ids)


def _policy_cross(a, b_t, precision, policy):
    """[q, m] cross term ``a @ b_t`` under the precision policy (the 2-D
    dispatch exact k-NN uses; norms never come through here)."""
    if policy == PrecisionPolicy.INT8_DIST.value:
        return int8_quantized_matmul(a, b_t)
    return policy_matmul(a, b_t, precision=precision, policy=policy)


def _policy_bucket_cross(queries, xj, precision, policy):
    """[q, cap] batched cross term ``einsum('qn,qcn->qc')`` under the
    precision policy — the probe-step analog of :func:`_policy_cross`."""
    if policy == PrecisionPolicy.INT8_DIST.value:
        def quant(t):
            amax = jnp.max(jnp.abs(t))
            scale = jnp.where(amax > 0, amax / 127.0, jnp.ones_like(amax))
            q = jnp.clip(jnp.round(t / scale), -127.0, 127.0)
            return q.astype(jnp.int8), scale
        qq, sq = quant(queries)
        qx, sx = quant(xj)
        acc = jnp.einsum(
            "qn,qcn->qc", qq, qx, preferred_element_type=jnp.int32
        )
        return acc.astype(queries.dtype) * (sq * sx)
    if policy == PrecisionPolicy.BF16_F32ACC.value:
        out = jnp.einsum(
            "qn,qcn->qc",
            queries.astype(jnp.bfloat16),
            xj.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return out.astype(queries.dtype)
    return jnp.einsum("qn,qcn->qc", queries, xj, precision=precision)


@partial(jax.jit, static_argnames=("k", "nprobe", "policy"))
def ivf_search(
    queries: jax.Array,  # [q, n]
    centroids: jax.Array,  # [nlist, n]
    bucket_items: jax.Array,  # [nlist, cap, n]
    bucket_ids: jax.Array,  # [nlist, cap] int32, −1 = pad
    k: int,
    nprobe: int,
    *,
    spill_items: jax.Array | None = None,  # [spill_pad, n]
    spill_ids: jax.Array | None = None,  # [spill_pad] int32, −1 = pad
    precision=DEFAULT_PRECISION,
    policy: str = DEFAULT_POLICY,
) -> tuple[jax.Array, jax.Array]:
    """(scores [q, k] descending −‖·‖², global ids [q, k]) over the
    ``nprobe`` nearest clusters per query, plus the whole spill list."""
    q, n = queries.shape
    nlist, cap = bucket_ids.shape
    nprobe = min(nprobe, nlist)

    # coarse pass: one [q, nlist] MXU matmul picks the probe set
    q_sq = jnp.sum(queries * queries, axis=1, keepdims=True)
    c_sq = jnp.sum(centroids * centroids, axis=1)[None, :]
    cd = q_sq + c_sq - 2.0 * _policy_cross(
        queries, centroids.T, precision, policy
    )
    _, probe = lax.top_k(-cd, nprobe)  # [q, nprobe]

    neg_inf = jnp.asarray(-jnp.inf, queries.dtype)

    # probe scan, blocked over queries: one monolithic [q, cap, n] gather
    # forces the whole gathered tensor through memory before the scoring
    # einsum can start; a [block, cap, n] tile instead stays cache/VMEM-
    # resident across its cross term, norms, and top-k merge (measured ~4x
    # on the scoring path at q=2048, cap=256). Blocking only partitions
    # query rows — every query still merges its probes in the same order
    # with the same values, so results are bit-identical to the unblocked
    # formulation.
    block = min(_SCAN_BLOCK_ROWS, q)
    n_blocks = -(-q // block)
    qpad = n_blocks * block
    pad = qpad - q

    def pad_rows(a):
        return jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))

    def block_step(_, args):
        qg, probeg, q_sqg = args  # [block, n], [block, nprobe], [block, 1]
        best = jnp.full((block, k), neg_inf, queries.dtype)
        bidx = jnp.full((block, k), jnp.int32(-1))

        def step(carry, j):
            best, bidx = carry
            cluster = probeg[:, j]  # [block]
            xj = bucket_items[cluster]  # [block, cap, n] gather
            ids = bucket_ids[cluster]  # [block, cap]
            cross = _policy_bucket_cross(qg, xj, precision, policy)
            x_sq = jnp.sum(xj * xj, axis=2)
            scores = -(q_sqg + x_sq - 2.0 * cross)
            scores = jnp.where(ids >= 0, scores, neg_inf)
            return merge_topk(best, bidx, scores, ids, k), None

        (best, bidx), _ = lax.scan(
            step, (best, bidx), jnp.arange(nprobe)
        )
        return None, (best, bidx)

    _, (best, bidx) = lax.scan(
        block_step,
        None,
        (
            pad_rows(queries).reshape(n_blocks, block, n),
            pad_rows(probe).reshape(n_blocks, block, nprobe),
            pad_rows(q_sq).reshape(n_blocks, block, 1),
        ),
    )
    best = best.reshape(qpad, k)[:q]
    bidx = bidx.reshape(qpad, k)[:q]

    # exact spill tail: overflowed members ride one reused [q, spill]
    # matmul per batch — cheap precisely because it has cross-query reuse,
    # unlike the per-query bucket gathers above
    if spill_items is not None and spill_items.shape[0] > 0:
        s_sq = jnp.sum(spill_items * spill_items, axis=1)[None, :]
        cross = _policy_cross(queries, spill_items.T, precision, policy)
        scores = -(q_sq + s_sq - 2.0 * cross)
        scores = jnp.where(spill_ids[None, :] >= 0, scores, neg_inf)
        ids = jnp.broadcast_to(spill_ids[None, :], scores.shape)
        best, bidx = merge_topk(best, bidx, scores, ids, k)
    return best, bidx
