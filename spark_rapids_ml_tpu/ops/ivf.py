"""IVF-Flat approximate k-NN device kernels.

The spark-rapids-ml family exposes ``approximate_nearest_neighbors`` with
cuML's ivfflat algorithm: cluster the corpus (KMeans), store each
cluster's members contiguously, and answer queries by scanning only the
``nprobe`` nearest clusters. This module is that algorithm TPU-first:

- the coarse quantizer IS this package's KMeans (ops/kmeans.py);
- cluster buckets are a dense padded [nlist, cap, n] tensor (cap = largest
  cluster) with a validity mask — XLA-friendly static shapes instead of
  CSR indirection;
- search probes clusters one at a time under a Python-static ``nprobe``
  loop: each step gathers the probed bucket per query ([q, cap, n], one
  HBM gather) and scores it with a batched matmul
  (``einsum('qn,qcn->qc')``), merging into a running top-k with the same
  tournament primitive exact k-NN uses (ops/neighbors.merge_topk).

Honest TPU note (why the default stays exact brute force): the MXU makes
the full [q, rows] distance matmul so cheap that IVF's flop savings only
beat the gather overhead at large corpus sizes; below that, exact k-NN is
both faster AND exact. ivfflat is here for API + recall parity with the
reference family, and because at ~10⁷+ rows the memory story flips.

With ``nprobe == nlist`` every cluster is scanned, so results must equal
exact brute-force k-NN bit-for-bit (the tests assert this).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from spark_rapids_ml_tpu.ops.linalg import DEFAULT_PRECISION
from spark_rapids_ml_tpu.ops.neighbors import merge_topk


def build_ivf_buckets(
    items: np.ndarray, labels: np.ndarray, nlist: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side packing: (bucket_items [nlist, cap, n], bucket_ids
    [nlist, cap] int32 positional ids (−1 pad), cap = largest cluster).
    Every item is stored — nothing is dropped, so recall loss comes only
    from probing, never from indexing."""
    counts = np.bincount(labels, minlength=nlist)
    cap = max(1, int(counts.max()))
    n = items.shape[1]
    bucket_items = np.zeros((nlist, cap, n), dtype=items.dtype)
    bucket_ids = np.full((nlist, cap), -1, dtype=np.int32)
    # fully vectorized packing (no per-item Python at the 10⁷-row scale
    # this index targets): sort by label, position = rank within cluster
    order = np.argsort(labels, kind="stable")
    sorted_labels = labels[order]
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(order)) - starts[sorted_labels]
    bucket_items[sorted_labels, pos] = items[order]
    bucket_ids[sorted_labels, pos] = order
    return bucket_items, bucket_ids, cap


@partial(jax.jit, static_argnames=("k", "nprobe"))
def ivf_search(
    queries: jax.Array,  # [q, n]
    centroids: jax.Array,  # [nlist, n]
    bucket_items: jax.Array,  # [nlist, cap, n]
    bucket_ids: jax.Array,  # [nlist, cap] int32, −1 = pad
    k: int,
    nprobe: int,
    *,
    precision=DEFAULT_PRECISION,
) -> tuple[jax.Array, jax.Array]:
    """(scores [q, k] descending −‖·‖², global ids [q, k]) over the
    ``nprobe`` nearest clusters per query."""
    q, n = queries.shape
    nlist, cap = bucket_ids.shape
    nprobe = min(nprobe, nlist)

    # coarse pass: one [q, nlist] MXU matmul picks the probe set
    q_sq = jnp.sum(queries * queries, axis=1, keepdims=True)
    c_sq = jnp.sum(centroids * centroids, axis=1)[None, :]
    cd = q_sq + c_sq - 2.0 * jnp.matmul(
        queries, centroids.T, precision=precision
    )
    _, probe = lax.top_k(-cd, nprobe)  # [q, nprobe]

    neg_inf = jnp.asarray(-jnp.inf, queries.dtype)
    best = jnp.full((q, k), neg_inf, queries.dtype)
    bidx = jnp.full((q, k), jnp.int32(-1))

    def step(carry, j):
        best, bidx = carry
        cluster = probe[:, j]  # [q]
        xj = bucket_items[cluster]  # [q, cap, n] gather
        ids = bucket_ids[cluster]  # [q, cap]
        cross = jnp.einsum(
            "qn,qcn->qc", queries, xj, precision=precision
        )
        x_sq = jnp.sum(xj * xj, axis=2)
        scores = -(q_sq + x_sq - 2.0 * cross)
        scores = jnp.where(ids >= 0, scores, neg_inf)
        return merge_topk(best, bidx, scores, ids, k), None

    (best, bidx), _ = lax.scan(
        step, (best, bidx), jnp.arange(nprobe)
    )
    return best, bidx
