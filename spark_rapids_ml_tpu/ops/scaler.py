"""Feature scaling kernels — StandardScaler / Normalizer device math.

BASELINE.json config 4: "StandardScaler / Normalizer preprocessing fused into
the PCA input pipeline". Statistics follow the same partition-monoid design
as PCA's GramStats: per-partition moments combine across partitions, so the
same reducers (tree-aggregate or mesh psum) apply. All transforms are pure
elementwise/matmul-free kernels XLA fuses into adjacent ops — which is what
"fused into the PCA input pipeline" means here: standardize + Gram compile
into one program with no extra HBM round-trip.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp


class MomentStats(NamedTuple):
    """Per-feature first/second moments — a commutative monoid like GramStats."""

    count: jax.Array  # []
    total: jax.Array  # [n]  — per-feature sums
    total_sq: jax.Array  # [n]  — per-feature sums of squares


def moment_stats(x: jax.Array) -> MomentStats:
    return MomentStats(
        count=jnp.asarray(x.shape[0], x.dtype),
        total=jnp.sum(x, axis=0),
        total_sq=jnp.sum(x * x, axis=0),
    )


def combine_moment_stats(a: MomentStats, b: MomentStats) -> MomentStats:
    return MomentStats(a.count + b.count, a.total + b.total, a.total_sq + b.total_sq)


def moment_stats_weighted(x: jax.Array, w: jax.Array) -> MomentStats:
    """MomentStats under the masking convention (``w``: instance weights on
    true rows, 0.0 on pads) — the count is the weight sum, so padded chunks
    reduce exactly. Unit weights reproduce :func:`moment_stats` of the
    zero-padded block bit-for-bit apart from the count fix-up."""
    xw = x * w[:, None]
    return MomentStats(
        count=jnp.sum(w),
        total=jnp.sum(xw, axis=0),
        total_sq=jnp.sum(xw * x, axis=0),
    )


def fold_moment_stats(
    carry: MomentStats, x: jax.Array, w: jax.Array
) -> MomentStats:
    """One streamed-fit fold step: carry + weighted moments of one chunk."""
    return combine_moment_stats(carry, moment_stats_weighted(x, w))


@lru_cache(maxsize=None)
def moment_fold_step():
    """Cached jitted fold with the carry donated (no per-chunk [n] realloc);
    dispatch returns immediately, so chunk ingest overlaps the device fold
    (ops.linalg.gram_fold_step rationale)."""
    return jax.jit(fold_moment_stats, donate_argnums=0)


def init_moment_carry(n: int, dtype) -> MomentStats:
    """Zero device-resident MomentStats carry for :func:`moment_fold_step`."""
    return MomentStats(
        count=jnp.zeros((), dtype),
        total=jnp.zeros((n,), dtype),
        total_sq=jnp.zeros((n,), dtype),
    )


def finalize_moments(stats: MomentStats) -> tuple[jax.Array, jax.Array]:
    """(mean, sample std) from reduced moments.

    Sample (n−1) variance to match Spark MLlib's StandardScaler; variance is
    clipped at zero against catastrophic cancellation on constant features.
    """
    count = jnp.maximum(stats.count, 1)
    mean = stats.total / count
    denom = jnp.maximum(count - 1, 1)
    var = jnp.clip((stats.total_sq - count * mean * mean) / denom, 0.0, None)
    return mean, jnp.sqrt(var)


def standardize(
    x: jax.Array,
    mean: jax.Array,
    std: jax.Array,
    *,
    with_mean: bool = False,
    with_std: bool = True,
) -> jax.Array:
    """(x − μ)/σ with Spark's flag semantics (withMean default false there);
    zero-variance features pass through unscaled rather than dividing by 0."""
    if with_mean:
        x = x - mean[None, :]
    if with_std:
        safe = jnp.where(std > 0, std, jnp.ones_like(std))
        x = x / safe[None, :]
    return x


def normalize(x: jax.Array, p: float = 2.0) -> jax.Array:
    """Row-wise p-normalization (Spark Normalizer semantics, p ≥ 1):
    rows with zero norm are left untouched."""
    if p == float("inf"):
        norms = jnp.max(jnp.abs(x), axis=1)
    else:
        norms = jnp.sum(jnp.abs(x) ** p, axis=1) ** (1.0 / p)
    safe = jnp.where(norms > 0, norms, jnp.ones_like(norms))
    return x / safe[:, None]


class RangeStats(NamedTuple):
    """Per-feature min / max / max-|x| — the monoid behind MinMaxScaler and
    MaxAbsScaler (Spark computes the same summary via MultivariateOnlineSummarizer;
    here it is one masked reduction per shard + an elementwise combine)."""

    count: jax.Array  # []
    min: jax.Array  # [n]
    max: jax.Array  # [n]
    max_abs: jax.Array  # [n]


def range_stats(
    x: jax.Array,
    true_rows: jax.Array | None = None,
    *,
    valid: jax.Array | None = None,
) -> RangeStats:
    """Masked per-feature min/max/max-|x| — ONE masking convention for both
    mask shapes the framework uses: a row-prefix count (``true_rows``, the
    partition-task shape) or an explicit [rows, 1]/[rows, n] ``valid`` mask
    (the mesh path's weight-derived pad mask). Masked entries go to ±inf
    (and 0 for max-|x|) so they can never clamp the fold."""
    if valid is None:
        valid = (jnp.arange(x.shape[0]) < true_rows)[:, None]
        count = jnp.asarray(true_rows, x.dtype)
    else:
        if valid.ndim == 1:
            valid = valid[:, None]
        count = jnp.sum(jnp.any(valid, axis=1)).astype(x.dtype)
    inf = jnp.asarray(jnp.inf, x.dtype)
    return RangeStats(
        count=count,
        min=jnp.min(jnp.where(valid, x, inf), axis=0),
        max=jnp.max(jnp.where(valid, x, -inf), axis=0),
        max_abs=jnp.max(jnp.where(valid, jnp.abs(x), 0.0), axis=0),
    )


def combine_range_stats(a: RangeStats, b: RangeStats) -> RangeStats:
    return RangeStats(
        a.count + b.count,
        jnp.minimum(a.min, b.min),
        jnp.maximum(a.max, b.max),
        jnp.maximum(a.max_abs, b.max_abs),
    )


def minmax_scale(
    x: jax.Array,
    original_min: jax.Array,
    original_max: jax.Array,
    lo: float,
    hi: float,
) -> jax.Array:
    """Spark MinMaxScalerModel semantics: rescale each feature's observed
    [E_min, E_max] onto [lo, hi]; a constant feature (zero range) maps to
    the midpoint 0.5*(lo+hi)."""
    span = original_max - original_min
    safe = jnp.where(span != 0, span, 1.0)
    raw = jnp.where(span != 0, (x - original_min) / safe, 0.5)
    return raw * (hi - lo) + lo


def maxabs_scale(x: jax.Array, max_abs: jax.Array) -> jax.Array:
    """Spark MaxAbsScalerModel semantics: divide by max |x| per feature
    (all-zero features pass through unscaled), landing in [-1, 1]."""
    return x / jnp.where(max_abs != 0, max_abs, 1.0)


def binarize(x: jax.Array, *, threshold: float = 0.0) -> jax.Array:
    """1.0 where x > threshold else 0.0 (Spark Binarizer's strict >)."""
    return jnp.where(x > threshold, 1.0, 0.0).astype(x.dtype)


def histogram_stats(
    x: jax.Array,
    true_rows: jax.Array,
    mins: jax.Array,
    maxs: jax.Array,
    *,
    bins: int,
    valid: jax.Array | None = None,
) -> jax.Array:
    """Per-feature fixed-bin histogram over [mins, maxs] — the additive
    monoid behind RobustScaler's distributed quantiles. TPU-shaped: the
    per-column count is one ``bincount`` (scatter-add); pad rows route to
    an overflow bin that is dropped, so zero pads never count.

    Returns [n, bins] counts. Quantile resolution is the bin width
    (range/bins) — a VALUE-resolution sketch, vs Spark's rank-error
    QuantileSummaries; at the default 4096 bins the error is ≤ 0.025% of
    the feature's range.
    """
    rows, n = x.shape
    mask = jnp.arange(rows) < true_rows
    width = (maxs - mins) / bins
    safe_w = jnp.where(width > 0, width, 1.0)
    idx = jnp.clip((x - mins[None, :]) / safe_w[None, :], 0, bins - 1).astype(
        jnp.int32
    )
    if valid is None:
        valid = jnp.ones(x.shape, dtype=bool)

    def col_hist(col_idx, col_valid):
        # pads AND invalid entries -> overflow bin (dropped)
        routed = jnp.where(mask & col_valid, col_idx, bins)
        return jnp.bincount(routed, length=bins + 1)[:bins]

    return jax.vmap(col_hist, in_axes=(1, 1))(idx, valid)


def quantile_from_histogram(
    hist: jax.Array, mins: jax.Array, maxs: jax.Array, q: float
) -> jax.Array:
    """Per-feature q-quantile from accumulated [n, bins] histograms with
    linear interpolation inside the selected bin. Zero-range (constant)
    features return their min exactly (width 0)."""
    counts = hist.astype(mins.dtype)
    bins = hist.shape[1]
    total = counts.sum(axis=1)
    cum = jnp.cumsum(counts, axis=1)
    target = q * total
    ge = cum >= target[:, None] - 1e-9
    bin_idx = jnp.argmax(ge, axis=1)
    take = lambda a, i: jnp.take_along_axis(a, i[:, None], axis=1)[:, 0]
    cum_before = jnp.where(bin_idx > 0, take(cum, jnp.maximum(bin_idx - 1, 0)), 0.0)
    in_bin = take(counts, bin_idx)
    frac = jnp.clip(
        (target - cum_before) / jnp.maximum(in_bin, 1.0), 0.0, 1.0
    )
    width = (maxs - mins) / bins
    return mins + (bin_idx.astype(mins.dtype) + frac) * width


def robust_scale(
    x: jax.Array,
    median: jax.Array,
    qrange: jax.Array,
    *,
    with_centering: bool,
    with_scaling: bool,
) -> jax.Array:
    """(x − median?) / range? — constant features (zero quantile range)
    pass through unscaled (divide by 1), the sklearn convention, chosen
    over a silent zero-out so information is never destroyed."""
    out = x
    if with_centering:
        out = out - median[None, :]
    if with_scaling:
        out = out / jnp.where(qrange > 0, qrange, 1.0)[None, :]
    return out


class NanMomentStats(NamedTuple):
    """Per-feature NaN-aware moments: the Imputer's mean-strategy monoid
    (missing entries contribute to neither sum nor count)."""

    count: jax.Array  # [n] — VALID entries per feature
    total: jax.Array  # [n] — sum over valid entries


def nan_moment_stats(
    x: jax.Array, true_rows: jax.Array, missing: float
) -> NanMomentStats:
    """Moments over entries that are present (row < true_rows) and not
    ``missing`` — ONE validity predicate (:func:`valid_mask`) shared with
    the median path so the strategies can never diverge on what counts as
    missing."""
    valid = valid_mask(x, true_rows, missing)
    xz = jnp.where(valid, x, 0.0)
    return NanMomentStats(
        count=jnp.sum(valid, axis=0).astype(x.dtype),
        total=jnp.sum(xz, axis=0),
    )


def combine_nan_moment_stats(a: NanMomentStats, b: NanMomentStats) -> NanMomentStats:
    return NanMomentStats(a.count + b.count, a.total + b.total)


def _is_missing(x: jax.Array, missing: float) -> jax.Array:
    """Elementwise missing-sentinel predicate (NaN via isnan, else ==) —
    the single definition every Imputer kernel shares."""
    return jnp.isnan(x) if missing != missing else x == missing


def impute(x: jax.Array, fill: jax.Array, missing: float) -> jax.Array:
    """Replace missing entries with the per-feature fill value."""
    return jnp.where(_is_missing(x, missing), fill[None, :], x)


class NanRangeStats(NamedTuple):
    """NaN-aware min/max + valid counts — the Imputer's median-strategy
    range pass (missing entries must not clamp the bounds)."""

    count: jax.Array  # [n] valid entries per feature
    min: jax.Array  # [n]
    max: jax.Array  # [n]


def valid_mask(x: jax.Array, true_rows: jax.Array, missing: float) -> jax.Array:
    """[rows, n] bool: present (row < true_rows) and not the missing
    sentinel (:func:`_is_missing`)."""
    row_ok = (jnp.arange(x.shape[0]) < true_rows)[:, None]
    return row_ok & ~_is_missing(x, missing)


def nan_range_stats(
    x: jax.Array, true_rows: jax.Array, missing: float
) -> NanRangeStats:
    valid = valid_mask(x, true_rows, missing)
    inf = jnp.asarray(jnp.inf, x.dtype)
    return NanRangeStats(
        count=jnp.sum(valid, axis=0).astype(x.dtype),
        min=jnp.min(jnp.where(valid, x, inf), axis=0),
        max=jnp.max(jnp.where(valid, x, -inf), axis=0),
    )


def combine_nan_range_stats(a: NanRangeStats, b: NanRangeStats) -> NanRangeStats:
    return NanRangeStats(
        a.count + b.count,
        jnp.minimum(a.min, b.min),
        jnp.maximum(a.max, b.max),
    )


def bucketize(x: jax.Array, splits: jax.Array) -> jax.Array:
    """Per-feature bucket ids from sorted split points.

    ``splits`` is [n, b+1] (±inf endpoints make every value in-range);
    bucket i is [splits[i], splits[i+1]) with the top edge inclusive
    (Spark Bucketizer's rule). Duplicate split points (collapsed
    quantiles on skewed data) yield empty buckets, never invalid ids.
    Output dtype follows x (Spark emits the id as a double).
    """

    def col(colx, cols):
        idx = jnp.searchsorted(cols, colx, side="right") - 1
        return jnp.clip(idx, 0, cols.shape[0] - 2)

    return jax.vmap(col, in_axes=(1, 0), out_axes=1)(x, splits).astype(x.dtype)


def dct2_matrix(n: int, dtype=jnp.float64) -> jax.Array:
    """The unitary DCT-II basis [n, n] (Spark DCT semantics: DCT-II scaled
    so the representing matrix is orthonormal — scipy's ``norm='ortho'``).
    Materialized once per n; the transform is then one MXU matmul."""
    k = jnp.arange(n, dtype=dtype)
    basis = jnp.cos(jnp.pi * (2.0 * k[None, :] + 1.0) * k[:, None] / (2.0 * n))
    scale = jnp.full((n,), jnp.sqrt(2.0 / n), dtype=dtype).at[0].set(
        jnp.sqrt(1.0 / n)
    )
    return basis * scale[:, None]


def dct2(x: jax.Array, basis: jax.Array, *, inverse: bool = False) -> jax.Array:
    """Row-wise unitary DCT-II (or its inverse, DCT-III) as one matmul."""
    return x @ (basis if inverse else basis.T)
