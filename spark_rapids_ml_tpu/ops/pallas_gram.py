"""Pallas TPU kernel: fused split-precision Gram + feature moments.

The hot op of the whole framework is the Gram pass (SURVEY.md §3.1 "HOT
LOOP 1"). This kernel makes one HBM read of X produce, in a single pass:

- ``gram``    = XᵀX accumulated in f32 via the **bf16 split trick**: X is
  decomposed as hi + lo (two bf16s ≈ 16 mantissa bits); XᵀX ≈ hiᵀhi + hiᵀlo
  + loᵀhi — three MXU passes at full bf16 throughput, ~2× the FLOP cost of
  one pass instead of the 6× that f32 ``Precision.HIGHEST`` pays, with
  near-f32 accuracy (the dropped loᵀlo term is ~2⁻³² relative).
- ``col_sum`` and ``sum_sq`` — the mean-centering statistic PCA needs and
  the variance statistic StandardScaler needs. This is BASELINE config 4's
  "scaler fused into the PCA input pipeline" delivered at the kernel level:
  fitting a standardize→PCA pipeline costs ONE data pass, not three.

Grid: (n/bn, n/bn, rows/br) with rows innermost, so each [bn, bn] output
tile stays resident in VMEM while row blocks stream through (the canonical
Pallas accumulation pattern); moments accumulate on the i==0 wavefront only.

Measured on v5e-1 (2M×512): 53 ms vs XLA's 38 ms for ``Precision.HIGHEST``
Gram+moments and 22 ms for ``Precision.HIGH`` (which applies this same
bf16-split decomposition with better stream scheduling — one X read per
column-block pair vs this kernel's two). ``symmetric_gram_moments`` below
fixes the HBM re-reads (1-D grid, whole accumulator VMEM-resident) and skips
the lower-triangle block pairs — measured 23.3 ms, a 1.43× win over this
kernel's formulation, but still behind XLA HIGH's 16.7 ms: the 37.5% flop
skip (n=512, 128-blocks) is outweighed by Mosaic reaching ~65% MXU
efficiency on the 3-dot tile loop where XLA's tuned gemm reaches ~100%. The
XLA paths therefore stay the production default in ops.linalg; these kernels
remain as the interpret-testable statement of the fused one-pass stats and
the measured record of the symmetric-skip experiment (the skip becomes
profitable if Mosaic's gemm pipelining improves or nt grows).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_CONTRACT_ROWS = (((0,), (0,)), ((), ()))  # aᵀb for row-major tiles


def _pad_and_split(x, block_rows, block_cols):
    """Shared kernel prologue: f32 cast, block padding, hi/lo bf16 split.

    Returns (hi, lo, n) where n is the pre-padding column count. Zero
    padding is exact for Gram/moment reductions; hi + lo carries ~16
    mantissa bits of the f32 input.
    """
    if x.dtype != jnp.float32:
        x = x.astype(jnp.float32)
    n = x.shape[1]
    pr = (-x.shape[0]) % block_rows
    pn = (-n) % block_cols
    if pr or pn:
        x = jnp.pad(x, ((0, pr), (0, pn)))
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo, n


def _trim(gram, colsum, sumsq, n):
    """Shared epilogue: drop the column padding from all three outputs."""
    if gram.shape[0] != n:
        gram = gram[:n, :n]
        colsum = colsum[:, :n]
        sumsq = sumsq[:, :n]
    return gram, colsum[0], sumsq[0]


def _fused_kernel(hi_i, lo_i, hi_j, lo_j, gram_ref, colsum_ref, sumsq_ref):
    i = pl.program_id(0)
    r = pl.program_id(2)

    @pl.when(r == 0)
    def _init_gram():
        gram_ref[:] = jnp.zeros_like(gram_ref)

    a_hi, a_lo = hi_i[:], lo_i[:]
    b_hi, b_lo = hi_j[:], lo_j[:]
    dot = partial(
        jax.lax.dot_general,
        dimension_numbers=_CONTRACT_ROWS,
        preferred_element_type=jnp.float32,
    )
    gram_ref[:] += dot(a_hi, b_hi) + dot(a_hi, b_lo) + dot(a_lo, b_hi)

    @pl.when(i == 0)
    def _moments():
        @pl.when(r == 0)
        def _init_moments():
            colsum_ref[:] = jnp.zeros_like(colsum_ref)
            sumsq_ref[:] = jnp.zeros_like(sumsq_ref)

        xb = b_hi.astype(jnp.float32) + b_lo.astype(jnp.float32)
        colsum_ref[:] += jnp.sum(xb, axis=0, keepdims=True)
        sumsq_ref[:] += jnp.sum(xb * xb, axis=0, keepdims=True)


def _symmetric_kernel(
    hi_ref, lo_ref, gram_ref, colsum_ref, sumsq_ref, *, nt, bc, n_rows
):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        gram_ref[:] = jnp.zeros_like(gram_ref)
        colsum_ref[:] = jnp.zeros_like(colsum_ref)
        sumsq_ref[:] = jnp.zeros_like(sumsq_ref)

    dot = partial(
        jax.lax.dot_general,
        dimension_numbers=_CONTRACT_ROWS,
        preferred_element_type=jnp.float32,
    )
    # Upper-triangle block pairs only: the flops XLA's full gemm wastes on
    # the mirrored lower half are simply never issued.
    for bi in range(nt):
        a_hi = hi_ref[:, bi * bc : (bi + 1) * bc]
        a_lo = lo_ref[:, bi * bc : (bi + 1) * bc]
        for bj in range(bi, nt):
            b_hi = hi_ref[:, bj * bc : (bj + 1) * bc]
            b_lo = lo_ref[:, bj * bc : (bj + 1) * bc]
            acc = dot(a_hi, b_hi) + dot(a_hi, b_lo) + dot(a_lo, b_hi)
            gram_ref[bi * bc : (bi + 1) * bc, bj * bc : (bj + 1) * bc] += acc

    xb = hi_ref[:].astype(jnp.float32) + lo_ref[:].astype(jnp.float32)
    colsum_ref[:] += jnp.sum(xb, axis=0, keepdims=True)
    sumsq_ref[:] += jnp.sum(xb * xb, axis=0, keepdims=True)

    # Last row block: mirror the strict upper blocks into the lower half.
    @pl.when(r == n_rows - 1)
    def _mirror():
        for bi in range(nt):
            for bj in range(bi + 1, nt):
                gram_ref[bj * bc : (bj + 1) * bc, bi * bc : (bi + 1) * bc] = (
                    gram_ref[bi * bc : (bi + 1) * bc, bj * bc : (bj + 1) * bc].T
                )


def symmetric_gram_moments(
    x: jax.Array,
    *,
    block_rows: int = 1024,
    block_cols: int = 128,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Symmetric one-wavefront (gram, col_sum, sum_sq) of a [rows, n] f32 X.

    The flops-skipping variant ``fused_gram_moments``'s docstring promises:

    - grid is 1-D over row blocks; the WHOLE [n, n] f32 accumulator plus the
      hi/lo bf16 row block stay VMEM-resident, so each X element is read
      from HBM exactly once (the (i, j, r) formulation re-reads each column
      block nt times — that made it HBM-bound and slower than XLA);
    - only upper-triangle block pairs are multiplied — nt(nt+1)/2 of nt²
      tiles, a 1.6-1.8× MXU-flop saving XLA's gemm cannot express since its
      output is not known-symmetric — with the lower half mirrored in VMEM
      on the final row block.

    Fits when the n×n f32 Gram + two bf16 row blocks fit VMEM: n ≤ ~1280 at
    the defaults. Callers gate on n and fall back to the XLA path above.
    """
    hi, lo, n = _pad_and_split(x, block_rows, block_cols)
    rows_p, n_p = hi.shape
    nt = n_p // block_cols
    n_row_blocks = rows_p // block_rows

    row_block = pl.BlockSpec((block_rows, n_p), lambda r: (r, 0))
    full_out = pl.BlockSpec((n_p, n_p), lambda r: (0, 0))
    moment_out = pl.BlockSpec((1, n_p), lambda r: (0, 0))

    gram, colsum, sumsq = pl.pallas_call(
        partial(
            _symmetric_kernel, nt=nt, bc=block_cols, n_rows=n_row_blocks
        ),
        grid=(n_row_blocks,),
        in_specs=[row_block, row_block],
        out_specs=(full_out, moment_out, moment_out),
        out_shape=(
            jax.ShapeDtypeStruct((n_p, n_p), jnp.float32),
            jax.ShapeDtypeStruct((1, n_p), jnp.float32),
            jax.ShapeDtypeStruct((1, n_p), jnp.float32),
        ),
        cost_estimate=pl.CostEstimate(
            flops=3 * rows_p * n_p * n_p * (nt + 1) // nt,  # 3·2·r·n²·(upper/total)
            bytes_accessed=2 * rows_p * n_p * 2 + n_p * n_p * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(hi, lo)

    return _trim(gram, colsum, sumsq, n)


def fused_gram_moments(
    x: jax.Array,
    *,
    block_rows: int = 1024,
    block_cols: int = 256,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One-pass (gram [n,n], col_sum [n], sum_sq [n]) of a [rows, n] f32 X.

    Zero-padding to block multiples is exact for all three reductions; the
    caller keeps true row counts (same contract as ops.linalg.GramStats).
    ``interpret=True`` runs the kernel on CPU for tests.
    """
    hi, lo, n = _pad_and_split(x, block_rows, block_cols)
    rows_p, n_p = hi.shape

    grid = (n_p // block_cols, n_p // block_cols, rows_p // block_rows)
    row_tile_i = pl.BlockSpec((block_rows, block_cols), lambda i, j, r: (r, i))
    row_tile_j = pl.BlockSpec((block_rows, block_cols), lambda i, j, r: (r, j))

    gram, colsum, sumsq = pl.pallas_call(
        _fused_kernel,
        grid=grid,
        in_specs=[row_tile_i, row_tile_i, row_tile_j, row_tile_j],
        out_specs=(
            pl.BlockSpec((block_cols, block_cols), lambda i, j, r: (i, j)),
            pl.BlockSpec((1, block_cols), lambda i, j, r: (0, j)),
            pl.BlockSpec((1, block_cols), lambda i, j, r: (0, j)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_p, n_p), jnp.float32),
            jax.ShapeDtypeStruct((1, n_p), jnp.float32),
            jax.ShapeDtypeStruct((1, n_p), jnp.float32),
        ),
        cost_estimate=pl.CostEstimate(
            flops=3 * 2 * rows_p * n_p * n_p,
            bytes_accessed=2 * rows_p * n_p * 2 * (n_p // block_cols) + n_p * n_p * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(hi, lo, hi, lo)

    return _trim(gram, colsum, sumsq, n)
