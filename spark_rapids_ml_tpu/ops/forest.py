"""Random-forest device kernels — histogram trees grown level-order on TPU.

The modern spark-rapids-ml family ships RandomForestClassifier/Regressor on
cuML's GPU forest builder; the 22.12 reference this framework re-designs
stops at PCA (SURVEY.md §2), so this is a capability-add in the
KMeans/NearestNeighbors/DBSCAN spirit — same Spark ML API surface,
TPU-native internals.

Why histogram trees, and why breadth-first:

- exact-split tree building (sort every feature at every node) is
  pointer-chasing — hostile to both the MXU and XLA's static shapes.
  Quantile-binned HISTOGRAM building (the XGBoost/LightGBM formulation,
  also what Spark MLlib itself does with maxBins) turns split finding into
  dense fixed-shape reductions;
- LEVEL-ORDER growth makes every depth a fixed-shape program: all 2^d
  nodes of a level build their [features, bins, stats] histograms in ONE
  segment-sum pass over the rows (segment id = node·B + bin), then split
  selection is a cumsum + argmax over a dense [F, nodes, B] gain tensor.
  No per-node recursion ever reaches XLA;
- the per-level histogram is a commutative monoid over rows — the mesh
  version (parallel/forest.py) psums it across row shards and every device
  takes identical split decisions, the same distribution shape as every
  other fit here (and as Spark MLlib's own RF aggregation).

Trees live in fixed heap-layout arrays (root 0, children 2i+1/2i+2, size
2^(maxDepth+1)−1): ``feature``/``split_bin`` per node, ``is_leaf``, and
``leaf_stats`` (class counts, or [w, wy, wy²] for regression) written for
every materialized node so prediction can stop at any depth. Rows carry
their current heap node; leaf rows go inactive (weight 0 in histograms).

Stats convention: classification S=C per-class weighted counts;
regression S=3 ([w, w·y, w·y²]). Impurities (gini/entropy/variance) are
computed in n-scaled form (n·impurity), where gain·n_total =
imp_n(parent) − imp_n(left) − imp_n(right) — no divisions until the gate.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

IMPURITIES = ("gini", "entropy", "variance")


class TreeArrays(NamedTuple):
    """One tree (or a [T, ...] stack) in heap layout."""

    feature: jax.Array  # [nodes] int32, −1 at leaves
    split_bin: jax.Array  # [nodes] int32 — go left when bin ≤ split_bin
    is_leaf: jax.Array  # [nodes] bool
    leaf_stats: jax.Array  # [nodes, S]
    gain: jax.Array  # [nodes] n-scaled impurity decrease at split nodes (0 at leaves) — feeds featureImportances


def _impurity_n(stats: jax.Array, impurity: str) -> jax.Array:
    """n·impurity over the trailing stats axis; 0 for empty cells."""
    if impurity == "variance":
        w = stats[..., 0]
        safe = jnp.where(w > 0, w, 1.0)
        v = stats[..., 2] - stats[..., 1] * stats[..., 1] / safe
        return jnp.where(w > 0, jnp.maximum(v, 0.0), 0.0)
    n = jnp.sum(stats, axis=-1)
    safe = jnp.where(n > 0, n, 1.0)
    if impurity == "gini":
        return jnp.where(
            n > 0, n - jnp.sum(stats * stats, axis=-1) / safe, 0.0
        )
    # entropy: Σ c·log(n/c) — 0·log(·) := 0
    c = stats
    ratio = jnp.where(c > 0, c / safe[..., None], 1.0)
    return jnp.where(n > 0, -safe * jnp.sum(ratio * jnp.log(ratio), axis=-1), 0.0)


def _node_count(stats: jax.Array, impurity: str) -> jax.Array:
    """Weighted instance count per cell from the stats vector."""
    return stats[..., 0] if impurity == "variance" else jnp.sum(stats, axis=-1)


@partial(
    jax.jit,
    static_argnames=(
        "max_depth", "n_bins", "k_features", "impurity", "axis_name",
    ),
)
def build_tree(
    key: jax.Array,
    binned: jax.Array,  # [rows, F] int32 bin ids in [0, n_bins)
    row_stats: jax.Array,  # [rows, S] per-row stats (UNweighted)
    w: jax.Array,  # [rows] bootstrap × instance weights (0 = excluded)
    min_instances: jax.Array,  # weighted count floor per child
    min_info_gain: jax.Array,
    *,
    max_depth: int,
    n_bins: int,
    k_features: int,
    impurity: str,
    axis_name: str | None = None,
) -> TreeArrays:
    """Grow one histogram tree level-order; fully jittable, fixed shapes.

    With ``axis_name`` set (mesh build), the per-level histogram and root
    total are psum'd over that axis — rows are sharded, decisions
    replicated. ``vmap`` over (key, w) grows a forest.
    """
    if impurity not in IMPURITIES:
        raise ValueError(f"impurity must be one of {IMPURITIES}")
    rows, n_feat = binned.shape
    S = row_stats.shape[1]
    max_nodes = 2 ** (max_depth + 1) - 1
    fdt = row_stats.dtype

    feature = jnp.full((max_nodes,), -1, jnp.int32)
    split_bin = jnp.zeros((max_nodes,), jnp.int32)
    is_leaf = jnp.ones((max_nodes,), bool)
    leaf_stats = jnp.zeros((max_nodes, S), fdt)
    gain = jnp.zeros((max_nodes,), fdt)

    node = jnp.zeros((rows,), jnp.int32)  # current heap node per row
    active = jnp.ones((rows,), bool)

    for d in range(max_depth + 1):
        nodes_d = 2 ** d
        offset = nodes_d - 1
        # inactive rows keep the stale heap id of the level they went leaf
        # at, so their local id is clipped into range — they contribute 0
        # to histograms (wa=0) and never route (active gates row_split)
        local = jnp.clip(node - offset, 0, nodes_d - 1)
        wa = jnp.where(active, w, 0.0)
        contrib = row_stats * wa[:, None]

        # [F, nodes_d·B, S] histograms in one vmapped segment-sum pass
        def hist_feature(bins_f):
            seg = local * n_bins + bins_f
            return jax.ops.segment_sum(
                contrib, seg, num_segments=nodes_d * n_bins
            )

        hist = jax.vmap(hist_feature)(binned.T)
        if axis_name is not None:
            hist = lax.psum(hist, axis_name)
        hist = hist.reshape(n_feat, nodes_d, n_bins, S)

        total = jnp.sum(hist[0], axis=1)  # [nodes_d, S]
        leaf_stats = lax.dynamic_update_slice(leaf_stats, total, (offset, 0))

        if d == max_depth:
            break  # depth-capped: this level is all leaves

        left = jnp.cumsum(hist, axis=2)  # [F, nodes_d, B, S]
        right = total[None, :, None, :] - left
        gain_n = (
            _impurity_n(total, impurity)[None, :, None]
            - _impurity_n(left, impurity)
            - _impurity_n(right, impurity)
        )
        n_tot = _node_count(total, impurity)  # [nodes_d]
        n_l = _node_count(left, impurity)
        n_r = _node_count(right, impurity)
        safe_tot = jnp.where(n_tot > 0, n_tot, 1.0)
        ok = (
            (n_l >= min_instances)
            & (n_r >= min_instances)
            & (gain_n / safe_tot[None, :, None] >= min_info_gain)
            & (gain_n > 1e-12)
        )
        # the last bin's "split" puts everything left — structurally invalid
        ok = ok & (jnp.arange(n_bins)[None, None, :] < n_bins - 1)

        if k_features < n_feat:
            # Spark's per-node feature subsampling: k distinct features per
            # node via Gumbel top-k (sampling without replacement)
            kd = jax.random.fold_in(key, d)
            g = jax.random.gumbel(kd, (nodes_d, n_feat), fdt)
            kth = lax.top_k(g, k_features)[0][:, -1]
            ok = ok & (g.T[:, :, None] >= kth[None, :, None])

        masked = jnp.where(ok, gain_n, -jnp.inf)
        flat = masked.transpose(1, 0, 2).reshape(nodes_d, n_feat * n_bins)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        best_f = (best // n_bins).astype(jnp.int32)
        best_b = (best % n_bins).astype(jnp.int32)
        do_split = best_gain > -jnp.inf  # [nodes_d]

        feature = lax.dynamic_update_slice(
            feature, jnp.where(do_split, best_f, -1), (offset,)
        )
        split_bin = lax.dynamic_update_slice(
            split_bin, jnp.where(do_split, best_b, 0), (offset,)
        )
        is_leaf = lax.dynamic_update_slice(is_leaf, ~do_split, (offset,))
        gain = lax.dynamic_update_slice(
            gain, jnp.where(do_split, best_gain, 0.0), (offset,)
        )

        # route rows: split nodes send rows to 2·node+1 (+1 if bin > b)
        row_split = active & do_split[local]
        rf = best_f[local]
        rb = best_b[local]
        row_bin = jnp.take_along_axis(binned, rf[:, None], axis=1)[:, 0]
        goes_right = (row_bin > rb).astype(jnp.int32)
        node = jnp.where(row_split, 2 * node + 1 + goes_right, node)
        active = active & row_split

    return TreeArrays(feature, split_bin, is_leaf, leaf_stats, gain)


def build_forest(
    keys: jax.Array,  # [T] PRNG keys (feature subsets)
    binned: jax.Array,
    row_stats: jax.Array,
    weights: jax.Array,  # [T, rows] per-tree bootstrap × instance weights
    min_instances,
    min_info_gain,
    **static,
) -> TreeArrays:
    """vmap :func:`build_tree` over trees → [T, ...] TreeArrays."""
    return jax.vmap(
        lambda k, w: build_tree(
            k, binned, row_stats, w, min_instances, min_info_gain, **static
        )
    )(keys, weights)


@partial(jax.jit, static_argnames=("max_depth",))
def tree_apply_binned(
    tree: TreeArrays,  # ONE tree (unstacked)
    binned: jax.Array,  # [rows, F] int32 bin ids
    *,
    max_depth: int,
) -> jax.Array:
    """[rows, S] leaf stats by descending on BIN ids (go left when
    bin ≤ split_bin) — the training-time router gradient boosting uses to
    update its running prediction without converting back to raw
    thresholds."""
    node = jnp.zeros((binned.shape[0],), jnp.int32)
    for _ in range(max_depth):
        leaf = tree.is_leaf[node]
        f = jnp.maximum(tree.feature[node], 0)
        b = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
        goes_right = (b > tree.split_bin[node]).astype(jnp.int32)
        node = jnp.where(leaf, node, 2 * node + 1 + goes_right)
    return tree.leaf_stats[node]


@partial(jax.jit, static_argnames=("max_depth",))
def forest_apply(
    trees: TreeArrays,  # [T, ...] stack
    x: jax.Array,  # [rows, F] RAW feature values
    thresholds: jax.Array,  # [T, nodes] split values (edges[f, b])
    *,
    max_depth: int,
) -> jax.Array:
    """[T, rows, S] leaf stats: descend every tree with gathers —
    ``max_depth`` dependent steps, each one vectorized gather+compare."""

    def one_tree(tree, thr):
        node = jnp.zeros((x.shape[0],), jnp.int32)
        for _ in range(max_depth):
            leaf = tree.is_leaf[node]
            f = jnp.maximum(tree.feature[node], 0)
            xv = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
            goes_right = (xv > thr[node]).astype(jnp.int32)
            node = jnp.where(leaf, node, 2 * node + 1 + goes_right)
        return tree.leaf_stats[node]

    return jax.vmap(one_tree)(trees, thresholds)
