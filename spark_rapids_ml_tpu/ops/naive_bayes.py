"""Naive Bayes sufficient statistics — one MXU pass, one monoid.

pyspark.ml's NaiveBayes (multinomial / bernoulli / gaussian) trains from
per-class reductions: weighted class counts + per-class feature sums
(one pass), and for gaussian a SECOND centered pass of squared
deviations against the reduced class means (``nb_centered_sq`` — the
numerically stable variance route). Each pass is one-hot matmuls — the
same onehotᵀ·X recast of scatter-by-label KMeans uses (ops/kmeans.py) —
and the stats tuple is a commutative monoid, so every reducer in this
framework (tree-aggregate, mesh psum) applies unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.linalg import DEFAULT_PRECISION


class NBStats(NamedTuple):
    counts: jax.Array  # [C]    — weighted class counts
    feat_sum: jax.Array  # [C, F] — weighted per-class feature sums


def combine_nb_stats(a: NBStats, b: NBStats) -> NBStats:
    return NBStats(*(av + bv for av, bv in zip(a, b)))


@partial(jax.jit, static_argnames=("n_classes",))
def nb_centered_sq(
    x: jax.Array,  # [rows, F]
    y: jax.Array,  # [rows] class indices
    w: jax.Array,  # [rows] weights (0 = pad)
    mu: jax.Array,  # [C, F] per-class means (replicated)
    n_classes: int,
    *,
    precision=DEFAULT_PRECISION,
) -> jax.Array:
    """[C, F] Σ w·(x − μ_class)² — the SECOND gaussian pass. Variance via
    squared deviations from the already-reduced class means is numerically
    stable where the one-pass Sq/N − μ² form cancels catastrophically on
    offset-heavy features (values ~1e8, spread ~1)."""
    yi = y.astype(jnp.int32)
    d = x - mu[jnp.clip(yi, 0, n_classes - 1)]
    onehot_w = (
        yi[:, None] == jnp.arange(n_classes, dtype=jnp.int32)[None, :]
    ).astype(x.dtype) * w[:, None]
    return jnp.matmul(onehot_w.T, d * d, precision=precision)


@partial(jax.jit, static_argnames=("n_classes",))
def nb_stats(
    x: jax.Array,  # [rows, F]
    y: jax.Array,  # [rows] class indices (float or int)
    w: jax.Array,  # [rows] instance weights (0 = pad/excluded)
    n_classes: int,
    *,
    precision=DEFAULT_PRECISION,
) -> NBStats:
    onehot_w = (
        y.astype(jnp.int32)[:, None]
        == jnp.arange(n_classes, dtype=jnp.int32)[None, :]
    ).astype(x.dtype) * w[:, None]
    return NBStats(
        counts=jnp.sum(onehot_w, axis=0),
        feat_sum=jnp.matmul(onehot_w.T, x, precision=precision),
    )
