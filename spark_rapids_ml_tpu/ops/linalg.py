"""Core PCA linear algebra as pure JAX kernels.

This module is the TPU-native re-design of the reference's device math:

- Gram/covariance accumulation  (reference: cuBLAS gemm in ``dgemmCov``,
  native/src/rapidsml_jni.cu:109-127)
- symmetric eigendecomposition with descending reorder + sqrt + sign-flip
  (reference: ``calSVD`` → raft::linalg::eigDC + colReverse/rowReverse +
  seqRoot + signFlip, native/src/rapidsml_jni.cu:215-269)
- batched projection for transform (reference: ``dgemm``,
  native/src/rapidsml_jni.cu:75-107)

Design notes (TPU-first, not a translation):

- The Gram pass is the hot loop (O(rows·n²) FLOPs) and is a single large
  matmul — exactly what the MXU wants. We default matmul precision to
  ``HIGHEST`` so f32 inputs use multi-pass bf16 on TPU, which is what lets an
  f32 accumulation meet the ≥0.9999 eigenvector cosine-sim bar vs an f64 CPU
  oracle without paying TPU-emulated f64 in the hot loop.
- Partition-local statistics are carried as a ``GramStats`` triple
  (XᵀX, column sums, row count) so mean-centering can be applied *after* the
  cross-partition reduction: (X-μ)ᵀ(X-μ) = XᵀX − s·sᵀ/count. The reference
  accepts a ``meanCentering`` param but never implements it (TODO stub at
  RapidsRowMatrix.scala:111-117); we implement it for real and keep the
  uncentered Gram path for behavioral parity.
- The n×n eigh is negligible next to the Gram pass, runs once, and stays on
  device via ``jnp.linalg.eigh`` — no hand-written solver needed on TPU.

Numerical semantics preserved exactly from the reference (SURVEY.md §3.1):
descending eigenvalue order, singular values = √λ, explainedVariance =
sᵢ/Σs over the FULL spectrum then truncated to k (RapidsRowMatrix.scala:92-99),
and the signFlip orientation rule (rapidsml_jni.cu:35-61).
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.autotune.policy import (
    FOLD_POLICIES,
    PrecisionPolicy,
    resolve_policy,
)

# Matmul precision for the hot Gram/projection matmuls. HIGHEST on TPU means
# multi-pass bf16 (6-pass) which recovers ~f32 accuracy on the MXU.
DEFAULT_PRECISION = lax.Precision.HIGHEST

# The user-facing precision tiers (the estimators' ``precision`` param and
# the TPU_ML_DEFAULT_PRECISION config knob map through this).
PRECISIONS = {
    "highest": lax.Precision.HIGHEST,
    "high": lax.Precision.HIGH,
    "default": lax.Precision.DEFAULT,
}

DEFAULT_POLICY = PrecisionPolicy.F32.value


def policy_matmul(a: jax.Array, b: jax.Array, *,
                  precision=DEFAULT_PRECISION,
                  policy: str = DEFAULT_POLICY) -> jax.Array:
    """The policy-aware matmul every accumulation kernel funnels through.

    ``f32`` is the seed behavior (the ``precision`` knob applies verbatim).
    ``bf16_f32acc`` casts the *operands* to bfloat16 and forces f32 MXU
    accumulation with ``preferred_element_type``, then upcasts the result
    back to the operand dtype — the downstream add into the f32/f64 carry
    is exact in the carry dtype, so donation (TPL001) and bitwise
    checkpoint/resume semantics are untouched; only operand mantissa is
    traded (bf16 tile (16, 128) halves MXU operand bytes vs f32 (8, 128)).
    """
    if policy == PrecisionPolicy.BF16_F32ACC.value:
        out = jnp.matmul(
            a.astype(jnp.bfloat16),
            b.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return out.astype(a.dtype)
    return jnp.matmul(a, b, precision=precision)


def int8_quantized_matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """Symmetric per-tensor int8 quantized ``a·b`` — the ``int8_dist``
    policy's cross term for kmeans/knn candidate scoring.

    Max-abs scales map each operand onto [−127, 127]; the int8×int8 matmul
    accumulates in int32 (``preferred_element_type``, int8 MXU tile
    (32, 128)) and dequantizes by the scale product. Strictly opt-in and
    only ever used for *distance ranking* — never for Gram/linear
    accumulation, where quantization error would compound over chunks.
    """

    def quant(t):
        amax = jnp.max(jnp.abs(t))
        scale = jnp.where(amax > 0, amax / 127.0, jnp.ones_like(amax))
        q = jnp.clip(jnp.round(t / scale), -127.0, 127.0).astype(jnp.int8)
        return q, scale

    qa, sa = quant(a)
    qb, sb = quant(b)
    acc = jnp.matmul(qa, qb, preferred_element_type=jnp.int32)
    return acc.astype(a.dtype) * (sa * sb)


class GramStats(NamedTuple):
    """Partition-local sufficient statistics for (optionally centered) PCA.

    A commutative monoid: ``combine_gram_stats`` sums two of them, which is
    what rides the cross-partition reduction (psum over ICI on an SPMD mesh,
    or host tree-aggregation on the portable path). This replaces the
    reference's JVM-heap breeze ``reduce((a, b) => a + b)``
    (RapidsRowMatrix.scala:139).
    """

    xtx: jax.Array  # [n, n] — Xᵀ·X of the partition's rows
    col_sum: jax.Array  # [n]  — per-feature sums (for mean centering)
    count: jax.Array  # []   — number of rows


def gram(x: jax.Array, *, precision=DEFAULT_PRECISION) -> jax.Array:
    """Uncentered Gram matrix XᵀX of a row-major [rows, n] block.

    Parity target: ``dgemmCov`` (native/src/rapidsml_jni.cu:109-127), which
    runs cublasgemm(OP_N, OP_T) on the column-major device buffer — the same
    XᵀX contraction.
    """
    return jnp.matmul(x.T, x, precision=precision)


def gram_stats(x: jax.Array, *, precision=DEFAULT_PRECISION) -> GramStats:
    """Compute the full sufficient-statistics triple for one partition."""
    return GramStats(
        xtx=gram(x, precision=precision),
        col_sum=jnp.sum(x, axis=0),
        count=jnp.asarray(x.shape[0], dtype=x.dtype),
    )


def combine_gram_stats(a: GramStats, b: GramStats) -> GramStats:
    """Monoid combine — elementwise sum of the triples."""
    return GramStats(a.xtx + b.xtx, a.col_sum + b.col_sum, a.count + b.count)


def gram_stats_weighted(
    x: jax.Array, w: jax.Array, *, precision=DEFAULT_PRECISION,
    policy: str = DEFAULT_POLICY,
) -> GramStats:
    """GramStats under the framework-wide masking convention: ``w`` carries
    instance weights on true rows and 0.0 on pad rows, so XᵀWX, the weighted
    column sums, and the weight-sum count are exact over padded chunks with
    no count fix-up. With unit weights this reduces bit-for-bit to
    :func:`gram_stats` of the zero-padded block (x·1.0 == x).

    Under ``policy='bf16_f32acc'`` only the XᵀWX matmul operands are cast
    (``policy_matmul``); col_sum and count stay exact in the carry dtype."""
    xw = x * w[:, None]
    return GramStats(
        xtx=policy_matmul(x.T, xw, precision=precision, policy=policy),
        col_sum=jnp.sum(xw, axis=0),
        count=jnp.sum(w),
    )


def fold_gram_stats(
    carry: GramStats, x: jax.Array, w: jax.Array, *,
    precision=DEFAULT_PRECISION, policy: str = DEFAULT_POLICY,
) -> GramStats:
    """One streamed-fit fold step: carry + weighted stats of one chunk."""
    return combine_gram_stats(
        carry, gram_stats_weighted(x, w, precision=precision, policy=policy)
    )


def gram_fold_step(precision=DEFAULT_PRECISION, policy: str | None = None):
    """The cached jitted fold step for streamed fits, with the carry
    **donated**: the [n, n] accumulator is updated in place on device, so a
    stream of C chunks allocates ONE set of carry buffers, not C — and the
    jitted call returns as soon as it is dispatched (JAX async dispatch),
    which is what lets the next chunk's host ingest overlap this chunk's
    MXU fold. Use ``carry = step(carry, x, w)`` and never touch the old
    carry again — donation invalidates it.

    ``policy=None`` resolves the process default (``TPU_ML_PRECISION_POLICY``)
    *before* the cache lookup, so an env change selects a different cached
    program instead of a stale one."""
    return _gram_fold_step(
        precision, resolve_policy(policy, allowed=FOLD_POLICIES)
    )


@lru_cache(maxsize=None)
def _gram_fold_step(precision, policy: str):
    def _step(carry: GramStats, x: jax.Array, w: jax.Array) -> GramStats:
        return fold_gram_stats(carry, x, w, precision=precision,
                               policy=policy)

    return jax.jit(_step, donate_argnums=0)


def init_gram_carry(n: int, dtype) -> GramStats:
    """Zero device-resident GramStats carry for :func:`gram_fold_step`."""
    return GramStats(
        xtx=jnp.zeros((n, n), dtype),
        col_sum=jnp.zeros((n,), dtype),
        count=jnp.zeros((), dtype),
    )


def gram_fold_xtx_step(precision=DEFAULT_PRECISION,
                       policy: str | None = None):
    """Donated fold of the bare [n, n] Gram (the TruncatedSVD accumulator —
    no col_sum/count companions). Pad rows are zero so no mask is needed."""
    return _gram_fold_xtx_step(
        precision, resolve_policy(policy, allowed=FOLD_POLICIES)
    )


@lru_cache(maxsize=None)
def _gram_fold_xtx_step(precision, policy: str):
    def _step(carry: jax.Array, x: jax.Array) -> jax.Array:
        return carry + policy_matmul(x.T, x, precision=precision,
                                     policy=policy)

    return jax.jit(_step, donate_argnums=0)


def covariance_from_stats(stats: GramStats, *, mean_centering: bool) -> jax.Array:
    """Finalize the (scatter-form) covariance from reduced statistics.

    With ``mean_centering=False`` this is the raw Gram XᵀX — the reference's
    actual observable behavior (its meanCentering is a TODO stub,
    RapidsRowMatrix.scala:111-117). With ``True`` it is the centered scatter
    matrix (X-μ)ᵀ(X-μ) = XᵀX − s·sᵀ/count. No 1/(n-1) normalization is
    applied, matching the reference; eigenvectors and the explained-variance
    *ratio* are invariant to that scale.
    """
    if not mean_centering:
        return stats.xtx
    denom = jnp.maximum(stats.count, jnp.ones_like(stats.count))
    return stats.xtx - jnp.outer(stats.col_sum, stats.col_sum) / denom


def standardized_cov_from_stats(
    stats: GramStats,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(scatter of standardized X, mean, sample std) from RAW GramStats —
    the fused StandardScaler→PCA pipeline (BASELINE config 4) in ONE data
    pass: with Xs = (X − μ)/σ,  XsᵀXs = D⁻¹(XᵀX − m·μμᵀ)D⁻¹ with D =
    diag(σ), so the standardized covariance needs no second pass over the
    data. σ is the sample (m−1) std matching StandardScaler
    (ops/scaler.py finalize_moments); zero-variance features pass through
    unscaled, like ``standardize``."""
    from spark_rapids_ml_tpu.ops import scaler as S

    # diag(XᵀX) IS the per-feature sum of squares: the scaler's own
    # finalize_moments derives mean/sample-std, so the fused path can never
    # drift from the staged StandardScaler pipeline it must equal
    mean, std = S.finalize_moments(
        S.MomentStats(stats.count, stats.col_sum, jnp.diagonal(stats.xtx))
    )
    m = jnp.maximum(stats.count, jnp.ones_like(stats.count))
    safe = jnp.where(std > 0, std, jnp.ones_like(std))
    centered = stats.xtx - m * jnp.outer(mean, mean)
    cov = centered / jnp.outer(safe, safe)
    return cov, mean, std


def sign_flip(u: jax.Array) -> jax.Array:
    """Deterministic eigenvector orientation.

    Parity target: the ``signFlip`` thrust kernel
    (native/src/rapidsml_jni.cu:35-61): for each column, find the element of
    largest absolute value; if it is negative, negate the whole column.
    """
    idx = jnp.argmax(jnp.abs(u), axis=0)
    anchors = jnp.take_along_axis(u, idx[None, :], axis=0)[0]
    signs = jnp.where(anchors < 0, -jnp.ones_like(anchors), jnp.ones_like(anchors))
    return u * signs[None, :]


def refine_eigh(
    a: jax.Array,
    v: jax.Array,
    evals: jax.Array,
    *,
    iters: int = 2,
    precision=DEFAULT_PRECISION,
) -> tuple[jax.Array, jax.Array]:
    """Iterative refinement of an approximate symmetric eigendecomposition.

    Newton-style correction in the spirit of Ogita–Aishima: given nearly
    orthonormal eigenvector estimates ``v``, form B = VᵀAV, take refined
    eigenvalues from diag(B) and a first-order eigenvector correction
    Zᵢⱼ = Bᵢⱼ/(Bⱼⱼ−Bᵢᵢ); converges quadratically for well-separated spectra.

    Why this exists: XLA's eigh lowers to an approximate QDWH/Jacobi route
    (residual ~1e-4·‖A‖ even in f64 on this stack) and TPU f64 is emulated.
    Two refinement sweeps of plain matmuls — exactly what the MXU is good
    at — recover LAPACK-grade residuals without a native solver, keeping the
    whole fit a single XLA program. Near-degenerate eigenpairs (gap below
    ~√eps·‖A‖) are left uncorrected: their subspace mixing is inherently
    ill-determined, and a huge 1/gap would destroy orthogonality.
    """
    eps = jnp.finfo(v.dtype).eps
    for _ in range(iters):
        av = jnp.matmul(a, v, precision=precision)
        b = jnp.matmul(v.T, av, precision=precision)
        d = jnp.diagonal(b)
        gap = d[None, :] - d[:, None]
        scale = jnp.max(jnp.abs(d)) + eps
        safe = jnp.abs(gap) > jnp.sqrt(eps) * scale
        z = jnp.where(safe, b / jnp.where(safe, gap, jnp.ones_like(gap)), 0.0)
        z = z - jnp.diag(jnp.diagonal(z))
        v = v + jnp.matmul(v, z, precision=precision)
        # One Newton–Schulz step restores orthonormality lost to the
        # first-order update: V ← V(3I − VᵀV)/2.
        vtv = jnp.matmul(v.T, v, precision=precision)
        v = jnp.matmul(
            v, 1.5 * jnp.eye(v.shape[1], dtype=v.dtype) - 0.5 * vtv,
            precision=precision,
        )
        evals = d
    av = jnp.matmul(a, v, precision=precision)
    evals = jnp.sum(v * av, axis=0) / jnp.sum(v * v, axis=0)
    return v, evals


def eigh_descending(
    cov: jax.Array, *, refine_iters: int = 2
) -> tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition in descending order with √λ and sign-flip.

    Returns ``(components, singular_values)`` where ``components`` is [n, n]
    (eigenvectors in columns, descending eigenvalue order, sign-flipped) and
    ``singular_values`` is √max(λ, 0) descending.

    Parity target: ``calSVD`` (native/src/rapidsml_jni.cu:215-269):
    raft eigDC (ascending) → colReverse/rowReverse → seqRoot → signFlip.
    """
    evals, evecs = jnp.linalg.eigh(cov)  # ascending, like cuSolver syevd
    if refine_iters:
        evecs, evals = refine_eigh(cov, evecs, evals, iters=refine_iters)
        order = jnp.argsort(evals)[::-1]  # refinement may reorder near-ties
        evals = evals[order]
        evecs = evecs[:, order]
    else:
        evals = evals[::-1]
        evecs = evecs[:, ::-1]
    singular_values = jnp.sqrt(jnp.clip(evals, 0.0, None))
    return sign_flip(evecs), singular_values


def randomized_eigh_descending(
    cov: jax.Array,
    k: int,
    *,
    oversample: int = 10,
    power_iters: int = 2,
    seed: int = 0,
    precision=DEFAULT_PRECISION,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Randomized top-k eigendecomposition of a PSD matrix (descending).

    Halko–Martinsson–Tropp randomized subspace iteration, shaped for the
    MXU: every step is a large dense matmul ([n, n]·[n, l] with
    l = k + oversample) plus a thin QR — O(n²·l) instead of the full eigh's
    O(n³). The win is real once n is a few thousand and k ≪ n (the regime
    the reference cannot reach at all: its n×n eig is single-GPU cuSolver,
    rapidsml_jni.cu:251).

    Returns ``(components [n, k], singular_values [l], tail_count)`` where
    singular values are √max(λ, 0) for ALL l = k + oversample Ritz values
    (the extra ones cost nothing and make the explained-variance tail
    estimate far tighter), components are the top-k Ritz vectors sign-flipped
    with the same orientation rule as the exact path, and ``tail_count`` =
    n − l is the count of eigenvalues not represented in the returned
    spectrum.
    """
    n = cov.shape[0]
    l = min(n, k + oversample)
    key = jax.random.PRNGKey(seed)
    omega = jax.random.normal(key, (n, l), dtype=cov.dtype)
    q, _ = jnp.linalg.qr(jnp.matmul(cov, omega, precision=precision))
    for _ in range(power_iters):
        q, _ = jnp.linalg.qr(jnp.matmul(cov, q, precision=precision))
    # Rayleigh–Ritz on the captured subspace: B = QᵀAQ, eigh of the small
    # l×l system, lift back with U = Q·V.
    aq = jnp.matmul(cov, q, precision=precision)
    b = jnp.matmul(q.T, aq, precision=precision)
    b = 0.5 * (b + b.T)
    evals, v = jnp.linalg.eigh(b)  # ascending
    evals = evals[::-1]
    v = v[:, ::-1][:, :k]
    u = sign_flip(jnp.matmul(q, v, precision=precision))
    singular_values = jnp.sqrt(jnp.clip(evals, 0.0, None))
    return u, singular_values, jnp.asarray(n - l, dtype=cov.dtype)


def explained_variance_from_partial(
    singular_values: jax.Array, trace: jax.Array, tail_count: jax.Array
) -> jax.Array:
    """Reference-shaped explainedVariance from a PARTIAL spectrum.

    The reference normalizes sᵢ over the FULL spectrum
    (RapidsRowMatrix.scala:92-93); a randomized solver only has the top
    l = k + oversample singular values. The unseen tail's Σ√λ is estimated
    from the leftover trace: Σλ_tail = trace − Σλ_top, and by concavity
    Σ√λ_tail ≤ √(tail_count·Σλ_tail); we use that bound as the estimate
    (exact when the tail is flat, conservative — ratios shrink — when it
    decays). Since everything below λ_l is ≤ the smallest computed Ritz
    value, the estimate is applied only to that sub-λ_l remainder — the
    oversampled Ritz values carry the rest — so the error is confined to
    the flattest part of the spectrum. Returns ratios for all input values;
    callers truncate to k.
    """
    top_sum = jnp.sum(singular_values)
    top_eval_sum = jnp.sum(singular_values**2)
    tail_eval_sum = jnp.clip(trace - top_eval_sum, 0.0, None)
    tail_sum = jnp.sqrt(tail_eval_sum * jnp.clip(tail_count, 0.0, None))
    total = top_sum + tail_sum
    safe_total = jnp.where(total > 0, total, jnp.ones_like(total))
    return singular_values / safe_total


def explained_variance(singular_values: jax.Array, k: int) -> jax.Array:
    """sᵢ/Σs over the FULL spectrum, truncated to the first k.

    This is the reference's (non-textbook) definition — singular-value
    proportions, normalized before truncation (RapidsRowMatrix.scala:92-99).
    """
    total = jnp.sum(singular_values)
    safe_total = jnp.where(total > 0, total, jnp.ones_like(total))
    return (singular_values / safe_total)[:k]


def randomized_profitable(n: int, k: int, *, oversample: int = 10) -> bool:
    """Shared 'auto' solver rule: the HMT subspace iteration wins when the
    captured subspace l = k + oversample is a small fraction of n. Both PCA
    and TruncatedSVD dispatch through this single predicate.

    Thresholds are TPU-measured, not asymptotic: on v5e at n=512, l=70 the
    randomized route saved ~6.7 ms over the refined eigh (XLA's QDWH-based
    eigh pays several n³ passes, so randomized profits far earlier than an
    O(n³)-vs-O(n²l) count suggests — bench.py records the measurement)."""
    return n >= 256 and (k + oversample) * 4 <= n


def pca_fit_from_cov(
    cov: jax.Array,
    k: int,
    *,
    solver: str = "full",
    oversample: int = 10,
    power_iters: int = 2,
    seed: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Decomposition stage: covariance → (pc [n, k], explained_variance [k]).

    ``solver``:
    - ``"full"`` — exact refined eigh (reference-parity path).
    - ``"randomized"`` — HMT subspace iteration, O(n²·(k+p)); explained
      variance uses the trace-based tail estimate.
    - ``"auto"`` — randomized when it is clearly profitable
      (n ≥ 256 and k + oversample ≤ n/4, the TPU-measured rule), else full.
    """
    n = cov.shape[0]
    if solver == "auto":
        solver = (
            "randomized"
            if randomized_profitable(n, k, oversample=oversample)
            else "full"
        )
    if solver == "randomized":
        u, s, tail_count = randomized_eigh_descending(
            cov, k, oversample=oversample, power_iters=power_iters, seed=seed
        )
        ev = explained_variance_from_partial(s, jnp.trace(cov), tail_count)
        return u, ev[:k]
    if solver != "full":
        raise ValueError(f"unknown solver {solver!r}")
    components, s = eigh_descending(cov)
    return components[:, :k], explained_variance(s, k)


def pca_fit_local(
    x: jax.Array,
    k: int,
    *,
    mean_centering: bool = False,
    precision=DEFAULT_PRECISION,
) -> tuple[jax.Array, jax.Array]:
    """Single-device end-to-end fit kernel: rows → (pc, explainedVariance).

    Fully jit-able with static ``k``/``mean_centering``. This is the
    whole reference fit() hot path (SURVEY.md §3.1) as one XLA program.

    When ``mean_centering=False`` (the reference's observable behavior —
    its centering is a TODO stub, RapidsRowMatrix.scala:111-117) the
    column-sum statistic is skipped entirely: that saves a second full
    HBM pass over X, leaving exactly the reference's computation
    (uncentered Gram + eig).
    """
    if not mean_centering:
        return pca_fit_from_cov(gram(x, precision=precision), k)
    stats = gram_stats(x, precision=precision)
    cov = covariance_from_stats(stats, mean_centering=True)
    return pca_fit_from_cov(cov, k)


def min_cosine_vs_f64_oracle(x_host, pc, k: int) -> float:
    """Min per-component |cosine| of fitted components vs the f64 host
    oracle (uncentered scatter eigh, descending) — the accuracy check the
    bench publishes per round and CI gates on (tests/test_accuracy_validation
    .py); ONE implementation so they can never desynchronize."""
    import numpy as np

    xa = np.asarray(x_host, dtype=np.float64)
    pc = np.asarray(pc, dtype=np.float64)
    _, evecs = np.linalg.eigh(xa.T @ xa)
    oracle = evecs[:, ::-1][:, :k]
    cosines = np.abs(np.sum(pc * oracle, axis=0)) / (
        np.linalg.norm(pc, axis=0) * np.linalg.norm(oracle, axis=0)
    )
    return float(cosines.min())


def qr_r(x: jax.Array) -> jax.Array:
    """R factor of a (tall) row block, always shaped [n, n].

    The building block of the direct-SVD fit path: R carries the complete
    sufficient statistic for X's right singular structure (RᵀR = XᵀX) while
    staying orthogonal-factor-accurate — unlike the Gram matrix, forming R
    never squares the condition number. Blocks with fewer than n rows are
    zero-padded (QR of [X; 0] has the same R up to the rows X determines).
    """
    rows, n = x.shape
    if rows < n:
        x = jnp.concatenate([x, jnp.zeros((n - rows, n), x.dtype)], axis=0)
    return jnp.linalg.qr(x, mode="r")


def combine_r(a: jax.Array, b: jax.Array) -> jax.Array:
    """Associative combine for R factors: QR of the stacked pair.

    (RᵃᵀRᵃ + RᵇᵀRᵇ) is preserved, so R factors reduce across partitions
    exactly like ``GramStats`` — a semigroup ridden by ``tree_reduce`` on
    the portable path and by the butterfly exchange in ``parallel.tsqr`` on
    the mesh path.
    """
    return jnp.linalg.qr(jnp.concatenate([a, b], axis=0), mode="r")


def svd_components_from_r(r: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """R → (components [n, k], singular values [n], both of X).

    The singular values of R are exactly the singular values of X (X = QR
    with Q orthonormal). Right singular vectors get the same deterministic
    sign-flip orientation as the eigh path (rapidsml_jni.cu:35-61). The one
    SVD(R) kernel both direct-path estimators (PCA solver='svd' and
    TruncatedSVD) decompose through.
    """
    _, s, vt = jnp.linalg.svd(r, full_matrices=False)  # descending already
    return sign_flip(vt.T[:, :k]), s


def svd_from_r(r: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Decomposition stage of the direct PCA path: R → (pc [n, k], ev [k]).

    The reference's explained-variance definition — sᵢ/Σs over the FULL
    spectrum, truncated to k (RapidsRowMatrix.scala:92-99) — transfers
    unchanged, computed here without ever forming XᵀX.
    """
    components, s = svd_components_from_r(r, k)
    return components, explained_variance(s, k)


def pca_fit_local_svd(
    x: jax.Array,
    k: int,
    *,
    mean_centering: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Single-device direct-SVD fit: rows → (pc, explainedVariance).

    Numerically superior alternative to the Gram path for ill-conditioned
    data: cond(XᵀX) = cond(X)², so the Gram route loses half the working
    digits before the eigensolver even starts; QR → SVD(R) works at
    cond(X). The reference has no such path (its only route is the Gram +
    cuSolver eig, SURVEY.md §3.1); this is a capability-add enabled by the
    TSQR reduction being mesh-friendly.
    """
    if mean_centering:
        x = x - jnp.mean(x, axis=0, keepdims=True)
    return svd_from_r(qr_r(x), k)


def project(x: jax.Array, pc: jax.Array, *, precision=DEFAULT_PRECISION) -> jax.Array:
    """Transform projection X·PC for a [rows, n] block and [n, k] components.

    Parity target: ``dgemm`` (native/src/rapidsml_jni.cu:75-107). The
    reference computes (X·PC)ᵀ with an OP_T transpose trick purely to land
    row-major data in its column-major LIST layout (RapidsPCA.scala:139-152);
    with row-major JAX arrays the plain contraction is the same math.
    """
    return jnp.matmul(x, pc, precision=precision)
