"""DBSCAN device kernels — density clustering without an n×n adjacency.

The modern spark-rapids-ml family ships DBSCAN on cuML's GPU kernels
(pairwise eps-neighborhood + BFS over the core graph); the 22.12 reference
this framework re-designs stops at PCA (SURVEY.md §2), so this is a
capability-add in the KMeans/NearestNeighbors spirit.

TPU-first formulation — three observations drive the design:

1. the eps-neighborhood test is the same ‖x−y‖² cross-term expansion every
   other kernel here uses: one MXU matmul per (row block, corpus block)
   tile pair, double-blocked under ``lax.scan`` so only [blk, blk] tiles
   ever exist — no n×n adjacency in HBM;
2. BFS (the GPU formulation) is hostile to XLA's static control flow, but
   connected components over the core-point graph are equally reachable by
   MIN-LABEL PROPAGATION: every core point repeatedly takes the smallest
   label among its core eps-neighbors. Each sweep is the same blocked
   distance pass with a masked min instead of a count;
3. plain propagation needs O(graph diameter) sweeps; pointer jumping
   (``labels = labels[labels]``, the Shiloach–Vishkin shortcut) after each
   sweep collapses label chains logarithmically, because a label is always
   the INDEX of another core row in the same cluster.

Labels out: cluster id = smallest core-row index in the cluster (relabeled
consecutively by the model layer), border rows take the smallest core
neighbor's cluster (deterministic, where sklearn's scan-order assignment is
not), noise = −1. ``w`` is sklearn-style sample_weight: a row is core when
the WEIGHT SUM of its eps-neighborhood (self included) reaches ``min_pts``
— weights gate CORE status only, so a zero-weight row within eps of a core
point is still labeled (sklearn semantics). ``valid`` is the separate pad
mask: invalid rows contribute nothing, can't be core, and come out −1.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.ops.kmeans import pairwise_sq_dists


def _block_pairs(x: jax.Array, block_rows: int):
    """Pad rows to a block multiple and reshape to [nblk, blk, n]."""
    rows, n = x.shape
    blk = min(block_rows, rows)
    nblk = -(-rows // blk)
    xp = jnp.pad(x, ((0, nblk * blk - rows), (0, 0)))
    return xp.reshape(nblk, blk, n), blk, nblk


def make_count_fn(eps_sq):
    """Tile accumulator: weighted eps-neighborhood mass. Shared by the
    single-device kernel and the mesh shards (parallel/dbscan.py)."""

    def count_fn(acc, d, extras):
        return acc + jnp.sum(
            jnp.where(d <= eps_sq, extras["w"][None, :], 0.0), axis=1
        )

    return count_fn


def make_min_fn(eps_sq, sentinel):
    """Tile accumulator: smallest label among core eps-neighbors. Shared by
    the single-device kernel and the mesh shards."""

    def min_fn(acc, d, extras):
        cand = jnp.where(
            (d <= eps_sq) & extras["core"].astype(bool)[None, :],
            extras["labels"][None, :],
            sentinel,
        )
        return jnp.minimum(acc, jnp.min(cand, axis=1))

    return min_fn


def _blocked_rowpass(
    queries: jax.Array,
    corpus_x: jax.Array,
    row_fn,
    init_row,
    *,
    block_rows: int,
    corpus=None,
):
    """Run ``row_fn(acc_tile, d_tile, corpus_slice) -> acc_tile`` over every
    (query block × corpus block) tile of the pairwise distance matrix,
    returning the [q_rows]-shaped accumulators — THE shared skeleton of the
    count pass and every propagation sweep, for both the single-device
    kernels (queries IS the corpus) and the mesh shards (shard rows vs the
    gathered full corpus, parallel/dbscan.py). ``corpus`` carries the
    per-corpus-row extras (weights, labels, core mask), delivered to
    ``row_fn`` as [blk]-shaped slices."""
    q_rows = queries.shape[0]
    c_rows = corpus_x.shape[0]
    qb, _, _ = _block_pairs(queries, block_rows)
    xb, blk, nblk = _block_pairs(corpus_x, block_rows)
    corpus = corpus or {}
    cb = {
        k: jnp.pad(v, (0, nblk * blk - c_rows)).reshape(nblk, blk)
        for k, v in corpus.items()
    }

    def outer(_, qi):
        def inner(acc, blk_slices):
            xj = blk_slices["_x"]
            extras = {k: v for k, v in blk_slices.items() if k != "_x"}
            d = pairwise_sq_dists(qi, xj)
            return row_fn(acc, d, extras), None

        acc0 = jnp.full((qi.shape[0],), init_row[0], init_row[1])
        acc, _ = lax.scan(inner, acc0, {"_x": xb, **cb})
        return None, acc

    _, out = lax.scan(outer, None, qb)
    return out.reshape(-1)[:q_rows]


@partial(jax.jit, static_argnames=("block_rows",))
def dbscan_core_mask(
    x: jax.Array,
    w: jax.Array,
    valid: jax.Array,
    eps_sq: jax.Array,
    min_pts: jax.Array,
    *,
    block_rows: int = 2048,
) -> jax.Array:
    """[rows] bool: valid, and weighted eps-neighborhood mass (self
    included) ≥ min_pts. Weight gates core status only — a zero-weight
    valid row is core when its neighbors' mass suffices (sklearn)."""
    wv = jnp.where(valid.astype(bool), w, 0.0)
    counts = _blocked_rowpass(
        x, x, make_count_fn(eps_sq), (0.0, x.dtype),
        block_rows=block_rows, corpus={"w": wv},
    )
    return (counts >= min_pts) & valid.astype(bool)


@partial(jax.jit, static_argnames=("block_rows",))
def dbscan_labels(
    x: jax.Array,
    w: jax.Array,
    valid: jax.Array,
    eps_sq: jax.Array,
    min_pts: jax.Array,
    *,
    block_rows: int = 2048,
) -> jax.Array:
    """Full DBSCAN on one device: [rows] int32 labels (smallest core index
    per cluster; border → smallest core neighbor's cluster; noise/pad −1)."""
    rows = x.shape[0]
    core = dbscan_core_mask(
        x, w, valid, eps_sq, min_pts, block_rows=block_rows
    )
    sentinel = jnp.int32(rows)

    def donated_min(labels):
        """[rows] smallest label among each row's CORE eps-neighbors."""
        return _blocked_rowpass(
            x,
            x,
            make_min_fn(eps_sq, sentinel),
            (sentinel, jnp.int32),
            block_rows=block_rows,
            corpus={"core": core.astype(jnp.int32), "labels": labels},
        )

    labels0 = jnp.where(core, jnp.arange(rows, dtype=jnp.int32), sentinel)

    def cond(carry):
        _, changed = carry
        return changed

    def body(carry):
        labels, _ = carry
        new = jnp.where(core, jnp.minimum(labels, donated_min(labels)), labels)
        # pointer jumping: a core label is the index of a core row in the
        # same cluster, so chasing it twice collapses chains logarithmically
        for _ in range(2):
            new = jnp.where(core, new[jnp.clip(new, 0, rows - 1)], new)
        return (new, jnp.any(new != labels))

    labels, _ = lax.while_loop(cond, body, (labels0, jnp.bool_(True)))

    # border pass: non-core rows adopt the smallest core neighbor's
    # (converged) cluster; no core neighbor ⇒ noise. Invalid (pad) rows −1.
    donated = donated_min(labels)
    out = jnp.where(core, labels, jnp.where(donated < sentinel, donated, -1))
    return jnp.where(valid.astype(bool), out, -1).astype(jnp.int32)
