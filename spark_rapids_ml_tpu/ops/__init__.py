"""Pure-JAX device kernels — the TPU replacement for the reference native layer.

Every function here is shape-static, functional, and ``jax.jit``-compatible so
XLA can tile the matmuls onto the MXU and fuse the elementwise epilogues.
"""

from spark_rapids_ml_tpu.ops.linalg import (  # noqa: F401
    GramStats,
    combine_gram_stats,
    eigh_descending,
    explained_variance,
    gram,
    gram_stats,
    pca_fit_from_cov,
    pca_fit_local,
    project,
    sign_flip,
)
