"""Exact k-nearest-neighbors device kernels — brute force on the MXU.

The modern spark-rapids-ml family ships an exact brute-force NearestNeighbors
built on RAFT's pairwise-distance + k-selection GPU kernels; the 22.12
reference this framework re-designs (SURVEY.md §2) stops at PCA, so this is
a capability-add in the same spirit as its KMeans sibling (ops/kmeans.py).

TPU-first formulation:

- distances are the same ‖x‖² + ‖y‖² − 2·x·yᵀ cross-term expansion KMeans
  uses — the [q, n]×[n, block] cross term is one MXU matmul per corpus
  block;
- k-selection is ``lax.top_k`` on NEGATED distances, merged blockwise: the
  running [q, k] winners concatenate with each block's [q, block] scores and
  a single top_k keeps the best k — a streaming tournament that never
  materializes the full [q, rows] distance matrix (HBM-bound otherwise);
- the corpus is scanned in fixed-size row blocks under ``lax.scan`` so one
  XLA program covers any corpus length with static shapes.

The mesh-sharded version (parallel/neighbors.py) runs this per shard and
merges candidates with one ``all_gather`` over the data axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.autotune.policy import PrecisionPolicy
from spark_rapids_ml_tpu.ops.linalg import (
    DEFAULT_PRECISION,
    DEFAULT_POLICY,
    int8_quantized_matmul,
    policy_matmul,
)

#: metric → (score sign) — kernels rank by LARGEST score internally.
#: "sqeuclidean": score = −‖x−y‖² (top-k = nearest);
#: "dot":         score = x·y     (top-k = largest inner product).
_METRICS = ("sqeuclidean", "dot")


def _block_scores(
    queries: jax.Array, block: jax.Array, metric: str, precision,
    policy: str = DEFAULT_POLICY,
) -> jax.Array:
    """[q, block] ranking scores (larger = better neighbor).

    The cross term honors the precision ``policy`` (bf16 operands or the
    opt-in int8 quantized candidate scoring); norms stay full precision."""
    if policy == PrecisionPolicy.INT8_DIST.value:
        cross = int8_quantized_matmul(queries, block.T)
    else:
        cross = policy_matmul(queries, block.T, precision=precision,
                              policy=policy)
    if metric == "dot":
        return cross
    q_sq = jnp.sum(queries * queries, axis=1, keepdims=True)
    b_sq = jnp.sum(block * block, axis=1)[None, :]
    return -jnp.clip(q_sq + b_sq - 2.0 * cross, 0.0, None)


def merge_topk(
    scores_a: jax.Array,
    idx_a: jax.Array,
    scores_b: jax.Array,
    idx_b: jax.Array,
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """Merge two candidate sets (scores descending-is-better) into the best
    k: one concat + one ``lax.top_k`` — the tournament step both the blocked
    scan and the cross-shard gather reuse."""
    scores = jnp.concatenate([scores_a, scores_b], axis=1)
    idx = jnp.concatenate([idx_a, idx_b], axis=1)
    best, which = lax.top_k(scores, k)
    return best, jnp.take_along_axis(idx, which, axis=1)


@partial(
    jax.jit,
    static_argnames=("k", "metric", "block_rows", "index_offset", "policy"),
)
def knn_topk(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    *,
    metric: str = "sqeuclidean",
    block_rows: int = 8192,
    index_offset: int = 0,
    precision=DEFAULT_PRECISION,
    policy: str = DEFAULT_POLICY,
) -> tuple[jax.Array, jax.Array]:
    """Best-k corpus rows per query, streamed over corpus blocks.

    ``valid`` masks corpus rows ([rows] bool/float; pad rows 0) — invalid
    rows score −inf and can never be selected. Returns
    ``(scores [q, k] descending, indices [q, k] int32)`` with indices
    offset by ``index_offset`` (the shard's global row base). Scores are
    negated squared distances for ``metric="sqeuclidean"`` and raw inner
    products for ``metric="dot"`` — the model layer converts to user-facing
    distances.
    """
    if metric not in _METRICS:
        raise ValueError(f"metric must be one of {_METRICS}, got {metric!r}")
    rows, n = corpus.shape
    q = queries.shape[0]
    if k > rows:
        raise ValueError(f"k={k} exceeds corpus rows={rows}")
    blk = min(block_rows, rows)
    nblk = -(-rows // blk)
    pad = nblk * blk - rows
    corpus = jnp.pad(corpus, ((0, pad), (0, 0)))
    validf = jnp.pad(valid.astype(bool), (0, pad), constant_values=False)
    blocks = corpus.reshape(nblk, blk, n)
    vblocks = validf.reshape(nblk, blk)
    base = index_offset + jnp.arange(nblk, dtype=jnp.int32) * blk

    neg_inf = jnp.asarray(-jnp.inf, queries.dtype)

    def step(carry, xs):
        best, bidx = carry
        block, vblock, b0 = xs
        scores = _block_scores(queries, block, metric, precision, policy)
        scores = jnp.where(vblock[None, :], scores, neg_inf)
        ids = jnp.broadcast_to(
            b0 + jnp.arange(blk, dtype=jnp.int32)[None, :], (q, blk)
        )
        return merge_topk(best, bidx, scores, ids, k), None

    init = (
        jnp.full((q, k), neg_inf, queries.dtype),
        jnp.full((q, k), jnp.int32(-1)),
    )
    (best, bidx), _ = lax.scan(step, init, (blocks, vblocks, base))
    return best, bidx
