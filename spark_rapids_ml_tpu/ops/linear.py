"""Linear-model device kernels — normal equations and IRLS, MXU-first.

The reference repo ships one estimator (PCA), but its family
(spark-rapids-ml's wider line-up) pairs it with GLMs. These kernels extend
the same architectural pattern the PCA path established (SURVEY.md §2
"parallelism strategies"): per-partition sufficient statistics as a
commutative monoid, combined by tree-aggregate or a mesh psum, with a tiny
replicated solve at the end.

- **LinearRegression** (closed form): the monoid is (XᵀX, Xᵀy, Σx, Σy, Σy²,
  m). Everything the [n, n] solve needs is one MXU pass over the data —
  structurally identical to PCA's Gram pass, so the hot loop hits the MXU
  with the same intensity.
- **LogisticRegression** (IRLS/Newton): each iteration's monoid is
  (XᵀWX, Xᵀ(y−p), loss) with W = p(1−p) — two matmuls per block. The
  replicated Newton solve is [n+1, n+1], negligible next to the data pass.

The intercept rides as an augmented all-ones feature column (``augment``),
so gradients/Hessians need no special-casing; L2 regularization masks the
intercept coordinate out of the penalty, matching Spark ML/sklearn.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.linalg import DEFAULT_PRECISION


def augment(x: jax.Array) -> jax.Array:
    """Append an all-ones intercept column: [rows, n] → [rows, n+1]."""
    return jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)


# ---------------------------------------------------------------------------
# Linear regression (normal equations)
# ---------------------------------------------------------------------------


class LinearStats(NamedTuple):
    """Sufficient statistics for (optionally intercepted, L2) least squares."""

    xtx: jax.Array  # [n, n]
    xty: jax.Array  # [n]
    x_sum: jax.Array  # [n]
    y_sum: jax.Array  # []
    y_sq: jax.Array  # []
    count: jax.Array  # []


def linear_stats(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array | None = None,
    *,
    precision=DEFAULT_PRECISION,
) -> LinearStats:
    """One-pass statistics over a row shard; ``weights`` masks padded rows."""
    if weights is not None:
        xw = x * weights[:, None]
        yw = y * weights
        count = jnp.sum(weights)
    else:
        xw, yw = x, y
        count = jnp.asarray(x.shape[0], x.dtype)
    return LinearStats(
        xtx=jnp.matmul(x.T, xw, precision=precision),
        xty=jnp.matmul(x.T, yw, precision=precision),
        x_sum=jnp.sum(xw, axis=0),
        y_sum=jnp.sum(yw),
        y_sq=jnp.sum(yw * y),
        count=count,
    )


def combine_linear_stats(a: LinearStats, b: LinearStats) -> LinearStats:
    return LinearStats(*(av + bv for av, bv in zip(a, b)))


def solve_normal(
    stats: LinearStats, *, reg_param: float = 0.0, fit_intercept: bool = True
) -> tuple[jax.Array, jax.Array]:
    """(coefficients [n], intercept []) from reduced statistics.

    With an intercept the normal equations are solved on centered moments
    (A = XᵀX − m·μμᵀ, b = Xᵀy − m·μȳ), which never penalizes the intercept;
    λ follows Spark ML's convention of scaling with the row count
    (regParam multiplies m so results match sklearn Ridge(alpha=λ·m)).
    """
    m = jnp.maximum(stats.count, jnp.ones_like(stats.count))
    n = stats.xtx.shape[0]
    lam = reg_param * m
    if fit_intercept:
        mu = stats.x_sum / m
        ybar = stats.y_sum / m
        a = stats.xtx - m * jnp.outer(mu, mu)
        b = stats.xty - m * mu * ybar
    else:
        a = stats.xtx
        b = stats.xty
    a = a + lam * jnp.eye(n, dtype=a.dtype)
    coef = jax.scipy.linalg.solve(a, b, assume_a="pos")
    # Rank-deficient designs (constant/collinear columns, λ=0) break the
    # Cholesky path with NaNs; fall back to the min-norm lstsq solution.
    # The [n, n] solve is negligible next to the data pass, so computing
    # the fallback unconditionally keeps this jittable (no host branch).
    coef_lstsq = jnp.linalg.lstsq(a, b)[0]
    coef = jnp.where(jnp.all(jnp.isfinite(coef)), coef, coef_lstsq)
    intercept = (
        stats.y_sum / m - jnp.dot(stats.x_sum / m, coef)
        if fit_intercept
        else jnp.zeros((), coef.dtype)
    )
    return coef, intercept


def predict_linear(
    x: jax.Array, coef: jax.Array, intercept: jax.Array, *, precision=DEFAULT_PRECISION
) -> jax.Array:
    return jnp.matmul(x, coef, precision=precision) + intercept


# ---------------------------------------------------------------------------
# Logistic regression (IRLS / Newton)
# ---------------------------------------------------------------------------


class NewtonStats(NamedTuple):
    """One Newton iteration's sufficient statistics over a row shard."""

    hess: jax.Array  # [d, d] — XᵀWX, W = p(1−p)
    grad: jax.Array  # [d]   — Xᵀ(y − p)
    loss: jax.Array  # []    — Σ log-loss
    count: jax.Array  # []


def combine_newton_stats(a: NewtonStats, b: NewtonStats) -> NewtonStats:
    return NewtonStats(*(av + bv for av, bv in zip(a, b)))


def logistic_newton_stats(
    x_aug: jax.Array,
    y: jax.Array,
    w_full: jax.Array,
    weights: jax.Array | None = None,
    *,
    precision=DEFAULT_PRECISION,
) -> NewtonStats:
    """Local gradient/Hessian/log-loss at ``w_full`` over an augmented shard.

    ``x_aug`` is [rows, d] with the intercept column appended (d = n+1 when
    fitting an intercept); ``w_full`` is the full [d] parameter vector.
    """
    z = jnp.matmul(x_aug, w_full, precision=precision)
    p = jax.nn.sigmoid(z)
    mask = (
        weights
        if weights is not None
        else jnp.ones(x_aug.shape[0], x_aug.dtype)
    )
    resid = (y - p) * mask
    w = p * (1.0 - p) * mask
    # log-loss via logaddexp for stability: log(1+e^z) − y·z
    loss = jnp.sum((jnp.logaddexp(0.0, z) - y * z) * mask)
    hess = jnp.matmul(x_aug.T * w[None, :], x_aug, precision=precision)
    grad = jnp.matmul(x_aug.T, resid, precision=precision)
    return NewtonStats(
        hess=hess,
        grad=grad,
        loss=loss,
        count=jnp.sum(mask),
    )


def newton_update(
    w_full: jax.Array,
    stats: NewtonStats,
    *,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """One damped-free Newton step: (new w, step-norm).

    L2 penalizes every coordinate except the intercept (the last one when
    ``fit_intercept``); λ scales with the row count like ``solve_normal``.
    """
    d = w_full.shape[0]
    m = jnp.maximum(stats.count, jnp.ones_like(stats.count))
    pen = jnp.ones((d,), w_full.dtype)
    if fit_intercept:
        pen = pen.at[-1].set(0.0)
    lam = reg_param * m * pen
    hess = stats.hess + jnp.diag(lam)
    grad = stats.grad - lam * w_full
    # √eps-scaled ridge keeps the solve well-posed when classes separate
    # perfectly, sized to the dtype so f32 rounding can't flip the Cholesky
    # (√eps(f64) ≈ 1.5e-8 — f64 behavior unchanged)
    eps = jnp.sqrt(jnp.finfo(hess.dtype).eps) * jnp.trace(hess) / d
    delta = jax.scipy.linalg.solve(
        hess + eps * jnp.eye(d, dtype=hess.dtype), grad, assume_a="pos"
    )
    return w_full + delta, jnp.linalg.norm(delta)


def predict_logistic_proba(
    x: jax.Array, coef: jax.Array, intercept: jax.Array, *, precision=DEFAULT_PRECISION
) -> jax.Array:
    return jax.nn.sigmoid(
        jnp.matmul(x, coef, precision=precision) + intercept
    )


# ---------------------------------------------------------------------------
# Multinomial (softmax) logistic regression — full-Newton IRLS
# ---------------------------------------------------------------------------


class SoftmaxStats(NamedTuple):
    """One softmax-Newton iteration's statistics over a row shard.

    The Hessian is the full [C·d, C·d] Fisher information — C(C+1)/2
    distinct [d, d] blocks H[c,c'] = Xᵀ diag(w·p_c(δ_cc' − p_c')) X, each one
    MXU matmul. C·d stays modest for classical multiclass problems (e.g.
    C=10, d=513 → 5130² ≈ 26M entries), and the full Newton keeps the
    quadratic convergence the binary path has.
    """

    hess: jax.Array  # [C·d, C·d]
    grad: jax.Array  # [C·d] — flattened [C, d]
    loss: jax.Array  # []
    count: jax.Array  # []


def combine_softmax_stats(a: SoftmaxStats, b: SoftmaxStats) -> SoftmaxStats:
    return SoftmaxStats(*(av + bv for av, bv in zip(a, b)))


def softmax_newton_stats(
    x_aug: jax.Array,
    y_idx: jax.Array,
    w_flat: jax.Array,
    n_classes: int,
    weights: jax.Array | None = None,
    *,
    precision=DEFAULT_PRECISION,
) -> SoftmaxStats:
    """Gradient/Hessian/NLL of the softmax model at ``w_flat`` over a shard.

    ``x_aug`` [rows, d] (intercept column appended when fitting one),
    ``y_idx`` [rows] integer class labels in [0, C), ``w_flat`` [C·d].
    """
    rows, d = x_aug.shape
    c = n_classes
    w = w_flat.reshape(c, d)
    mask = (
        weights if weights is not None else jnp.ones(rows, x_aug.dtype)
    )
    logits = jnp.matmul(x_aug, w.T, precision=precision)  # [rows, C]
    logz = jax.scipy.special.logsumexp(logits, axis=1)
    p = jnp.exp(logits - logz[:, None])  # [rows, C]
    onehot = jax.nn.one_hot(y_idx, c, dtype=x_aug.dtype)
    loss = jnp.sum((logz - jnp.sum(onehot * logits, axis=1)) * mask)
    resid = (onehot - p) * mask[:, None]  # [rows, C]
    grad = jnp.matmul(resid.T, x_aug, precision=precision).reshape(-1)

    # Hessian blocks, upper triangle: H[c,c'] = Xᵀ diag(v_cc') X with
    # v_cc' = w·p_c(δ − p_c'). The pair loop unrolls at trace time —
    # C(C+1)/2 MXU matmuls.
    blocks = [[None] * c for _ in range(c)]
    for ci in range(c):
        for cj in range(ci, c):
            delta = 1.0 if ci == cj else 0.0
            v = mask * p[:, ci] * (delta - p[:, cj])
            blk = jnp.matmul(x_aug.T * v[None, :], x_aug, precision=precision)
            blocks[ci][cj] = blk
            if ci != cj:
                blocks[cj][ci] = blk.T
    hess = jnp.block(blocks)
    return SoftmaxStats(
        hess=hess,
        grad=grad,
        loss=loss,
        count=jnp.sum(mask),
    )


def softmax_newton_update(
    w_flat: jax.Array,
    stats: SoftmaxStats,
    n_classes: int,
    *,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """One Newton step on the flattened [C·d] parameter: (new w, step norm).

    L2 penalizes every coordinate except the per-class intercepts. The
    softmax parameterization has a flat direction (adding any vector to all
    classes leaves p unchanged); the L2 penalty pins the coefficients and the
    eps ridge pins the unpenalized intercept-shift direction — gradients are
    zero along it, so the regularized solve simply doesn't move there.
    """
    cd = w_flat.shape[0]
    d = cd // n_classes
    m = jnp.maximum(stats.count, jnp.ones_like(stats.count))
    pen = jnp.ones((n_classes, d), w_flat.dtype)
    if fit_intercept:
        pen = pen.at[:, -1].set(0.0)
    pen = pen.reshape(-1)
    lam = reg_param * m * pen
    hess = stats.hess + jnp.diag(lam)
    grad = stats.grad - lam * w_flat
    # √eps-scaled ridge: the exact Fisher matrix is PSD with a ZERO
    # eigenvalue along the class-shift flat direction, and dtype rounding
    # makes it slightly indefinite (measured ~-5e-5 in f32) — a fixed 1e-8
    # ridge NaNs the f32 Cholesky on the first step. √eps(f64) ≈ 1.5e-8, so
    # f64 behavior is unchanged.
    eps = jnp.sqrt(jnp.finfo(hess.dtype).eps) * jnp.trace(hess) / cd
    delta = jax.scipy.linalg.solve(
        hess + eps * jnp.eye(cd, dtype=hess.dtype), grad, assume_a="pos"
    )
    return w_flat + delta, jnp.linalg.norm(delta)


def predict_softmax_proba(
    x: jax.Array,
    coef: jax.Array,
    intercept: jax.Array,
    *,
    precision=DEFAULT_PRECISION,
) -> jax.Array:
    """[rows, C] class probabilities; ``coef`` [C, n], ``intercept`` [C]."""
    logits = jnp.matmul(x, coef.T, precision=precision) + intercept[None, :]
    return jax.nn.softmax(logits, axis=1)
