"""Linear-model device kernels — normal equations and IRLS, MXU-first.

The reference repo ships one estimator (PCA), but its family
(spark-rapids-ml's wider line-up) pairs it with GLMs. These kernels extend
the same architectural pattern the PCA path established (SURVEY.md §2
"parallelism strategies"): per-partition sufficient statistics as a
commutative monoid, combined by tree-aggregate or a mesh psum, with a tiny
replicated solve at the end.

- **LinearRegression** (closed form): the monoid is (XᵀX, Xᵀy, Σx, Σy, Σy²,
  m). Everything the [n, n] solve needs is one MXU pass over the data —
  structurally identical to PCA's Gram pass, so the hot loop hits the MXU
  with the same intensity.
- **LogisticRegression** (IRLS/Newton): each iteration's monoid is
  (XᵀWX, Xᵀ(y−p), loss) with W = p(1−p) — two matmuls per block. The
  replicated Newton solve is [n+1, n+1], negligible next to the data pass.

The intercept rides as an augmented all-ones feature column (``augment``),
so gradients/Hessians need no special-casing; L2 regularization masks the
intercept coordinate out of the penalty, matching Spark ML/sklearn.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.autotune.policy import FOLD_POLICIES, resolve_policy
from spark_rapids_ml_tpu.ops.linalg import (
    DEFAULT_PRECISION,
    DEFAULT_POLICY,
    policy_matmul,
)


def augment(x: jax.Array) -> jax.Array:
    """Append an all-ones intercept column: [rows, n] → [rows, n+1]."""
    return jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)


# ---------------------------------------------------------------------------
# Linear regression (normal equations)
# ---------------------------------------------------------------------------


class LinearStats(NamedTuple):
    """Sufficient statistics for (optionally intercepted, L2) least squares."""

    xtx: jax.Array  # [n, n]
    xty: jax.Array  # [n]
    x_sum: jax.Array  # [n]
    y_sum: jax.Array  # []
    y_sq: jax.Array  # []
    count: jax.Array  # []


def linear_stats(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array | None = None,
    *,
    precision=DEFAULT_PRECISION,
    policy: str = DEFAULT_POLICY,
) -> LinearStats:
    """One-pass statistics over a row shard; ``weights`` masks padded rows.

    ``policy='bf16_f32acc'`` casts only the XᵀX/Xᵀy matmul operands
    (``linalg.policy_matmul``); the sums and count stay in the carry dtype."""
    if weights is not None:
        xw = x * weights[:, None]
        yw = y * weights
        count = jnp.sum(weights)
    else:
        xw, yw = x, y
        count = jnp.asarray(x.shape[0], x.dtype)
    return LinearStats(
        xtx=policy_matmul(x.T, xw, precision=precision, policy=policy),
        xty=policy_matmul(x.T, yw, precision=precision, policy=policy),
        x_sum=jnp.sum(xw, axis=0),
        y_sum=jnp.sum(yw),
        y_sq=jnp.sum(yw * y),
        count=count,
    )


def combine_linear_stats(a: LinearStats, b: LinearStats) -> LinearStats:
    return LinearStats(*(av + bv for av, bv in zip(a, b)))


def fold_linear_stats(
    carry: LinearStats,
    x: jax.Array,
    y: jax.Array,
    w: jax.Array,
    *,
    precision=DEFAULT_PRECISION,
    policy: str = DEFAULT_POLICY,
) -> LinearStats:
    """One streamed-fit fold step: carry + weighted stats of one chunk
    (``w`` is the instance-weight/pad-mask vector, 0.0 on pads)."""
    return combine_linear_stats(
        carry, linear_stats(x, y, w, precision=precision, policy=policy)
    )


def linear_fold_step(precision=DEFAULT_PRECISION, policy: str | None = None):
    """Cached jitted fold with the carry donated — the [n, n] normal-equation
    accumulator updates in place and the dispatch returns before the device
    fold completes (ops.linalg.gram_fold_step rationale). ``policy=None``
    resolves ``TPU_ML_PRECISION_POLICY`` before the cache lookup."""
    return _linear_fold_step(
        precision, resolve_policy(policy, allowed=FOLD_POLICIES)
    )


@lru_cache(maxsize=None)
def _linear_fold_step(precision, policy: str):
    def _step(carry, x, y, w):
        return fold_linear_stats(carry, x, y, w, precision=precision,
                                 policy=policy)

    return jax.jit(_step, donate_argnums=0)


def init_linear_carry(n: int, dtype) -> LinearStats:
    """Zero device-resident LinearStats carry for :func:`linear_fold_step`."""
    z = jnp.zeros
    return LinearStats(
        xtx=z((n, n), dtype),
        xty=z((n,), dtype),
        x_sum=z((n,), dtype),
        y_sum=z((), dtype),
        y_sq=z((), dtype),
        count=z((), dtype),
    )


def solve_normal(
    stats: LinearStats, *, reg_param: float = 0.0, fit_intercept: bool = True
) -> tuple[jax.Array, jax.Array]:
    """(coefficients [n], intercept []) from reduced statistics.

    With an intercept the normal equations are solved on centered moments
    (A = XᵀX − m·μμᵀ, b = Xᵀy − m·μȳ), which never penalizes the intercept;
    λ follows Spark ML's convention of scaling with the row count
    (regParam multiplies m so results match sklearn Ridge(alpha=λ·m)).
    """
    m = jnp.maximum(stats.count, jnp.ones_like(stats.count))
    n = stats.xtx.shape[0]
    lam = reg_param * m
    if fit_intercept:
        mu = stats.x_sum / m
        ybar = stats.y_sum / m
        a = stats.xtx - m * jnp.outer(mu, mu)
        b = stats.xty - m * mu * ybar
    else:
        a = stats.xtx
        b = stats.xty
    a = a + lam * jnp.eye(n, dtype=a.dtype)
    coef = jax.scipy.linalg.solve(a, b, assume_a="pos")
    # Rank-deficient designs (constant/collinear columns, λ=0) break the
    # Cholesky path with NaNs; fall back to the min-norm lstsq solution.
    # The [n, n] solve is negligible next to the data pass, so computing
    # the fallback unconditionally keeps this jittable (no host branch).
    coef_lstsq = jnp.linalg.lstsq(a, b)[0]
    coef = jnp.where(jnp.all(jnp.isfinite(coef)), coef, coef_lstsq)
    intercept = (
        stats.y_sum / m - jnp.dot(stats.x_sum / m, coef)
        if fit_intercept
        else jnp.zeros((), coef.dtype)
    )
    return coef, intercept


def _soft_threshold(v: jax.Array, thresh) -> jax.Array:
    return jnp.sign(v) * jnp.maximum(jnp.abs(v) - thresh, 0.0)


def _power_lam_max(a: jax.Array) -> jax.Array:
    """λmax estimate of PSD ``a`` via power iteration.

    FISTA's step 1/L is only covered by the convergence guarantee when the
    L estimate is ≥ λmax_true, and 32 fixed iterations can sit slightly
    below it when the spectral gap is small. Defenses, in order: use
    ‖a·v‖ of the final unit iterate (≥ the Rayleigh quotient, still ≤
    λmax), inflate by 5% (a marginally smaller step costs a few
    iterations; an underestimated L makes FISTA blow up silently), and
    clamp into the always-valid PSD envelope [trace/n, trace] — the lower
    edge catches a collapsed iteration (v0 ⊥ range(a), e.g.
    exactly-cancelling column pairs zero out a·1) by falling back to the
    trace upper bound, and the upper edge keeps the inflation from
    overshooting past a bound we know holds."""
    n = a.shape[0]

    def power_body(_, v):
        v = a @ v
        return v / jnp.maximum(jnp.linalg.norm(v), 1e-30)

    v0 = jnp.ones((n,), a.dtype) / jnp.sqrt(jnp.asarray(n, a.dtype))
    v = lax.fori_loop(0, 32, power_body, v0)
    norm_bound = jnp.linalg.norm(a @ v)
    tr = jnp.trace(a)
    est = 1.05 * norm_bound
    return jnp.where(est >= tr / n, jnp.minimum(est, tr), tr)


def _fista(grad, thresh, eta, w0, max_iter, tol):
    """Beck–Teboulle accelerated proximal gradient, tol-gated.

    Minimizes smooth(w) + ‖thresh/eta ⊙ w‖₁ given the smooth part's
    ``grad`` and step ``eta``; ``thresh`` is the per-coordinate (or
    scalar) soft-threshold ``eta·λ₁``. Stops when the relative coefficient
    change drops below ``tol`` or after ``max_iter`` iterations — one
    jittable ``lax.while_loop``.
    """

    def cond(carry):
        _, _, _, it, delta = carry
        return (it < max_iter) & (delta > tol)

    def body(carry):
        w, z, t, it, _ = carry
        w_new = _soft_threshold(z - eta * grad(z), thresh)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_new = w_new + ((t - 1.0) / t_new) * (w_new - w)
        delta = jnp.max(jnp.abs(w_new - w)) / jnp.maximum(
            jnp.max(jnp.abs(w_new)), 1e-12
        )
        return w_new, z_new, t_new, it + 1, delta

    init = (
        w0,
        w0,
        jnp.ones((), w0.dtype),
        jnp.int32(0),
        jnp.asarray(jnp.inf, w0.dtype),
    )
    w, _, _, _, _ = lax.while_loop(cond, body, init)
    return w


def solve_elastic_net(
    stats: LinearStats,
    *,
    reg_param: float,
    elastic_net_param: float,
    fit_intercept: bool = True,
    max_iter: int = 500,
    tol: float = 1e-8,
) -> tuple[jax.Array, jax.Array]:
    """(coefficients [n], intercept []) for the elastic-net objective, from
    the SAME reduced statistics as the closed-form path.

    Objective (Spark ML's convention, regParam=λ, elasticNetParam=α):

        1/(2m)·‖y − Xw − b₀‖² + λ·(α‖w‖₁ + (1−α)/2·‖w‖²)

    equivalently ``sklearn.linear_model.ElasticNet(alpha=λ, l1_ratio=α)``.
    (Contrast with :func:`solve_normal`'s pure-L2, where the repo matches
    ``Ridge(alpha=λ·m)`` — both are the Spark convention; Ridge's sklearn
    loss is unnormalized, ElasticNet's is 1/(2m)-normalized.)

    The L1 term has no closed form, but it does NOT need another data pass:
    the smooth gradient is (Aw − b)/m + λ(1−α)w with A/b the centered
    second moments already reduced over the cluster, so the whole FISTA
    loop (accelerated proximal gradient, Beck & Teboulle) runs replicated
    on the tiny [n, n] problem — one distributed statistics pass, zero
    per-iteration communication. The step size is 1/L with
    L = λmax(A)/m + λ(1−α) from a fixed power-iteration loop; everything is
    one jittable ``lax.while_loop`` (no data-dependent Python control flow).

    Not implemented in the reference family at all; pyspark.ml gets it via
    breeze OWL-QN over full data passes per iteration.
    """
    _check_alpha(elastic_net_param)
    m = jnp.maximum(stats.count, jnp.ones_like(stats.count))
    n = stats.xtx.shape[0]
    if fit_intercept:
        mu = stats.x_sum / m
        ybar = stats.y_sum / m
        a = stats.xtx - m * jnp.outer(mu, mu)
        b = stats.xty - m * mu * ybar
    else:
        a = stats.xtx
        b = stats.xty
    lam1 = reg_param * elastic_net_param
    lam2 = reg_param * (1.0 - elastic_net_param)

    # Lipschitz constant of the smooth part: λmax(A)/m + λ₂ (power
    # iteration with the PSD trace fallback — _power_lam_max).
    lip = _power_lam_max(a) / m + lam2
    eta = 1.0 / jnp.maximum(lip, 1e-30)

    def grad(w):
        return (a @ w - b) / m + lam2 * w

    w0 = jnp.zeros((n,), a.dtype)
    coef = _fista(grad, eta * lam1, eta, w0, max_iter, tol)
    intercept = (
        stats.y_sum / m - jnp.dot(stats.x_sum / m, coef)
        if fit_intercept
        else jnp.zeros((), coef.dtype)
    )
    return coef, intercept


def solve_from_stats(
    stats: LinearStats,
    *,
    reg_param: float = 0.0,
    elastic_net_param: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 500,
    tol: float = 1e-8,
) -> tuple[jax.Array, jax.Array]:
    """Dispatch the linear solve on the reduced statistics: closed-form
    normal equations for pure L2 (α=0), FISTA for any L1 mixture. Every
    data path (core partitions, Spark driver-merge, barrier mesh, in-core
    mesh) funnels through here, so elastic net works on all of them from
    the same one-pass monoid."""
    if elastic_net_param == 0.0:
        return solve_normal(
            stats, reg_param=reg_param, fit_intercept=fit_intercept
        )
    return solve_elastic_net(
        stats,
        reg_param=reg_param,
        elastic_net_param=elastic_net_param,
        fit_intercept=fit_intercept,
        max_iter=max_iter,
        tol=tol,
    )


def predict_linear(
    x: jax.Array, coef: jax.Array, intercept: jax.Array, *, precision=DEFAULT_PRECISION
) -> jax.Array:
    return jnp.matmul(x, coef, precision=precision) + intercept


# ---------------------------------------------------------------------------
# Logistic regression (IRLS / Newton)
# ---------------------------------------------------------------------------


class NewtonStats(NamedTuple):
    """One Newton iteration's sufficient statistics over a row shard."""

    hess: jax.Array  # [d, d] — XᵀWX, W = p(1−p)
    grad: jax.Array  # [d]   — Xᵀ(y − p)
    loss: jax.Array  # []    — Σ log-loss
    count: jax.Array  # []


def combine_newton_stats(a: NewtonStats, b: NewtonStats) -> NewtonStats:
    return NewtonStats(*(av + bv for av, bv in zip(a, b)))


def logistic_newton_stats(
    x_aug: jax.Array,
    y: jax.Array,
    w_full: jax.Array,
    weights: jax.Array | None = None,
    *,
    precision=DEFAULT_PRECISION,
) -> NewtonStats:
    """Local gradient/Hessian/log-loss at ``w_full`` over an augmented shard.

    ``x_aug`` is [rows, d] with the intercept column appended (d = n+1 when
    fitting an intercept); ``w_full`` is the full [d] parameter vector.
    """
    z = jnp.matmul(x_aug, w_full, precision=precision)
    p = jax.nn.sigmoid(z)
    mask = (
        weights
        if weights is not None
        else jnp.ones(x_aug.shape[0], x_aug.dtype)
    )
    resid = (y - p) * mask
    w = p * (1.0 - p) * mask
    # log-loss via logaddexp for stability: log(1+e^z) − y·z
    loss = jnp.sum((jnp.logaddexp(0.0, z) - y * z) * mask)
    hess = jnp.matmul(x_aug.T * w[None, :], x_aug, precision=precision)
    grad = jnp.matmul(x_aug.T, resid, precision=precision)
    return NewtonStats(
        hess=hess,
        grad=grad,
        loss=loss,
        count=jnp.sum(mask),
    )


def svc_newton_stats(
    x_aug: jax.Array,
    y: jax.Array,
    w_full: jax.Array,
    weights: jax.Array | None = None,
    *,
    precision=DEFAULT_PRECISION,
) -> NewtonStats:
    """Squared-hinge (L2-SVM) Newton statistics over an augmented shard —
    the LinearSVC loss (cuML/sklearn's default; pyspark.ml's LinearSVC
    minimizes the non-smooth plain hinge with OWLQN, but the squared hinge
    is smooth, so the SAME IRLS/Newton machinery as logistic applies and
    converges in a handful of data passes).

    Labels arrive 0/1 (the Spark label contract) and map to ±1. With
    margin mᵢ = 1 − ŷᵢ·zᵢ and the active set mᵢ > 0:

        loss  = Σ cᵢ·mᵢ²                       (active)
        grad  = Σ 2cᵢ·ŷᵢ·mᵢ·xᵢ                 (ascent of −loss, active)
        hess  = Σ 2cᵢ·xᵢxᵢᵀ                    (active)

    — the same NewtonStats monoid as logistic, so every reducer
    (tree-aggregate, mesh psum, chunked checkpoints) applies unchanged.
    """
    z = jnp.matmul(x_aug, w_full, precision=precision)
    yy = 2.0 * y - 1.0
    c = (
        weights
        if weights is not None
        else jnp.ones(x_aug.shape[0], x_aug.dtype)
    )
    margin = jnp.maximum(1.0 - yy * z, 0.0)
    wa = 2.0 * c * (margin > 0)
    hess = jnp.matmul(x_aug.T * wa[None, :], x_aug, precision=precision)
    grad = jnp.matmul(x_aug.T, 2.0 * c * yy * margin, precision=precision)
    loss = jnp.sum(c * margin * margin)
    return NewtonStats(hess=hess, grad=grad, loss=loss, count=jnp.sum(c))


def _check_alpha(elastic_net_param: float) -> None:
    if not 0.0 <= elastic_net_param <= 1.0:
        raise ValueError(
            f"elastic_net_param must be in [0, 1], got {elastic_net_param}"
        )


def _regularized_newton_solve(
    w: jax.Array,
    hess: jax.Array,
    grad: jax.Array,
    pen: jax.Array,
    m: jax.Array,
    reg_param: float,
    elastic_net_param: float,
) -> tuple[jax.Array, jax.Array]:
    """Shared Newton-step tail for the binary AND softmax paths: closed-form
    solve at α=0, warm-started FISTA prox step otherwise. ``hess``/``grad``
    arrive with the L2 fold and the eps ridge already applied; ``grad`` is
    the ASCENT direction of the smooth model.

    Divergence guard: an unregularized fit on linearly separable data has
    no finite maximizer — the iterates grow until z=x·w overflows and the
    solve turns NaN. A non-finite proposal is rejected in favor of the
    incoming iterate, with the step-norm set to **NaN as a sentinel**:
    ``NaN > tol`` is False, so every tol-gated while_loop exits at the last
    finite iterate (the same "big finite weights, no error" outcome
    Spark's LBFGS gives separable data) — and the host can distinguish the
    outcome from a clean converge (:func:`check_newton_outcome` raises when
    the rejection happened on the very first step from the zero init, which
    means the DATA carried non-finite values, not that the fit diverged)."""
    if elastic_net_param == 0.0:
        delta = jax.scipy.linalg.solve(hess, grad, assume_a="pos")
        new_w, step = w + delta, jnp.linalg.norm(delta)
    else:
        lam1 = reg_param * elastic_net_param * m
        eta = 1.0 / jnp.maximum(_power_lam_max(hess), 1e-30)

        def sub_grad(z):
            return hess @ (z - w) - grad

        new_w = _fista(sub_grad, eta * lam1 * pen, eta, w, 200, 1e-10)
        step = jnp.linalg.norm(new_w - w)
    ok = jnp.isfinite(step) & jnp.all(jnp.isfinite(new_w))
    nan = jnp.asarray(jnp.nan, step.dtype)
    return jnp.where(ok, new_w, w), jnp.where(ok, step, nan)


def check_newton_outcome(step_norm, w) -> None:
    """Host-side decode of the Newton loops' final (step, w).

    NaN step + all-zero parameters means the FIRST step from the zero init
    was already non-finite — the input data contains NaN/Inf (a zero
    gradient at init would have produced step 0, not NaN) — so raise a
    diagnosable error instead of returning an all-zero model that silently
    predicts one class everywhere. NaN step with nonzero parameters is the
    separable-divergence outcome: the model holds the last finite iterate,
    which is the accepted behavior (see _regularized_newton_solve)."""
    import numpy as np

    if np.isnan(float(np.asarray(step_norm))) and not np.asarray(w).any():
        raise ValueError(
            "the first Newton step produced non-finite statistics from the "
            "zero initialization — the features, labels, or instance "
            "weights contain NaN/Inf values; clean or impute them before "
            "fit"
        )


def newton_update(
    w_full: jax.Array,
    stats: NewtonStats,
    *,
    reg_param: float = 0.0,
    elastic_net_param: float = 0.0,
    fit_intercept: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """One Newton / proximal-Newton step: (new w, step-norm).

    Regularization follows the LinearRegression convention (Spark ML's):
    λ=regParam, α=elasticNetParam, objective

        (1/m)·Σ logloss + λ·(α‖w‖₁ + (1−α)/2·‖w‖²)

    with the intercept coordinate (last, when ``fit_intercept``) exempt
    from both penalties. α=0 is the exact closed-form IRLS step. α>0 is a
    **proximal Newton** step (Lee/Sun/Saunders): the L1 term has no
    closed-form solve, so the step minimizes the local quadratic model +
    L1 via FISTA on the replicated [d, d] Hessian — the distributed part
    of an iteration (the NewtonStats psum) is UNCHANGED, so L1 logistic
    costs the same communication per iteration as L2.
    """
    _check_alpha(elastic_net_param)
    d = w_full.shape[0]
    m = jnp.maximum(stats.count, jnp.ones_like(stats.count))
    pen = jnp.ones((d,), w_full.dtype)
    if fit_intercept:
        pen = pen.at[-1].set(0.0)
    lam2 = reg_param * (1.0 - elastic_net_param) * m * pen
    hess = stats.hess + jnp.diag(lam2)
    grad = stats.grad - lam2 * w_full  # ascent direction of the smooth part
    # √eps-scaled ridge keeps the solve well-posed when classes separate
    # perfectly, sized to the dtype so f32 rounding can't flip the Cholesky
    # (√eps(f64) ≈ 1.5e-8 — f64 behavior unchanged)
    eps = jnp.sqrt(jnp.finfo(hess.dtype).eps) * jnp.trace(hess) / d
    hess = hess + eps * jnp.eye(d, dtype=hess.dtype)
    return _regularized_newton_solve(
        w_full, hess, grad, pen, m, reg_param, elastic_net_param
    )


def predict_logistic_proba(
    x: jax.Array, coef: jax.Array, intercept: jax.Array, *, precision=DEFAULT_PRECISION
) -> jax.Array:
    return jax.nn.sigmoid(
        jnp.matmul(x, coef, precision=precision) + intercept
    )


# ---------------------------------------------------------------------------
# Multinomial (softmax) logistic regression — full-Newton IRLS
# ---------------------------------------------------------------------------


class SoftmaxStats(NamedTuple):
    """One softmax-Newton iteration's statistics over a row shard.

    The Hessian is the full [C·d, C·d] Fisher information — C(C+1)/2
    distinct [d, d] blocks H[c,c'] = Xᵀ diag(w·p_c(δ_cc' − p_c')) X, each one
    MXU matmul. C·d stays modest for classical multiclass problems (e.g.
    C=10, d=513 → 5130² ≈ 26M entries), and the full Newton keeps the
    quadratic convergence the binary path has.
    """

    hess: jax.Array  # [C·d, C·d]
    grad: jax.Array  # [C·d] — flattened [C, d]
    loss: jax.Array  # []
    count: jax.Array  # []


def combine_softmax_stats(a: SoftmaxStats, b: SoftmaxStats) -> SoftmaxStats:
    return SoftmaxStats(*(av + bv for av, bv in zip(a, b)))


def softmax_newton_stats(
    x_aug: jax.Array,
    y_idx: jax.Array,
    w_flat: jax.Array,
    n_classes: int,
    weights: jax.Array | None = None,
    *,
    precision=DEFAULT_PRECISION,
) -> SoftmaxStats:
    """Gradient/Hessian/NLL of the softmax model at ``w_flat`` over a shard.

    ``x_aug`` [rows, d] (intercept column appended when fitting one),
    ``y_idx`` [rows] integer class labels in [0, C), ``w_flat`` [C·d].
    """
    rows, d = x_aug.shape
    c = n_classes
    w = w_flat.reshape(c, d)
    mask = (
        weights if weights is not None else jnp.ones(rows, x_aug.dtype)
    )
    logits = jnp.matmul(x_aug, w.T, precision=precision)  # [rows, C]
    logz = jax.scipy.special.logsumexp(logits, axis=1)
    p = jnp.exp(logits - logz[:, None])  # [rows, C]
    onehot = jax.nn.one_hot(y_idx, c, dtype=x_aug.dtype)
    loss = jnp.sum((logz - jnp.sum(onehot * logits, axis=1)) * mask)
    resid = (onehot - p) * mask[:, None]  # [rows, C]
    grad = jnp.matmul(resid.T, x_aug, precision=precision).reshape(-1)

    # Hessian blocks, upper triangle: H[c,c'] = Xᵀ diag(v_cc') X with
    # v_cc' = w·p_c(δ − p_c'). The pair loop unrolls at trace time —
    # C(C+1)/2 MXU matmuls.
    blocks = [[None] * c for _ in range(c)]
    for ci in range(c):
        for cj in range(ci, c):
            delta = 1.0 if ci == cj else 0.0
            v = mask * p[:, ci] * (delta - p[:, cj])
            blk = jnp.matmul(x_aug.T * v[None, :], x_aug, precision=precision)
            blocks[ci][cj] = blk
            if ci != cj:
                blocks[cj][ci] = blk.T
    hess = jnp.block(blocks)
    return SoftmaxStats(
        hess=hess,
        grad=grad,
        loss=loss,
        count=jnp.sum(mask),
    )


def softmax_newton_update(
    w_flat: jax.Array,
    stats: SoftmaxStats,
    n_classes: int,
    *,
    reg_param: float = 0.0,
    elastic_net_param: float = 0.0,
    fit_intercept: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """One Newton / proximal-Newton step on the flattened [C·d] parameter.

    L2 penalizes every coordinate except the per-class intercepts. The
    softmax parameterization has a flat direction (adding any vector to all
    classes leaves p unchanged); the L2 penalty pins the coefficients and the
    eps ridge pins the unpenalized intercept-shift direction — gradients are
    zero along it, so the regularized solve simply doesn't move there.
    α>0 swaps the closed-form solve for the same warm-started FISTA
    subproblem as the binary :func:`newton_update` — the per-class-coordinate
    L1 prox is the elementwise soft-threshold on the flat vector, so nothing
    about the C-class block structure changes. (With α=1 the L1 term alone
    does NOT pin the flat direction, but the prox is applied to a Newton
    model whose Hessian carries the eps ridge, and FISTA is warm-started at
    the current w — the step stays well-posed the same way the L2 path's
    ridge-only intercept direction does.)
    """
    _check_alpha(elastic_net_param)
    cd = w_flat.shape[0]
    d = cd // n_classes
    m = jnp.maximum(stats.count, jnp.ones_like(stats.count))
    pen = jnp.ones((n_classes, d), w_flat.dtype)
    if fit_intercept:
        pen = pen.at[:, -1].set(0.0)
    pen = pen.reshape(-1)
    lam2 = reg_param * (1.0 - elastic_net_param) * m * pen
    hess = stats.hess + jnp.diag(lam2)
    grad = stats.grad - lam2 * w_flat
    # √eps-scaled ridge: the exact Fisher matrix is PSD with a ZERO
    # eigenvalue along the class-shift flat direction, and dtype rounding
    # makes it slightly indefinite (measured ~-5e-5 in f32) — a fixed 1e-8
    # ridge NaNs the f32 Cholesky on the first step. √eps(f64) ≈ 1.5e-8, so
    # f64 behavior is unchanged.
    eps = jnp.sqrt(jnp.finfo(hess.dtype).eps) * jnp.trace(hess) / cd
    hess = hess + eps * jnp.eye(cd, dtype=hess.dtype)
    return _regularized_newton_solve(
        w_flat, hess, grad, pen, m, reg_param, elastic_net_param
    )


def predict_softmax_proba(
    x: jax.Array,
    coef: jax.Array,
    intercept: jax.Array,
    *,
    precision=DEFAULT_PRECISION,
) -> jax.Array:
    """[rows, C] class probabilities; ``coef`` [C, n], ``intercept`` [C]."""
    logits = jnp.matmul(x, coef.T, precision=precision) + intercept[None, :]
    return jax.nn.softmax(logits, axis=1)
