"""KMeans device kernels — the stretch estimator (BASELINE.json config 5).

The reference family's KMeans runs RAFT pairwise-distance + argmin kernels
on GPU; the TPU-native formulation puts both hot ops on the MXU:

- distances: ‖x−c‖² expanded to ‖x‖² + ‖c‖² − 2·x·cᵀ — the cross term is a
  [rows, n]×[n, k] matmul;
- centroid accumulation: scatter-by-label recast as a one-hot matmul
  onehotᵀ·x ([k, rows]×[rows, n]) — a second MXU pass instead of the GPU's
  atomic scatters, which TPUs don't like.

Row blocks are processed under ``lax.scan`` so the [block, k] distance and
one-hot tiles stay bounded in VMEM/HBM regardless of partition size (rows·k
would otherwise explode at k=1000). Per-partition ``KMeansStats`` are the
usual commutative monoid, reduced by the same tree/psum machinery as PCA.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.autotune.policy import PrecisionPolicy
from spark_rapids_ml_tpu.ops.linalg import (
    DEFAULT_PRECISION,
    DEFAULT_POLICY,
    int8_quantized_matmul,
    policy_matmul,
)


class KMeansStats(NamedTuple):
    """Sufficient statistics of one Lloyd iteration over a row shard."""

    sums: jax.Array  # [k, n] — per-cluster feature sums
    counts: jax.Array  # [k]   — per-cluster row counts
    cost: jax.Array  # []    — sum of min squared distances (inertia)


def combine_kmeans_stats(a: KMeansStats, b: KMeansStats) -> KMeansStats:
    return KMeansStats(a.sums + b.sums, a.counts + b.counts, a.cost + b.cost)


def pairwise_sq_dists(
    x: jax.Array, centers: jax.Array, *, precision=DEFAULT_PRECISION,
    policy: str = DEFAULT_POLICY,
) -> jax.Array:
    """[rows, k] squared distances via the MXU cross-term expansion.

    Only the cross term honors the precision ``policy`` (bf16 operands or
    the opt-in int8 quantized path); the row/center norms stay full
    precision, so ranking error is bounded by the cross-term quantization
    alone."""
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)
    c_sq = jnp.sum(centers * centers, axis=1)[None, :]
    if policy == PrecisionPolicy.INT8_DIST.value:
        cross = int8_quantized_matmul(x, centers.T)
    else:
        cross = policy_matmul(x, centers.T, precision=precision,
                              policy=policy)
    return jnp.clip(x_sq + c_sq - 2.0 * cross, 0.0, None)


def assign_clusters(
    x: jax.Array, centers: jax.Array, *, precision=DEFAULT_PRECISION,
    policy: str = DEFAULT_POLICY,
) -> tuple[jax.Array, jax.Array]:
    """(labels [rows], min squared distances [rows])."""
    d = pairwise_sq_dists(x, centers, precision=precision, policy=policy)
    return jnp.argmin(d, axis=1), jnp.min(d, axis=1)


@partial(jax.jit, static_argnames=("block_rows", "policy"))
def kmeans_stats(
    x: jax.Array,
    centers: jax.Array,
    weights: jax.Array | None = None,
    *,
    block_rows: int = 8192,
    policy: str = DEFAULT_POLICY,
) -> KMeansStats:
    """One Lloyd accumulation pass over a row shard, scanned in blocks.

    ``weights`` masks padded rows (0 weight) so shape bucketing stays exact.
    """
    rows, n = x.shape
    k = centers.shape[0]
    if weights is None:
        weights = jnp.ones((rows,), x.dtype)

    pad = (-rows) % block_rows
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        weights = jnp.pad(weights, (0, pad))
    nb = x.shape[0] // block_rows
    xb = x.reshape(nb, block_rows, n)
    wb = weights.reshape(nb, block_rows)

    def step(carry, blk):
        sums, counts, cost = carry
        xi, wi = blk
        labels, dists = assign_clusters(xi, centers, policy=policy)
        onehot = (
            labels[:, None] == jnp.arange(k, dtype=labels.dtype)[None, :]
        ).astype(x.dtype) * wi[:, None]
        sums = sums + jnp.matmul(onehot.T, xi, precision=DEFAULT_PRECISION)
        counts = counts + jnp.sum(onehot, axis=0)
        cost = cost + jnp.sum(dists * wi)
        return (sums, counts, cost), None

    init = (
        jnp.zeros((k, n), x.dtype),
        jnp.zeros((k,), x.dtype),
        jnp.zeros((), x.dtype),
    )
    (sums, counts, cost), _ = lax.scan(step, init, (xb, wb))
    return KMeansStats(sums, counts, cost)


def update_centers(stats: KMeansStats, old_centers: jax.Array) -> jax.Array:
    """New centroids = sums/counts; empty clusters keep their old center
    (Spark MLlib behavior)."""
    counts = stats.counts[:, None]
    safe = jnp.where(counts > 0, counts, jnp.ones_like(counts))
    return jnp.where(counts > 0, stats.sums / safe, old_centers)


def center_shift_sq(old: jax.Array, new: jax.Array) -> jax.Array:
    """Max squared movement of any centroid — the convergence criterion."""
    return jnp.max(jnp.sum((old - new) ** 2, axis=1))


def kmeans_plus_plus_init(
    key: jax.Array, x: jax.Array, k: int, *, precision=DEFAULT_PRECISION
) -> jax.Array:
    """k-means++ seeding on a (sub)sample, fully jittable.

    D²-weighted sequential sampling (Arthur & Vassilvitskii); the estimator
    layer samples the dataset down before calling so rows stays modest —
    the same role Spark's k-means|| plays for its distributed init. The
    unweighted special case of ``weighted_kmeans_plus_plus_init``.
    """
    return weighted_kmeans_plus_plus_init(
        key, x, jnp.ones((x.shape[0],), x.dtype), k, precision=precision
    )


def min_sq_dists(
    x: jax.Array, centers: jax.Array, *, precision=DEFAULT_PRECISION,
    policy: str = DEFAULT_POLICY,
) -> jax.Array:
    """[rows] squared distance of each row to its nearest center."""
    return jnp.min(
        pairwise_sq_dists(x, centers, precision=precision, policy=policy),
        axis=1,
    )


def weighted_kmeans_plus_plus_init(
    key: jax.Array,
    x: jax.Array,
    w: jax.Array,
    k: int,
    *,
    precision=DEFAULT_PRECISION,
) -> jax.Array:
    """Weighted k-means++ — the finishing step of k-means‖ (Bahmani et al.,
    §3.4): reduce the oversampled candidate set to k seeds, sampling ∝ w·D².

    ``w`` are candidate weights (how many data rows each candidate owns);
    zero-weight candidates can never be drawn.
    """
    rows = x.shape[0]
    w = w.astype(x.dtype)
    tiny = jnp.finfo(x.dtype).tiny

    key, sub = jax.random.split(key)
    first = jax.random.choice(sub, rows, p=w / jnp.maximum(jnp.sum(w), tiny))
    centers0 = jnp.zeros((k, x.shape[1]), x.dtype).at[0].set(x[first])
    d0 = jnp.sum((x - centers0[0][None, :]) ** 2, axis=1)

    def body(i, carry):
        centers, dists, key = carry
        key, sub = jax.random.split(key)
        scores = w * dists
        probs = scores / jnp.maximum(jnp.sum(scores), tiny)
        idx = jax.random.choice(sub, rows, p=probs)
        c = x[idx]
        centers = centers.at[i].set(c)
        d_new = jnp.sum((x - c[None, :]) ** 2, axis=1)
        return centers, jnp.minimum(dists, d_new), key

    centers, _, _ = lax.fori_loop(1, k, body, (centers0, d0, key))
    return centers
