"""UMAP device kernels — fuzzy k-NN graph + SGD layout as XLA programs.

The spark-rapids-ml family ships UMAP on cuML's GPU implementation
(McInnes et al., arXiv:1802.03426); the 22.12 reference this framework
re-designs stops at PCA (SURVEY.md §2), so this is a capability-add
completing the family surface. TPU-first formulation:

- the k-NN graph comes from this package's exact brute-force kernel
  (ops/neighbors.py) — one MXU-bound tournament, no ANN trees;
- per-point (rho, sigma) calibration is VECTORIZED BISECTION: all rows
  solve Σ_j exp(−max(0, d_ij − rho_i)/σ_i) = log2(k) simultaneously for a
  fixed 64 halvings (umap-learn's SMOOTH_K_TOLERANCE loop, but with no
  data-dependent trip count — XLA wants static control flow);
- the layout optimizer runs the reference force model (attractive
  −2ab·d^{2(b−1)}/(1+a·d^{2b}) along graph edges on their
  epochs_per_sample schedule, repulsive 2b/((ε+d²)(1+a·d^{2b})) against
  uniform negative samples, both clipped to ±4, lr annealed linearly) as
  ONE ``lax.fori_loop`` program over epochs: every epoch processes the
  full fixed-shape [E] edge list with masks for edges not yet due —
  dense vector math + two segment-sum scatters instead of umap-learn's
  per-edge Python/numba loop.

Determinism: negative samples derive from ``fold_in(key, epoch)``; the
whole embedding is a pure function of (graph, init, key).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SMOOTH_K_TARGET_ITERS = 64
MIN_K_DIST_SCALE = 1e-3
_GRAD_CLIP = 4.0


@partial(jax.jit, static_argnames=())
def smooth_knn_calibration(
    knn_dists: jax.Array,  # [n, k] ascending, self possibly at col 0
) -> tuple[jax.Array, jax.Array]:
    """(rho [n], sigma [n]) — umap-learn's smooth_knn_dist, vectorized.

    rho_i = smallest POSITIVE neighbor distance; sigma_i solves
    Σ_j exp(−max(0, d_ij − rho_i)/σ_i) = log2(k) by bisection (64 fixed
    halvings ≈ 1e−19 interval — far past float precision).
    """
    n, k = knn_dists.shape
    # k is a Python int from .shape — static under tracing, no sync
    # tpulint: disable=TPL002
    target = jnp.log2(jnp.asarray(float(k), knn_dists.dtype))
    pos = jnp.where(knn_dists > 0, knn_dists, jnp.inf)
    rho = jnp.min(pos, axis=1)
    rho = jnp.where(jnp.isfinite(rho), rho, 0.0)

    def mass(sigma):
        d = jnp.maximum(knn_dists - rho[:, None], 0.0)
        return jnp.sum(jnp.exp(-d / sigma[:, None]), axis=1)

    lo = jnp.full((n,), 1e-12, knn_dists.dtype)
    hi = jnp.full((n,), 1e4, knn_dists.dtype)

    def body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        too_small = mass(mid) < target  # need larger sigma
        return jnp.where(too_small, mid, lo), jnp.where(too_small, hi, mid)

    lo, hi = lax.fori_loop(0, SMOOTH_K_TARGET_ITERS, body, (lo, hi))
    sigma = 0.5 * (lo + hi)
    # umap-learn floors sigma at MIN_K_DIST_SCALE × mean distance
    mean_d = jnp.mean(knn_dists)
    return rho, jnp.maximum(sigma, MIN_K_DIST_SCALE * mean_d)


def membership_strengths(
    knn_dists: jax.Array, rho: jax.Array, sigma: jax.Array
) -> jax.Array:
    """[n, k] directed fuzzy membership exp(−max(0, d−rho)/sigma)."""
    d = jnp.maximum(knn_dists - rho[:, None], 0.0)
    w = jnp.exp(-d / sigma[:, None])
    return jnp.where(knn_dists > 0, w, 1.0)  # self/duplicate → full strength


def fuzzy_union_edges(
    knn_idx: np.ndarray,  # [n, k]
    weights: np.ndarray,  # [n, k]
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Symmetrize the directed graph (w ∪ wᵀ: a+b−ab) into a padded edge
    list (heads [E], tails [E], weights [E]) — host-side NumPy, O(nk),
    done once at fit.

    Self-edges are dropped (they exert no layout force)."""
    n, k = knn_idx.shape
    heads = np.repeat(np.arange(n, dtype=np.int64), k)
    tails = knn_idx.reshape(-1).astype(np.int64)
    vals = weights.reshape(-1).astype(np.float64)
    keep = heads != tails
    heads, tails, vals = heads[keep], tails[keep], vals[keep]
    # directed weight lookup table via lexsort on (head, tail)
    import scipy.sparse as sp

    A = sp.coo_matrix((vals, (heads, tails)), shape=(n, n)).tocsr()
    A.sum_duplicates()
    At = A.T.tocsr()
    U = A + At - A.multiply(At)  # fuzzy set union
    Uc = U.tocoo()
    keep = Uc.row < Uc.col  # undirected: keep each pair once
    return (
        Uc.row[keep].astype(np.int32),
        Uc.col[keep].astype(np.int32),
        Uc.data[keep].astype(np.float64),
    )


def find_ab_params(spread: float, min_dist: float) -> tuple[float, float]:
    """Fit the (a, b) of 1/(1+a·x^{2b}) to the target membership curve —
    umap-learn's find_ab_params, via scipy curve_fit."""
    from scipy.optimize import curve_fit

    xv = np.linspace(0, spread * 3, 300)
    yv = np.where(
        xv < min_dist, 1.0, np.exp(-(xv - min_dist) / spread)
    )
    params, _ = curve_fit(
        lambda x, a, b: 1.0 / (1.0 + a * x ** (2 * b)), xv, yv,
        maxfev=5000,
    )
    return float(params[0]), float(params[1])


@partial(
    jax.jit,
    static_argnames=("n_epochs", "n_neg", "move_tails"),
)
def optimize_layout(
    key: jax.Array,
    embedding: jax.Array,  # [n, dim] init
    heads: jax.Array,  # [E] int32
    tails: jax.Array,  # [E] int32
    epochs_per_sample: jax.Array,  # [E] float
    a: jax.Array,
    b: jax.Array,
    *,
    n_epochs: int,
    n_neg: int = 5,
    initial_lr: float = 1.0,
    move_tails: bool = True,
) -> jax.Array:
    """The UMAP SGD layout loop as one XLA program.

    Per epoch every edge computes its force, masked by the
    epochs_per_sample schedule (edge e fires when its accumulated
    next-due counter ≤ epoch — the reference schedule, carried as [E]
    state); tail points receive the opposite attractive force
    (``move_tails``; False for transform(), where reference points stay
    fixed). Updates land via segment-sum scatter-adds.
    """
    n, dim = embedding.shape
    E = heads.shape[0]
    fdt = embedding.dtype
    eps = jnp.asarray(1e-3, fdt)

    def epoch_step(epoch, carry):
        y, next_due = carry
        alpha = initial_lr * (1.0 - epoch / n_epochs)
        due = next_due <= epoch  # [E]

        yh = y[heads]
        yt = y[tails]
        diff = yh - yt
        d2 = jnp.sum(diff * diff, axis=1)
        # attractive: −2ab·d^{2(b−1)} / (1 + a·d^{2b})
        grad_coeff = jnp.where(
            d2 > 0,
            (-2.0 * a * b * d2 ** (b - 1.0)) / (a * d2 ** b + 1.0),
            0.0,
        )
        g = jnp.clip(grad_coeff[:, None] * diff, -_GRAD_CLIP, _GRAD_CLIP)
        g = jnp.where(due[:, None], g, 0.0) * alpha
        y = y.at[heads].add(g)
        if move_tails:
            y = y.at[tails].add(-g)

        # negative samples: n_neg uniform points per due edge
        kk = jax.random.fold_in(key, epoch)
        neg = jax.random.randint(kk, (E, n_neg), 0, n)
        yh2 = y[heads]  # re-read after attractive update
        yneg = y[neg]  # [E, n_neg, dim]
        diffn = yh2[:, None, :] - yneg
        d2n = jnp.sum(diffn * diffn, axis=2)
        rep = (2.0 * b) / ((eps + d2n) * (a * d2n ** b + 1.0))
        gn = jnp.clip(rep[:, :, None] * diffn, -_GRAD_CLIP, _GRAD_CLIP)
        # zero-distance negatives get the reference's unit kick
        gn = jnp.where(d2n[:, :, None] > 0, gn, _GRAD_CLIP)
        gn = jnp.where(
            (due[:, None] & (neg != heads[:, None]))[:, :, None], gn, 0.0
        )
        y = y.at[heads].add(alpha * jnp.sum(gn, axis=1))

        next_due = jnp.where(due, next_due + epochs_per_sample, next_due)
        return y, next_due

    # first fire at ≈epochs_per_sample, matching the reference's
    # epoch_of_next_sample initialization
    y, _ = lax.fori_loop(
        0, n_epochs, epoch_step, (embedding, epochs_per_sample)
    )
    return y


def spectral_init(
    heads: np.ndarray,
    tails: np.ndarray,
    weights: np.ndarray,
    n: int,
    dim: int,
    seed: int,
) -> np.ndarray:
    """Symmetric-normalized-Laplacian eigenvector init (umap-learn's
    'spectral'), via scipy sparse eigsh on the host — the graph is k-sparse
    and the decomposition is a one-off fit cost. Falls back to scaled
    random on convergence failure."""
    import scipy.sparse as sp
    import scipy.sparse.linalg as spl

    rng = np.random.default_rng(seed)
    try:
        W = sp.coo_matrix(
            (
                np.concatenate([weights, weights]),
                (
                    np.concatenate([heads, tails]),
                    np.concatenate([tails, heads]),
                ),
            ),
            shape=(n, n),
        ).tocsr()
        deg = np.asarray(W.sum(axis=1)).reshape(-1)
        dinv = 1.0 / np.sqrt(np.maximum(deg, 1e-12))
        L = sp.identity(n) - sp.diags(dinv) @ W @ sp.diags(dinv)
        k_eig = dim + 1
        vals, vecs = spl.eigsh(
            L, k=k_eig, which="SM", tol=1e-4, maxiter=n * 5,
            v0=rng.normal(size=n),
        )
        order = np.argsort(vals)[1 : dim + 1]  # drop the trivial 0-vector
        emb = vecs[:, order]
        # umap-learn scales spectral init to ~[-10, 10] and jitters
        expansion = 10.0 / np.abs(emb).max()
        return emb * expansion + rng.normal(scale=1e-4, size=emb.shape)
    except Exception:
        return rng.uniform(-10, 10, size=(n, dim))
