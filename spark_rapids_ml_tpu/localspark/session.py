"""LocalSparkSession: createDataFrame + the worker-process pool behind
``mapInArrow`` (see ``worker.py`` for the boundary-fidelity contract)."""

from __future__ import annotations

import atexit
import logging
import os
import subprocess
import sys
import tempfile
import threading
from typing import Any, Iterator

import numpy as np
import pyarrow as pa

from spark_rapids_ml_tpu.localspark import types as T
from spark_rapids_ml_tpu.localspark import worker as W
from spark_rapids_ml_tpu.utils import devicepolicy, knobs
from spark_rapids_ml_tpu.localspark.dataframe import (
    DataFrame,
    Row,
    _infer_type,
    dataframe_from_partitions,
)

logger = logging.getLogger("spark_rapids_ml_tpu")


class WorkerException(RuntimeError):
    """A mapInArrow plan function raised inside a worker process; carries the
    worker-side traceback (the analog of pyspark's PythonException)."""


class _Worker:
    """One reusable worker subprocess + its half of the framing protocol."""

    dead = False

    def __init__(self, extra_env: dict[str, str | None] | None = None):
        env = devicepolicy.apply_overrides(os.environ, extra_env or {})
        self._probe_armed = bool(env.get(devicepolicy.PROBE_VAR))
        self._tasks_done = 0
        self._stderr = tempfile.NamedTemporaryFile(
            mode="w+b", prefix="localspark-worker-", suffix=".log", delete=False
        )
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_ml_tpu.localspark.worker"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=self._stderr,
            env=env,
        )
        self._lock = threading.Lock()

    def run_task(
        self,
        fn_bytes: bytes,
        data: bytes,
        schema_bytes: bytes,
        context: dict | None = None,
        partition: int | None = None,
    ) -> bytes:
        trailer = b""
        with self._lock:
            try:
                out = self.proc.stdin
                if context is None:
                    out.write(W.MAGIC)
                else:
                    out.write(W.MAGIC_BARRIER)
                W.write_block(out, fn_bytes)
                W.write_block(out, data)
                W.write_block(out, schema_bytes)
                if context is not None:
                    import json

                    W.write_block(out, json.dumps(context).encode())
                out.flush()
                status = self.proc.stdout.read(1)
                if len(status) != 1:
                    raise EOFError
                payload = W.read_block(self.proc.stdout)
                if status == b"O":
                    # telemetry trailer: the worker's registry delta +
                    # timeline events for THIS task (worker.py framing doc)
                    trailer = W.read_block(self.proc.stdout)
            except (EOFError, BrokenPipeError, OSError) as e:
                self.dead = True  # session must not reuse this process
                try:  # EOF can precede process teardown: wait briefly for rc
                    rc = self.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    rc = None
                # the probe can only fail before the first task of an armed
                # worker — a later rc collision is an unrelated crash
                if (
                    rc == devicepolicy.PROBE_EXIT_CODE
                    and self._probe_armed
                    and self._tasks_done == 0
                ):
                    raise WorkerException(
                        "localspark worker failed its device-policy probe "
                        "(see utils/devicepolicy.py); stderr tail:\n"
                        + self._stderr_tail()
                    ) from e
                raise WorkerException(
                    f"localspark worker died mid-task (exit code {rc}); "
                    "stderr tail:\n" + self._stderr_tail()
                ) from e
        self._tasks_done += 1
        if status == b"E":
            import cloudpickle

            raise WorkerException(
                "mapInArrow plan function failed in the worker process:\n"
                + cloudpickle.loads(payload)
            )
        self._merge_telemetry(trailer, partition)
        return payload

    @staticmethod
    def _merge_telemetry(trailer: bytes, partition: int | None) -> None:
        """Fold a worker's telemetry trailer into the driver's registry and
        flight-recorder timeline, labeling every series/event with the
        partition it came from. Best-effort by design: a malformed trailer
        is logged and dropped, never failing the task that produced it."""
        if not trailer:
            return
        try:
            import json
            import time

            from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
            from spark_rapids_ml_tpu.telemetry.timeline import TIMELINE

            t = json.loads(trailer)
            label = "" if partition is None else str(partition)
            if t.get("registry"):
                REGISTRY.merge_wire(t["registry"], partition=label)
            if t.get("events"):
                TIMELINE.merge(t["events"], partition=label)
            # worker-liveness recency for the health monitor: monotonic
            # stamp of the last merged trailer (telemetry.health compares
            # its age against TPU_ML_HEALTH_STALE_S)
            REGISTRY.gauge_set("worker.last_trailer", time.monotonic())
        except Exception:
            logger.warning(
                "dropping unmergeable worker telemetry trailer (partition=%s)",
                partition,
                exc_info=True,
            )

    def _stderr_tail(self, limit: int = 4000) -> str:
        try:
            with open(self._stderr.name, "rb") as f:
                data = f.read()
            return data[-limit:].decode(errors="replace")
        except OSError:
            return "<stderr unavailable>"

    def close(self) -> None:
        try:
            if self.proc.stdin:
                self.proc.stdin.close()
            self.proc.wait(timeout=10)
        except Exception:
            self.proc.kill()
        finally:
            try:
                self._stderr.close()
                os.unlink(self._stderr.name)
            except OSError:
                pass


class LocalSparkSession:
    """A no-JVM session with the ``SparkSession`` surface the estimators use.

    Parameters mirror the Spark knobs they stand in for:

    - ``parallelism``: default partition count of ``createDataFrame``
      (``spark.default.parallelism``)
    - ``num_workers``: worker processes executing mapInArrow tasks; they are
      reused across jobs (``spark.python.worker.reuse``)
    - ``max_records_per_batch``: input chunking so plan functions see
      multiple batches per partition
      (``spark.sql.execution.arrow.maxRecordsPerBatch``)
    - ``worker_platform``: the device policy for worker processes (see
      ``utils.devicepolicy``). Default ``"cpu"`` — one device owner per
      host: the driver keeps the accelerator, workers run the JAX CPU
      backend, and the known accelerator-bootstrap env triggers are
      scrubbed from worker environments so an interpreter-start plugin
      cannot claim (or block on) the chip. Pass ``None`` to let workers
      inherit the parent environment untouched.
    - ``worker_env``: extra env overrides for workers, applied on top of
      the device policy (a value of ``None`` removes the variable)
    """

    def __init__(
        self,
        parallelism: int = 2,
        num_workers: int = 1,
        max_records_per_batch: int = 10_000,
        worker_env: dict[str, str | None] | None = None,
        worker_platform: str | None = "cpu",
    ):
        if parallelism < 1 or num_workers < 1 or max_records_per_batch < 1:
            raise ValueError("parallelism/num_workers/max_records_per_batch >= 1")
        self.parallelism = parallelism
        self.num_workers = num_workers
        self.max_records_per_batch = max_records_per_batch
        self._worker_env = devicepolicy.worker_env(worker_platform)
        self._worker_env.update(worker_env or {})
        # rendezvous bound for barrier stages (spark.barrier.sync.timeout).
        # Env-tunable because the bound races the workers' FIRST JAX
        # compile: on a saturated host (e.g. a bench run sharing the box)
        # 120 s can flake — the test harness raises it rather than letting
        # load turn into spurious WorkerExceptions.
        raw_bt = os.environ.get(knobs.BARRIER_TIMEOUT_S.name, "120")
        try:
            self.barrier_timeout = float(raw_bt)
        except ValueError:
            raise ValueError(
                f"{knobs.BARRIER_TIMEOUT_S.name} must be a number of "
                f"seconds, got {raw_bt!r}"
            ) from None
        if self.barrier_timeout <= 0:
            raise ValueError(
                f"{knobs.BARRIER_TIMEOUT_S.name} must be > 0, got {raw_bt!r}"
            )
        self._workers: list[_Worker] = []
        self._closed = False
        atexit.register(self.stop)

    # -- DataFrame construction --------------------------------------------

    def createDataFrame(
        self,
        data: Any,
        schema: T.StructType | list[str] | None = None,
        numPartitions: int | None = None,
    ) -> DataFrame:
        if self._closed:
            raise RuntimeError("session is stopped")
        # pa.Table first: it also implements the dataframe-interchange
        # protocol, so the pandas duck-check below would claim it
        if isinstance(data, pa.Table):
            struct = T.from_arrow_schema(data.schema)
            parts = self._split_batches(data, numPartitions or self.parallelism)
            return dataframe_from_partitions(self, struct, parts)
        if hasattr(data, "itertuples"):  # pandas (or API-compatible) frame
            rows = [tuple(r) for r in data.itertuples(index=False)]
            names = [str(c) for c in data.columns]
            struct = self._infer_schema(rows, names) if schema is None else schema
        else:
            rows = [tuple(r) for r in data]
            if schema is None:
                raise ValueError(
                    "createDataFrame from rows needs a schema (StructType or "
                    "column names)"
                )
            struct = schema
            names = None
        if isinstance(struct, list):
            struct = self._infer_schema(rows, struct)
        if not isinstance(struct, T.StructType):
            raise TypeError(f"unsupported schema: {struct!r}")

        arrow_schema = struct.to_arrow()
        columns = []
        for i, field in enumerate(arrow_schema):
            vals = [_coerce_cell(r[i]) for r in rows]
            columns.append(pa.array(vals, type=field.type))
        table = pa.Table.from_arrays(columns, schema=arrow_schema)
        parts = self._split_batches(table, numPartitions or self.parallelism)
        return dataframe_from_partitions(self, struct, parts)

    def _infer_schema(self, rows, names) -> T.StructType:
        if not rows:
            raise ValueError("cannot infer schema from an empty dataset")
        first = rows[0]
        if len(first) != len(names):
            raise ValueError(
                f"row arity {len(first)} != number of column names {len(names)}"
            )
        return T.StructType(
            [T.StructField(n, _infer_type(v)) for n, v in zip(names, first)]
        )

    def _split_batches(
        self, table: pa.Table, num_partitions: int
    ) -> list[list[pa.RecordBatch]]:
        cuts = np.linspace(0, table.num_rows, num_partitions + 1).astype(int)
        return [
            table.slice(lo, hi - lo).to_batches() if hi > lo else []
            for lo, hi in zip(cuts[:-1], cuts[1:])
        ]

    # -- execution ----------------------------------------------------------

    def _chunk_batches(
        self, part: list[pa.RecordBatch], schema: pa.Schema
    ) -> bytes:
        """One partition -> IPC stream, re-chunked to max_records_per_batch."""
        out = []
        for b in part:
            for at in range(0, b.num_rows, self.max_records_per_batch):
                out.append(b.slice(at, self.max_records_per_batch))
        return W.batches_to_ipc(out, schema)

    def _ensure_workers(self) -> list[_Worker]:
        if self._closed:
            raise RuntimeError("session is stopped")
        # a crashed worker (segfault/OOM) is replaced, not reused — one
        # transient death must not poison the session
        for w in [w for w in self._workers if w.dead or w.proc.poll() is not None]:
            self._workers.remove(w)
            w.close()
        while len(self._workers) < self.num_workers:
            self._workers.append(_Worker(self._worker_env))
        return self._workers

    def _run_map_in_arrow(
        self, func, task_parts: list[bytes], target: pa.Schema
    ) -> Iterator[list[pa.RecordBatch]]:
        import cloudpickle

        fn_bytes = cloudpickle.dumps(func)  # fails here exactly like Spark would
        schema_bytes = target.serialize().to_pybytes()
        workers = self._ensure_workers()
        results: list[list[pa.RecordBatch] | None] = [None] * len(task_parts)

        def run_on(worker: _Worker, indices: list[int]) -> None:
            for i in indices:
                payload = worker.run_task(
                    fn_bytes, task_parts[i], schema_bytes, partition=i
                )
                results[i], _ = W.batches_from_ipc(payload)

        assignments = [
            (workers[w], [i for i in range(len(task_parts)) if i % len(workers) == w])
            for w in range(len(workers))
        ]
        live = [a for a in assignments if a[1]]
        if len(live) == 1:
            run_on(*live[0])
        elif live:
            errors: list[BaseException] = []

            def guarded(a):
                try:
                    run_on(*a)
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    errors.append(e)

            threads = [
                threading.Thread(target=guarded, args=(a,), daemon=True) for a in live
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise errors[0]
        yield from (r if r is not None else [] for r in results)

    def _run_map_in_arrow_barrier(
        self, func, task_parts: list[bytes], target: pa.Schema
    ) -> Iterator[list[pa.RecordBatch]]:
        """Barrier-mode stage: every partition's task launches SIMULTANEOUSLY
        in its own FRESH worker process, with a shared BarrierTaskContext for
        rendezvous/allGather — Spark's ``RDD.barrier()`` semantics, which an
        SPMD mesh program needs from the scheduler.

        Fresh (non-reused) workers are deliberate: a barrier task typically
        bootstraps ``jax.distributed`` for the stage's process group, which
        must happen before the interpreter's first JAX backend init — a
        reused worker (or one that ran the device-policy probe) has already
        initialized JAX. The workers are torn down when the stage ends, like
        Spark executors finishing a barrier stage. The startup probe is
        disarmed for the same reason; the bootstrap-trigger scrub (the part
        that prevents the accelerator hang) still applies.
        """
        import cloudpickle

        from spark_rapids_ml_tpu.utils import devicepolicy

        if self._closed:
            raise RuntimeError("session is stopped")
        n = len(task_parts)
        fn_bytes = cloudpickle.dumps(func)
        schema_bytes = target.serialize().to_pybytes()
        barrier_dir = tempfile.mkdtemp(prefix="localspark-barrier-")
        env = dict(self._worker_env)
        env.pop(devicepolicy.PROBE_VAR, None)
        workers = [_Worker(env) for _ in range(n)]
        results: list[list[pa.RecordBatch] | None] = [None] * n
        errors: list[BaseException] = []

        def run_one(rank: int) -> None:
            context = {
                "partition_id": rank,
                "num_tasks": n,
                "barrier_dir": barrier_dir,
                "timeout": self.barrier_timeout,
            }
            try:
                payload = workers[rank].run_task(
                    fn_bytes, task_parts[rank], schema_bytes, context,
                    partition=rank,
                )
                results[rank], _ = W.batches_from_ipc(payload)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors.append(e)

        threads = [
            threading.Thread(target=run_one, args=(r,), daemon=True)
            for r in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for w in workers:
            w.close()
        import shutil

        shutil.rmtree(barrier_dir, ignore_errors=True)
        if errors:
            raise errors[0]
        yield from (r if r is not None else [] for r in results)

    # -- lifecycle -----------------------------------------------------------

    def stop(self) -> None:
        self._closed = True
        workers, self._workers = self._workers, []
        for w in workers:
            w.close()

    def __enter__(self) -> "LocalSparkSession":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # pyspark-compat sugar so ``LocalSparkSession.builder...getOrCreate()``
    # shaped code works in examples
    class _Builder:
        def master(self, _):
            return self

        def appName(self, _):
            return self

        def config(self, *_, **__):
            return self

        def getOrCreate(self) -> "LocalSparkSession":
            return LocalSparkSession()

    class _BuilderDescriptor:
        def __get__(self, obj, objtype=None) -> "LocalSparkSession._Builder":
            return LocalSparkSession._Builder()

    builder = _BuilderDescriptor()


def _coerce_cell(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, Row):
        return tuple(v)
    return v
