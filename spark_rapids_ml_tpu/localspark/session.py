"""LocalSparkSession: createDataFrame + the worker-process pool behind
``mapInArrow`` (see ``worker.py`` for the boundary-fidelity contract)."""

from __future__ import annotations

import atexit
import logging
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Iterator

import numpy as np
import pyarrow as pa

from spark_rapids_ml_tpu.localspark import types as T
from spark_rapids_ml_tpu.localspark import worker as W
from spark_rapids_ml_tpu.resilience import faults, sites
from spark_rapids_ml_tpu.resilience.supervisor import (
    WorkerSupervisor,
    hedge_config,
)
from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
from spark_rapids_ml_tpu.telemetry.timeline import TIMELINE
from spark_rapids_ml_tpu.utils import devicepolicy, knobs
from spark_rapids_ml_tpu.localspark.dataframe import (
    DataFrame,
    Row,
    _infer_type,
    dataframe_from_partitions,
)

logger = logging.getLogger("spark_rapids_ml_tpu")


class WorkerException(RuntimeError):
    """A mapInArrow plan function raised inside a worker process; carries the
    worker-side traceback (the analog of pyspark's PythonException)."""


class _BarrierInfraFailure(Exception):
    """Internal: a barrier epoch failed on *infrastructure* (worker death,
    injected preemption, rank-join deadline) — retryable with fresh workers,
    unlike a plan error, which would only run the same bug twice."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


def _require_results(
    results: list, stage: str
) -> list:
    """Every partition must have produced a result; a silent ``None`` used
    to be yielded as an empty batch list — data loss dressed up as an empty
    partition. Name the holes and refuse instead."""
    missing = [i for i, r in enumerate(results) if r is None]
    if missing:
        raise WorkerException(
            f"{stage} stage finished without a result for partition(s) "
            f"{missing}: no worker returned a payload for them and no "
            "failure was recorded — refusing to yield partial output"
        )
    return results


class _Worker:
    """One reusable worker subprocess + its half of the framing protocol."""

    dead = False

    def __init__(self, extra_env: dict[str, str | None] | None = None):
        env = devicepolicy.apply_overrides(os.environ, extra_env or {})
        self._probe_armed = bool(env.get(devicepolicy.PROBE_VAR))
        self._tasks_done = 0
        self._stderr = tempfile.NamedTemporaryFile(
            mode="w+b", prefix="localspark-worker-", suffix=".log", delete=False
        )
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_ml_tpu.localspark.worker"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=self._stderr,
            env=env,
        )
        self._lock = threading.Lock()

    def run_task(
        self,
        fn_bytes: bytes,
        data: bytes,
        schema_bytes: bytes,
        context: dict | None = None,
        partition: int | None = None,
        defer_trailer: bool = False,
    ) -> bytes | tuple[bytes, bytes]:
        trailer = b""
        with self._lock:
            try:
                out = self.proc.stdin
                if context is None:
                    out.write(W.MAGIC)
                else:
                    out.write(W.MAGIC_BARRIER)
                W.write_block(out, fn_bytes)
                W.write_block(out, data)
                W.write_block(out, schema_bytes)
                if context is not None:
                    import json

                    W.write_block(out, json.dumps(context).encode())
                out.flush()
                status = self.proc.stdout.read(1)
                if len(status) != 1:
                    raise EOFError
                payload = W.read_block(self.proc.stdout)
                if status == b"O":
                    # telemetry trailer: the worker's registry delta +
                    # timeline events for THIS task (worker.py framing doc)
                    trailer = W.read_block(self.proc.stdout)
            except (EOFError, BrokenPipeError, OSError) as e:
                self.dead = True  # session must not reuse this process
                try:  # EOF can precede process teardown: wait briefly for rc
                    rc = self.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    rc = None
                # the probe can only fail before the first task of an armed
                # worker — a later rc collision is an unrelated crash
                if (
                    rc == devicepolicy.PROBE_EXIT_CODE
                    and self._probe_armed
                    and self._tasks_done == 0
                ):
                    raise WorkerException(
                        "localspark worker failed its device-policy probe "
                        "(see utils/devicepolicy.py); stderr tail:\n"
                        + self._stderr_tail()
                    ) from e
                raise WorkerException(
                    f"localspark worker died mid-task (exit code {rc}); "
                    "stderr tail:\n" + self._stderr_tail()
                ) from e
        self._tasks_done += 1
        if status == b"E":
            import cloudpickle

            raise WorkerException(
                "mapInArrow plan function failed in the worker process:\n"
                + cloudpickle.loads(payload)
            )
        if defer_trailer:
            # the caller decides whether this attempt's telemetry counts —
            # a hedge loser's trailer must be dropped, not merged twice
            return payload, trailer
        self._merge_telemetry(trailer, partition)
        return payload

    @staticmethod
    def _merge_telemetry(trailer: bytes, partition: int | None) -> None:
        """Fold a worker's telemetry trailer into the driver's registry and
        flight-recorder timeline, labeling every series/event with the
        partition it came from. Best-effort by design: a malformed trailer
        is logged and dropped, never failing the task that produced it."""
        if not trailer:
            return
        try:
            import json
            import time

            from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
            from spark_rapids_ml_tpu.telemetry.timeline import TIMELINE

            t = json.loads(trailer)
            label = "" if partition is None else str(partition)
            if t.get("registry"):
                REGISTRY.merge_wire(t["registry"], partition=label)
            if t.get("events"):
                TIMELINE.merge(t["events"], partition=label)
            # worker-liveness recency for the health monitor: monotonic
            # stamp of the last merged trailer (telemetry.health compares
            # its age against TPU_ML_HEALTH_STALE_S)
            REGISTRY.gauge_set("worker.last_trailer", time.monotonic())
        except Exception:
            logger.warning(
                "dropping unmergeable worker telemetry trailer (partition=%s)",
                partition,
                exc_info=True,
            )

    def _stderr_tail(self, limit: int = 4000) -> str:
        try:
            with open(self._stderr.name, "rb") as f:
                data = f.read()
            return data[-limit:].decode(errors="replace")
        except OSError:
            return "<stderr unavailable>"

    def close(self) -> None:
        try:
            if self.proc.stdin:
                self.proc.stdin.close()
            self.proc.wait(timeout=10)
        except Exception:
            self.proc.kill()
        finally:
            try:
                self._stderr.close()
                os.unlink(self._stderr.name)
            except OSError:
                pass


class LocalSparkSession:
    """A no-JVM session with the ``SparkSession`` surface the estimators use.

    Parameters mirror the Spark knobs they stand in for:

    - ``parallelism``: default partition count of ``createDataFrame``
      (``spark.default.parallelism``)
    - ``num_workers``: worker processes executing mapInArrow tasks; they are
      reused across jobs (``spark.python.worker.reuse``)
    - ``max_records_per_batch``: input chunking so plan functions see
      multiple batches per partition
      (``spark.sql.execution.arrow.maxRecordsPerBatch``)
    - ``worker_platform``: the device policy for worker processes (see
      ``utils.devicepolicy``). Default ``"cpu"`` — one device owner per
      host: the driver keeps the accelerator, workers run the JAX CPU
      backend, and the known accelerator-bootstrap env triggers are
      scrubbed from worker environments so an interpreter-start plugin
      cannot claim (or block on) the chip. Pass ``None`` to let workers
      inherit the parent environment untouched.
    - ``worker_env``: extra env overrides for workers, applied on top of
      the device policy (a value of ``None`` removes the variable)
    """

    def __init__(
        self,
        parallelism: int = 2,
        num_workers: int = 1,
        max_records_per_batch: int = 10_000,
        worker_env: dict[str, str | None] | None = None,
        worker_platform: str | None = "cpu",
    ):
        if parallelism < 1 or num_workers < 1 or max_records_per_batch < 1:
            raise ValueError("parallelism/num_workers/max_records_per_batch >= 1")
        self.parallelism = parallelism
        self.num_workers = num_workers
        self.max_records_per_batch = max_records_per_batch
        self._worker_env = devicepolicy.worker_env(worker_platform)
        self._worker_env.update(worker_env or {})
        # rendezvous bound for barrier stages (spark.barrier.sync.timeout).
        # Env-tunable because the bound races the workers' FIRST JAX
        # compile: on a saturated host (e.g. a bench run sharing the box)
        # 120 s can flake — the test harness raises it rather than letting
        # load turn into spurious WorkerExceptions.
        raw_bt = os.environ.get(knobs.BARRIER_TIMEOUT_S.name, "120")
        try:
            self.barrier_timeout = float(raw_bt)
        except ValueError:
            raise ValueError(
                f"{knobs.BARRIER_TIMEOUT_S.name} must be a number of "
                f"seconds, got {raw_bt!r}"
            ) from None
        if self.barrier_timeout <= 0:
            raise ValueError(
                f"{knobs.BARRIER_TIMEOUT_S.name} must be > 0, got {raw_bt!r}"
            )
        # worker lifecycle is owned by the supervisor: leases, bounded
        # respawn with backoff, per-slot circuit breaker (see
        # resilience/supervisor.py) — replacing the old unbounded
        # remove-dead-and-respawn loop
        self._supervisor = WorkerSupervisor(
            lambda extra: _Worker({**self._worker_env, **extra}),
            num_workers,
        )
        self._closed = False
        atexit.register(self.stop)

    @property
    def _workers(self) -> list[_Worker]:
        """Live supervised workers in slot order — kept as a property for
        the tests and diagnostics that peeked at the old worker list."""
        return self._supervisor.live_workers()

    # -- DataFrame construction --------------------------------------------

    def createDataFrame(
        self,
        data: Any,
        schema: T.StructType | list[str] | None = None,
        numPartitions: int | None = None,
    ) -> DataFrame:
        if self._closed:
            raise RuntimeError("session is stopped")
        # pa.Table first: it also implements the dataframe-interchange
        # protocol, so the pandas duck-check below would claim it
        if isinstance(data, pa.Table):
            struct = T.from_arrow_schema(data.schema)
            parts = self._split_batches(data, numPartitions or self.parallelism)
            return dataframe_from_partitions(self, struct, parts)
        if hasattr(data, "itertuples"):  # pandas (or API-compatible) frame
            rows = [tuple(r) for r in data.itertuples(index=False)]
            names = [str(c) for c in data.columns]
            struct = self._infer_schema(rows, names) if schema is None else schema
        else:
            rows = [tuple(r) for r in data]
            if schema is None:
                raise ValueError(
                    "createDataFrame from rows needs a schema (StructType or "
                    "column names)"
                )
            struct = schema
            names = None
        if isinstance(struct, list):
            struct = self._infer_schema(rows, struct)
        if not isinstance(struct, T.StructType):
            raise TypeError(f"unsupported schema: {struct!r}")

        arrow_schema = struct.to_arrow()
        columns = []
        for i, field in enumerate(arrow_schema):
            vals = [_coerce_cell(r[i]) for r in rows]
            columns.append(pa.array(vals, type=field.type))
        table = pa.Table.from_arrays(columns, schema=arrow_schema)
        parts = self._split_batches(table, numPartitions or self.parallelism)
        return dataframe_from_partitions(self, struct, parts)

    def _infer_schema(self, rows, names) -> T.StructType:
        if not rows:
            raise ValueError("cannot infer schema from an empty dataset")
        first = rows[0]
        if len(first) != len(names):
            raise ValueError(
                f"row arity {len(first)} != number of column names {len(names)}"
            )
        return T.StructType(
            [T.StructField(n, _infer_type(v)) for n, v in zip(names, first)]
        )

    def _split_batches(
        self, table: pa.Table, num_partitions: int
    ) -> list[list[pa.RecordBatch]]:
        cuts = np.linspace(0, table.num_rows, num_partitions + 1).astype(int)
        return [
            table.slice(lo, hi - lo).to_batches() if hi > lo else []
            for lo, hi in zip(cuts[:-1], cuts[1:])
        ]

    # -- execution ----------------------------------------------------------

    def _chunk_batches(
        self, part: list[pa.RecordBatch], schema: pa.Schema
    ) -> bytes:
        """One partition -> IPC stream, re-chunked to max_records_per_batch."""
        out = []
        for b in part:
            for at in range(0, b.num_rows, self.max_records_per_batch):
                out.append(b.slice(at, self.max_records_per_batch))
        return W.batches_to_ipc(out, schema)

    def _run_map_in_arrow(
        self, func, task_parts: list[bytes], target: pa.Schema
    ) -> Iterator[list[pa.RecordBatch]]:
        """Elastic stage scheduler.

        Partitions flow through a work queue instead of the old static
        round-robin split, which made every worker death fatal to the whole
        stage. Three behaviors fall out:

        - a worker death fails only the *attempt* — the partition is
          re-queued and migrates to a surviving slot
          (``scheduler.reassign``) while the supervisor respawns, backs
          off, or quarantines the crashed slot;
        - an idle slot *hedges* a straggler: once a running partition's age
          exceeds ``max(TPU_ML_HEDGE_FLOOR_S, TPU_ML_HEDGE_FACTOR × p50)``
          of completed-partition runtimes, a duplicate attempt launches and
          the first result wins (``scheduler.hedge``); the loser's payload
          AND telemetry trailer are discarded, so nothing double-counts;
        - each slot is seeded its first partition deterministically (the
          worker-reuse and both-workers-used placement contracts), only the
          remainder is contended.

        Plan errors — the worker survived, the user's function raised —
        stay immediately fatal: re-running a deterministic bug is not
        resilience, it is the same traceback twice.
        """
        import cloudpickle

        from spark_rapids_ml_tpu.utils.config import get_config

        fn_bytes = cloudpickle.dumps(func)  # fails here exactly like Spark would
        schema_bytes = target.serialize().to_pybytes()
        if self._closed:
            raise RuntimeError("session is stopped")
        n = len(task_parts)
        if n == 0:
            return
        sup = self._supervisor
        sup.begin_stage()
        slots = sup.available_slots()
        hedge_factor, hedge_floor = hedge_config()
        max_attempts = 1 + max(0, get_config().task_retries)

        cv = threading.Condition()
        results: list[list[pa.RecordBatch] | None] = [None] * n
        seeds: dict[int, deque] = {s: deque() for s in slots}
        queue: deque = deque()
        for i in range(n):
            if i < len(slots):
                seeds[slots[i]].append(i)
            else:
                queue.append(i)
        attempts_left = [max_attempts] * n
        done = [False] * n
        hedged = [False] * n
        inflight: dict[int, dict] = {}  # idx -> {"t0": start, "count": live}
        durations: list[float] = []
        fatal: list[BaseException] = []
        state = {"done": 0, "last_error": None}

        def _pick(slot):
            # under cv: the next (partition, is_hedge) for this slot, or None
            if seeds[slot]:
                return seeds[slot].popleft(), False
            if queue:
                return queue.popleft(), False
            if hedge_factor > 0 and durations:
                med = sorted(durations)[len(durations) // 2]
                limit = max(hedge_floor, hedge_factor * med)
                now = time.monotonic()
                for idx, info in inflight.items():
                    if (
                        not done[idx]
                        and not hedged[idx]
                        and now - info["t0"] > limit
                    ):
                        hedged[idx] = True
                        return idx, True
            return None

        def _depart(idx):
            # under cv: one attempt of idx left flight
            info = inflight.get(idx)
            if info is not None:
                info["count"] -= 1
                if info["count"] <= 0:
                    del inflight[idx]

        def _attempt_failed(idx, exc):
            # under cv: consume an attempt — requeue, defer to a live hedge
            # twin, or fail the stage once every recourse is spent
            state["last_error"] = exc
            if done[idx]:
                return
            attempts_left[idx] -= 1
            if inflight.get(idx, {"count": 0})["count"] > 0:
                return  # a hedge twin is still running; let it decide
            if attempts_left[idx] > 0:
                queue.append(idx)
                REGISTRY.counter_inc("scheduler.reassign", partition=str(idx))
                TIMELINE.record_instant("scheduler.reassign", partition=str(idx))
            else:
                fatal.append(exc)

        def _runner(slot):
            worker = None
            try:
                while True:
                    with cv:
                        unit = None
                        while unit is None:
                            if fatal or state["done"] >= n:
                                return
                            unit = _pick(slot)
                            if unit is None:
                                cv.wait(0.05)
                        idx, is_hedge = unit
                        info = inflight.setdefault(
                            idx, {"t0": time.monotonic(), "count": 0}
                        )
                        info["count"] += 1
                        if is_hedge:
                            REGISTRY.counter_inc(
                                "scheduler.hedge", partition=str(idx)
                            )
                            TIMELINE.record_instant(
                                "scheduler.hedge",
                                partition=str(idx),
                                slot=str(slot),
                            )
                            logger.info(
                                "hedging straggler partition %d on slot %d",
                                idx, slot,
                            )
                        else:
                            REGISTRY.counter_inc("scheduler.tasks")
                    if worker is None or worker.dead:
                        worker = sup.checkout(slot)
                        if worker is None:  # quarantined/stopped under us
                            with cv:
                                _depart(idx)
                                _attempt_failed(
                                    idx,
                                    WorkerException(
                                        f"worker slot {slot} is unavailable"
                                    ),
                                )
                                cv.notify_all()
                            return
                    t0 = time.monotonic()
                    try:
                        faults.inject(sites.SCHEDULER_TASK)
                        payload, trailer = worker.run_task(
                            fn_bytes,
                            task_parts[idx],
                            schema_bytes,
                            partition=idx,
                            defer_trailer=True,
                        )
                        batches, _ = W.batches_from_ipc(payload)
                    except faults.FaultInjected as e:
                        # injected dispatch failure: the worker is fine,
                        # the attempt is spent
                        with cv:
                            _depart(idx)
                            _attempt_failed(idx, e)
                            cv.notify_all()
                        continue
                    except WorkerException as e:
                        if worker.dead:
                            quarantined = sup.report_crash(slot, e)
                            worker = None
                            with cv:
                                _depart(idx)
                                _attempt_failed(idx, e)
                                cv.notify_all()
                            if quarantined:
                                return
                            continue
                        with cv:  # plan error: fatal, never retried
                            _depart(idx)
                            fatal.append(e)
                            cv.notify_all()
                        return
                    sup.report_success(slot)
                    accept = False
                    with cv:
                        _depart(idx)
                        if not done[idx]:
                            done[idx] = True
                            state["done"] += 1
                            results[idx] = batches
                            durations.append(time.monotonic() - t0)
                            accept = True
                        cv.notify_all()
                    if accept:
                        _Worker._merge_telemetry(trailer, idx)
            except BaseException as e:  # noqa: BLE001 - surfaced to the stage
                with cv:
                    fatal.append(e)
                    cv.notify_all()

        threads = [
            threading.Thread(target=_runner, args=(s,), daemon=True)
            for s in slots
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if fatal:
            raise fatal[0]
        if state["done"] < n and state["last_error"] is not None:
            raise state["last_error"]
        yield from _require_results(results, "mapInArrow")

    def _run_map_in_arrow_barrier(
        self, func, task_parts: list[bytes], target: pa.Schema
    ) -> Iterator[list[pa.RecordBatch]]:
        """Barrier-mode stage: every partition's task launches SIMULTANEOUSLY
        in its own FRESH worker process, with a shared BarrierTaskContext for
        rendezvous/allGather — Spark's ``RDD.barrier()`` semantics, which an
        SPMD mesh program needs from the scheduler.

        Fresh (non-reused) workers are deliberate: a barrier task typically
        bootstraps ``jax.distributed`` for the stage's process group, which
        must happen before the interpreter's first JAX backend init — a
        reused worker (or one that ran the device-policy probe) has already
        initialized JAX. The workers are torn down when the stage ends, like
        Spark executors finishing a barrier stage. The startup probe is
        disarmed for the same reason; the bootstrap-trigger scrub (the part
        that prevents the accelerator hang) still applies.

        A barrier stage is all-or-nothing — its membership is fixed at
        launch, so a single lost rank dooms the epoch. Instead of turning
        one preemption into a failed fit, the whole round is retried with
        fresh workers up to ``TPU_ML_BARRIER_RETRIES`` times
        (``scheduler.barrier_retry``). Only *infrastructure* failures
        (worker death, injected preemption, rank-join deadline) retry; a
        plan error raises immediately, every time.
        """
        import cloudpickle

        if self._closed:
            raise RuntimeError("session is stopped")
        fn_bytes = cloudpickle.dumps(func)
        schema_bytes = target.serialize().to_pybytes()
        raw = os.environ.get(knobs.BARRIER_RETRIES.name, "")
        try:
            retries = max(0, int(raw)) if raw else 1
        except ValueError:
            retries = 1
        results = None
        for epoch in range(retries + 1):
            try:
                results = self._run_barrier_epoch(
                    fn_bytes, task_parts, schema_bytes
                )
                break
            except _BarrierInfraFailure as e:
                if epoch >= retries:
                    raise e.cause
                REGISTRY.counter_inc("scheduler.barrier_retry")
                TIMELINE.record_instant(
                    "scheduler.barrier_retry", epoch=str(epoch)
                )
                logger.warning(
                    "barrier epoch %d lost a rank to infrastructure (%s); "
                    "retrying the whole round with fresh workers (%d "
                    "retry(ies) left)",
                    epoch, e, retries - epoch,
                )
        yield from _require_results(results, "mapInArrow(barrier)")

    def _run_barrier_epoch(
        self, fn_bytes: bytes, task_parts: list[bytes], schema_bytes: bytes
    ) -> list:
        """One all-or-nothing barrier round: fresh workers, deadline-bounded
        rank joins, teardown + scratch-dir cleanup guaranteed by finally.

        Raises :class:`_BarrierInfraFailure` when the round died to
        infrastructure (retryable), or the plan error itself when user code
        raised with its worker still alive (never retried).
        """
        n = len(task_parts)
        barrier_dir = tempfile.mkdtemp(prefix="localspark-barrier-")
        env = dict(self._worker_env)
        env.pop(devicepolicy.PROBE_VAR, None)
        workers: list[_Worker] = []
        results: list[list[pa.RecordBatch] | None] = [None] * n
        errors: list[tuple[int, BaseException]] = []
        torn_down = False

        def close_all() -> None:
            for w in workers:
                w.close()

        try:
            workers.extend(_Worker(env) for _ in range(n))

            def run_one(rank: int) -> None:
                context = {
                    "partition_id": rank,
                    "num_tasks": n,
                    "barrier_dir": barrier_dir,
                    "timeout": self.barrier_timeout,
                }
                try:
                    REGISTRY.counter_inc("scheduler.tasks")
                    faults.inject(sites.SCHEDULER_RANK)
                    payload = workers[rank].run_task(
                        fn_bytes, task_parts[rank], schema_bytes, context,
                        partition=rank,
                    )
                    results[rank], _ = W.batches_from_ipc(payload)
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    errors.append((rank, e))

            threads = [
                threading.Thread(target=run_one, args=(r,), daemon=True)
                for r in range(n)
            ]
            for t in threads:
                t.start()
            # bounded joins: the in-worker rendezvous is already capped at
            # barrier_timeout, so 2x + grace only catches a wedged compute
            deadline = time.monotonic() + 2.0 * self.barrier_timeout + 30.0
            pending = list(threads)
            while pending:
                for t in list(pending):
                    t.join(timeout=0.1)
                    if not t.is_alive():
                        pending.remove(t)
                if not pending:
                    break
                if errors and not torn_down:
                    # membership is fixed: one failed rank dooms the epoch.
                    # Kill the survivors now rather than letting them wait
                    # out the rendezvous timeout on a rank that never comes.
                    torn_down = True
                    close_all()
                elif time.monotonic() > deadline:
                    errors.append((-1, WorkerException(
                        f"barrier rank(s) failed to join within "
                        f"{2.0 * self.barrier_timeout + 30.0:.0f}s "
                        f"(2x {knobs.BARRIER_TIMEOUT_S.name} + grace); "
                        "tearing the epoch down"
                    )))
                    torn_down = True
                    close_all()
                    for t in pending:
                        t.join(timeout=15)
                    break
        finally:
            close_all()
            shutil.rmtree(barrier_dir, ignore_errors=True)
        if errors:
            def _infra(rank: int, exc: BaseException) -> bool:
                return (
                    isinstance(exc, faults.FaultInjected)
                    or rank < 0
                    or (rank < len(workers) and workers[rank].dead)
                )

            plan_errors = [e for r, e in errors if not _infra(r, e)]
            if plan_errors:
                raise plan_errors[0]
            # prefer an injected fault as the representative cause: the
            # early teardown above kills the surviving ranks, so their
            # died-mid-task errors are downstream noise of the first fault
            cause = next(
                (e for _, e in errors if isinstance(e, faults.FaultInjected)),
                errors[0][1],
            )
            raise _BarrierInfraFailure(cause)
        return results

    # -- lifecycle -----------------------------------------------------------

    def stop(self) -> None:
        self._closed = True
        self._supervisor.close()

    def __enter__(self) -> "LocalSparkSession":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # pyspark-compat sugar so ``LocalSparkSession.builder...getOrCreate()``
    # shaped code works in examples
    class _Builder:
        def master(self, _):
            return self

        def appName(self, _):
            return self

        def config(self, *_, **__):
            return self

        def getOrCreate(self) -> "LocalSparkSession":
            return LocalSparkSession()

    class _BuilderDescriptor:
        def __get__(self, obj, objtype=None) -> "LocalSparkSession._Builder":
            return LocalSparkSession._Builder()

    builder = _BuilderDescriptor()


def _coerce_cell(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, Row):
        return tuple(v)
    return v
