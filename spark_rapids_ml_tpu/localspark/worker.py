"""The localspark Python worker: a separate OS process that executes
mapInArrow plan functions, mirroring Spark's executor-side Python worker.

Faithfulness to Spark's boundaries is the point (SURVEY.md §4 — the
reference is only ever tested through a live executor):

- the plan function arrives **cloudpickle-serialized** (the serializer
  pyspark itself uses for Python UDFs), so un-picklable closures fail here
  exactly as they would on a cluster;
- partition data crosses as an **Arrow IPC stream**, so schema/layout
  assumptions are exercised at a process boundary, not in-process;
- the worker is a **fresh interpreter** (``python -m``) — module-level
  state of the driver process is NOT available; the function's own imports
  (including JAX device init) must work cold, like on an executor;
- output batches are **cast to the declared schema**, the validation Spark
  applies to mapInArrow results; a mismatch raises here, not downstream;
- workers are **reused** across jobs of a session (Spark's
  ``spark.python.worker.reuse``), so per-process caches (jitted kernels)
  amortize the way they do on real executors.

Framing protocol, little-endian u64 lengths, one task per request::

    driver -> worker:  b"LSPK" | fn | input-arrow-stream | target-schema
    driver -> worker:  b"LSPB" | fn | input-arrow-stream | target-schema
                       | json task-context               (barrier task)
    worker -> driver:  b"O" | output-arrow-stream
                       | json telemetry-trailer          (success)
                       b"E" | pickled traceback string   (failure)

A barrier frame additionally installs a ``BarrierTaskContext`` (see
``taskcontext.py``) before invoking the plan function, the way Spark's
worker exposes ``BarrierTaskContext.get()`` inside barrier stages.

The telemetry trailer on the success frame is what keeps worker-side
observability from dying with the process: everything the task recorded
into THIS worker's registry (a snapshot delta — columnar counters, spans,
fault injections) plus its flight-recorder timeline events, JSON-encoded.
The driver merges it into its own registry/timeline labeled by partition
(``session._Worker.run_task``). Serialization failures degrade to an empty
trailer — telemetry must never fail a task.

stdout is re-pointed at stderr after startup so user ``print``\\ s inside
plan functions cannot corrupt the protocol stream (Spark's workers talk
over a socket for the same reason).
"""

from __future__ import annotations

import io
import os
import struct
import sys
import time
import traceback

import pyarrow as pa

MAGIC = b"LSPK"
MAGIC_BARRIER = b"LSPB"


def write_block(stream, payload: bytes) -> None:
    stream.write(struct.pack("<Q", len(payload)))
    stream.write(payload)


def read_block(stream) -> bytes:
    header = stream.read(8)
    if len(header) != 8:
        raise EOFError("worker protocol stream truncated")
    (length,) = struct.unpack("<Q", header)
    payload = stream.read(length)
    if len(payload) != length:
        raise EOFError("worker protocol stream truncated")
    return payload


def batches_to_ipc(batches: list[pa.RecordBatch], schema: pa.Schema) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, schema) as writer:
        for b in batches:
            writer.write_batch(b)
    return sink.getvalue()


def batches_from_ipc(payload: bytes) -> tuple[list[pa.RecordBatch], pa.Schema]:
    with pa.ipc.open_stream(pa.BufferReader(payload)) as reader:
        schema = reader.schema
        return list(reader), schema


def cast_to_declared(batch: pa.RecordBatch, target: pa.Schema) -> pa.RecordBatch:
    """Validate/cast one output batch against the declared mapInArrow schema.

    Matches Spark's behavior: columns are matched by NAME (order-free),
    value-compatible types are cast, anything else is an error naming the
    column — so a plan-function bug surfaces at the boundary with a
    message, not as corrupt downstream data.
    """
    if batch.schema.equals(target):
        return batch
    cols = []
    for field in target:
        idx = batch.schema.get_field_index(field.name)
        if idx < 0:
            raise ValueError(
                f"mapInArrow output is missing declared column {field.name!r}; "
                f"got columns {batch.schema.names}"
            )
        col = batch.column(idx)
        if col.type != field.type:
            try:
                col = col.cast(field.type)
            except pa.ArrowInvalid as e:
                raise ValueError(
                    f"mapInArrow output column {field.name!r} has type "
                    f"{col.type}, cannot cast to declared {field.type}: {e}"
                ) from e
        cols.append(col)
    return pa.RecordBatch.from_arrays(cols, schema=target)


def run_task(
    fn_bytes: bytes,
    data: bytes,
    schema_bytes: bytes,
    context: dict | None = None,
) -> bytes:
    """Execute one mapInArrow task; returns the output IPC stream bytes."""
    import cloudpickle

    from spark_rapids_ml_tpu.localspark.taskcontext import BarrierTaskContext

    fn = cloudpickle.loads(fn_bytes)
    batches, _ = batches_from_ipc(data)
    target = pa.ipc.read_schema(pa.BufferReader(schema_bytes))
    if context is not None:
        BarrierTaskContext._install(
            BarrierTaskContext(
                partition_id=context["partition_id"],
                num_tasks=context["num_tasks"],
                barrier_dir=context["barrier_dir"],
                timeout=context.get("timeout", 120.0),
            )
        )
    try:
        out = [cast_to_declared(b, target) for b in fn(iter(batches))]
    finally:
        if context is not None:
            BarrierTaskContext._install(None)
    return batches_to_ipc(out, target)


def main() -> None:
    import cloudpickle

    # keep the protocol fd private; user prints go to stderr
    proto_in = os.fdopen(os.dup(0), "rb")
    proto_out = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    sys.stdout = sys.stderr

    # Device-policy probe BEFORE accepting tasks: if this process cannot
    # initialize JAX on its assigned platform within a bounded time, exit
    # with a diagnosable error instead of hanging the first fit() job
    # indefinitely (utils/devicepolicy.py documents why the env var alone is
    # not enough). Armed by the session only on accelerator-attached hosts,
    # because it costs the cold-interpreter fidelity documented above. The
    # driver maps PROBE_EXIT_CODE to a policy-specific WorkerException.
    from spark_rapids_ml_tpu.utils import devicepolicy

    if os.environ.get(devicepolicy.PROBE_VAR):
        try:
            devicepolicy.probe_platform()
        except devicepolicy.DevicePolicyError as e:
            print(f"[tpu-ml worker] device policy violation: {e}", file=sys.stderr)
            sys.stderr.flush()
            os._exit(devicepolicy.PROBE_EXIT_CODE)

    import json

    # jax-free on purpose: importing the registry/timeline must not trigger
    # a backend init in workers that never touch jax (pure-Arrow tasks)
    from spark_rapids_ml_tpu.telemetry.registry import REGISTRY
    from spark_rapids_ml_tpu.telemetry.timeline import TIMELINE

    while True:
        magic = proto_in.read(4)
        if not magic:
            return  # driver closed the pipe: clean shutdown
        if magic not in (MAGIC, MAGIC_BARRIER):
            raise RuntimeError(f"bad task frame magic: {magic!r}")
        fn_bytes = read_block(proto_in)
        data = read_block(proto_in)
        schema_bytes = read_block(proto_in)
        context = (
            json.loads(read_block(proto_in)) if magic == MAGIC_BARRIER else None
        )
        # bracket the task so the trailer carries exactly what IT recorded
        reg0 = REGISTRY.snapshot()
        tl_seq0 = TIMELINE.seq()
        t0 = time.perf_counter()
        try:
            # fault site for chaos tests: a worker-scoped TPU_ML_FAULT_PLAN
            # (e.g. worker.task:kill:1) crashes THIS process mid-job,
            # exercising the session's crashed-worker replacement
            from spark_rapids_ml_tpu.resilience import faults

            faults.inject("worker.task")
            payload, status = run_task(fn_bytes, data, schema_bytes, context), b"O"
        except BaseException:
            payload, status = cloudpickle.dumps(traceback.format_exc()), b"E"
        proto_out.write(status)
        write_block(proto_out, payload)
        if status == b"O":
            # the one span every task gets, recorded worker-side (plain
            # registry/timeline calls, not trace_range — that would drag a
            # jax import into pure-Arrow tasks)
            t1 = time.perf_counter()
            REGISTRY.histogram_record("span.seconds", t1 - t0, phase="worker.task")
            TIMELINE.record_span("worker.task", t0, t1)
            try:
                trailer = json.dumps(
                    {
                        "registry": REGISTRY.snapshot().delta(reg0).to_wire(),
                        "events": TIMELINE.events(since_seq=tl_seq0),
                    }
                ).encode()
            except Exception:
                trailer = b"{}"  # telemetry must never fail a task
            write_block(proto_out, trailer)
        proto_out.flush()


if __name__ == "__main__":
    main()
