"""localspark — a pyspark-API-compatible local execution engine.

Two jobs, one component:

1. **Execution proof for the Spark integration.** The reference is only
   testable against a live Spark (`PCASuite.scala:42-88` runs fit/transform
   on a real SparkSession via the harness `RapidsMLTest.scala:22-33`).
   pyspark cannot be assumed present, so this package supplies the same
   proof locally: a ``DataFrame`` whose ``mapInArrow`` ships the plan
   function to a REAL separate worker process — serialized with
   cloudpickle (the serializer Spark itself uses for Python UDFs), data
   crossing as Arrow IPC streams, output validated against the declared
   schema — so every failure mode Spark introduces (closure pickling,
   worker-side imports/JAX init, Arrow schema mismatches) is exercised
   without a JVM. The real-pyspark integration suite runs the same tests
   against a live SparkSession when pyspark is installed (CI).

2. **Standalone mode for users.** The Spark-facing estimators
   (``spark_rapids_ml_tpu.spark``) accept these DataFrames
   interchangeably with pyspark ones, so the drop-in API works on a
   laptop or a single TPU VM with no Spark cluster at all — a capability
   the reference cannot offer (it is compiled against the JVM plugin,
   SURVEY.md §1 L0).

API surface mirrors the ``pyspark.sql`` subset the estimators use:
``LocalSparkSession.createDataFrame``, ``DataFrame.{select, where, limit,
sample, randomSplit, repartition, mapInArrow, collect, first, count,
toArrow, schema}``, ``types.{StructType, StructField, ArrayType,
DoubleType, ...}``, ``functions.{col, rand, lit}``.
"""

from spark_rapids_ml_tpu.localspark import functions, types
from spark_rapids_ml_tpu.localspark.dataframe import DataFrame, Row
from spark_rapids_ml_tpu.localspark.session import LocalSparkSession

__all__ = [
    "DataFrame",
    "LocalSparkSession",
    "Row",
    "functions",
    "types",
]
