"""localspark DataFrame: a lazily planned, partitioned Arrow dataset with
the ``pyspark.sql.DataFrame`` surface the estimators drive.

A DataFrame is (schema, plan); the plan yields partitions — each a list of
``pyarrow.RecordBatch`` — on demand. Narrow ops (select / where / sample /
limit) evaluate inline on the driver; ``mapInArrow`` is the execution
boundary, dispatched to the session's worker processes (see ``worker.py``
for the fidelity contract). Actions (``collect``/``count``/``toArrow``/
``first``) materialize.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterator

import numpy as np
import pyarrow as pa

from spark_rapids_ml_tpu.localspark import types as T
from spark_rapids_ml_tpu.localspark.functions import Column


class Row(tuple):
    """Positional + by-name + attribute row access, like ``pyspark.sql.Row``."""

    __fields__: tuple

    def __new__(cls, values, names):
        row = super().__new__(cls, values)
        row.__fields__ = tuple(names)
        return row

    def __getitem__(self, key):
        if isinstance(key, str):
            try:
                key = self.__fields__.index(key)
            except ValueError:
                raise KeyError(key) from None
        return super().__getitem__(key)

    def __getattr__(self, name):
        try:
            return self[self.__fields__.index(name)]
        except ValueError:
            raise AttributeError(name) from None

    def asDict(self) -> dict:
        return dict(zip(self.__fields__, self))

    def __repr__(self) -> str:
        body = ", ".join(f"{n}={v!r}" for n, v in zip(self.__fields__, self))
        return f"Row({body})"


def _value_to_python(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (list, np.ndarray)):
        return [_value_to_python(x) for x in v]
    return v


class DataFrame:
    def __init__(
        self,
        session,
        schema: T.StructType,
        parts: Callable[[], Iterator[list[pa.RecordBatch]]],
        num_partitions: int,
    ):
        self._session = session
        self._schema = schema
        self._parts = parts
        self._num_partitions = num_partitions

    # -- metadata -----------------------------------------------------------

    @property
    def schema(self) -> T.StructType:
        return self._schema

    @property
    def columns(self) -> list[str]:
        return self._schema.names

    @property
    def rdd(self):  # only getNumPartitions, for parity probes in tests
        df = self

        class _RddShim:
            def getNumPartitions(self) -> int:
                return df._num_partitions

        return _RddShim()

    def __repr__(self) -> str:
        cols = ", ".join(
            f"{f.name}: {f.dataType.simpleString()}" for f in self._schema.fields
        )
        return f"LocalDataFrame[{cols}]"

    # -- narrow transformations (driver-inline) -----------------------------

    def _derive(self, schema, parts, num_partitions=None) -> "DataFrame":
        return DataFrame(
            self._session,
            schema,
            parts,
            self._num_partitions if num_partitions is None else num_partitions,
        )

    def select(self, *cols: str) -> "DataFrame":
        names = [c if isinstance(c, str) else str(c) for c in cols]
        fields = [self._schema[n] for n in names]  # KeyError on bad name, eagerly

        def parts():
            for part in self._parts():
                yield [b.select(names) for b in part]

        return self._derive(T.StructType(fields), parts)

    def where(self, condition: Column) -> "DataFrame":
        if not isinstance(condition, Column):
            raise TypeError(
                "localspark where() takes a Column expression "
                "(use functions.col); string predicates are not supported"
            )

        def parts():
            for pid, part in enumerate(self._parts()):
                out, off = [], 0
                for b in part:
                    mask = condition.evaluate(b, pid, off)
                    off += b.num_rows
                    out.append(b.filter(mask))
                yield out

        return self._derive(self._schema, parts)

    filter = where

    def sample(self, withReplacement=None, fraction=None, seed=None) -> "DataFrame":
        # pyspark allows sample(fraction=f, seed=s) or sample(False, f, s);
        # it also forgives sample(f) and sample(f, s) positionally
        if isinstance(withReplacement, float):
            withReplacement, fraction, seed = False, withReplacement, fraction
        if withReplacement:
            raise NotImplementedError("localspark sample: withReplacement=False only")
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")
        seed = 0 if seed is None else int(seed)

        def parts():
            for pid, part in enumerate(self._parts()):
                rng = np.random.default_rng((seed, pid))
                out = []
                for b in part:
                    mask = rng.random(b.num_rows) < fraction  # Bernoulli per row
                    out.append(b.filter(pa.array(mask)))
                yield out

        return self._derive(self._schema, parts)

    def randomSplit(self, weights: list[float], seed: int | None = None) -> list["DataFrame"]:
        if any(w <= 0 for w in weights):
            raise ValueError("randomSplit weights must be positive")
        total = float(sum(weights))
        bounds = np.cumsum([w / total for w in weights])
        seed = 0 if seed is None else int(seed)

        def parts_for(lo: float, hi: float):
            def parts():
                for pid, part in enumerate(self._parts()):
                    rng = np.random.default_rng((seed, pid))
                    out = []
                    for b in part:
                        u = rng.random(b.num_rows)
                        out.append(b.filter(pa.array((u >= lo) & (u < hi))))
                    yield out

            return parts

        lows = [0.0] + list(bounds[:-1])
        return [
            self._derive(self._schema, parts_for(lo, hi))
            for lo, hi in zip(lows, bounds)
        ]

    def union(self, other: "DataFrame") -> "DataFrame":
        """Concatenate rows POSITIONALLY (pyspark ``union`` semantics):
        ``other``'s i-th column becomes this DataFrame's i-th column
        regardless of its name, so the result's batches all carry THIS
        schema's names and later name-based ops stay aligned."""
        if len(self._schema.fields) != len(other._schema.fields):
            raise ValueError(
                f"union requires the same number of columns: "
                f"{len(self._schema.fields)} vs {len(other._schema.fields)}"
            )
        names = [f.name for f in self._schema.fields]

        def parts():
            yield from self._parts()
            for part in other._parts():
                yield [
                    pa.RecordBatch.from_arrays(list(b.columns), names=names)
                    for b in part
                ]

        return self._derive(
            self._schema, parts, self.rdd.getNumPartitions() + other.rdd.getNumPartitions()
        )

    unionAll = union  # pyspark alias

    def limit(self, n: int) -> "DataFrame":
        def parts():
            remaining = n
            for part in self._parts():
                if remaining <= 0:
                    yield []
                    continue
                out = []
                for b in part:
                    if remaining <= 0:
                        break
                    take = min(remaining, b.num_rows)
                    out.append(b.slice(0, take))
                    remaining -= take
                yield out

        return self._derive(self._schema, parts)

    def repartition(self, numPartitions: int) -> "DataFrame":
        if numPartitions < 1:
            raise ValueError("numPartitions must be >= 1")

        def parts():
            table = self._to_table()
            n_rows = table.num_rows
            # contiguous near-equal slices, like a round-robin shuffle's result
            cuts = np.linspace(0, n_rows, numPartitions + 1).astype(int)
            for lo, hi in zip(cuts[:-1], cuts[1:]):
                yield table.slice(lo, hi - lo).to_batches() if hi > lo else []

        return self._derive(self._schema, parts, num_partitions=numPartitions)

    # -- the execution boundary --------------------------------------------

    def mapInArrow(self, func, schema, barrier: bool = False) -> "DataFrame":
        """pyspark 3.5 signature incl. ``barrier``: when True all partition
        tasks launch simultaneously with a BarrierTaskContext (the surface an
        SPMD ``jax.distributed`` bootstrap needs — see session docstring)."""
        if isinstance(schema, str):
            raise TypeError(
                "localspark mapInArrow takes a StructType schema, not a DDL string"
            )
        out_schema: T.StructType = schema
        arrow_target = out_schema.to_arrow()
        session = self._session

        def parts():
            task_parts = [
                session._chunk_batches(part, self._arrow_schema())
                for part in self._parts()
            ]
            runner = (
                session._run_map_in_arrow_barrier
                if barrier
                else session._run_map_in_arrow
            )
            yield from runner(func, task_parts, arrow_target)

        return self._derive(out_schema, parts)

    # -- actions ------------------------------------------------------------

    def _arrow_schema(self) -> pa.Schema:
        return self._schema.to_arrow()

    def _to_table(self) -> pa.Table:
        batches = [b for part in self._parts() for b in part if b.num_rows]
        if not batches:
            return pa.Table.from_batches([], schema=self._arrow_schema())
        return pa.Table.from_batches(batches)

    def toArrow(self) -> pa.Table:
        return self._to_table()

    def toPandas(self):
        return self._to_table().to_pandas()

    def collect(self) -> list[Row]:
        names = self._schema.names
        rows: list[Row] = []
        for part in self._parts():
            for b in part:
                cols = [c.to_pylist() for c in b.columns]
                for vals in zip(*cols):
                    rows.append(Row([_value_to_python(v) for v in vals], names))
        return rows

    def first(self) -> Row | None:
        head = self.head(1)
        return head[0] if head else None

    def head(self, n: int = 1) -> list[Row]:
        names = self._schema.names
        rows: list[Row] = []
        for part in self._parts():
            for b in part:
                if not b.num_rows:
                    continue
                # slice BEFORE to_pylist — converting a whole multi-thousand
                # row batch to Python objects to peek at one row dominates
                # fit() setup time (nCols inference does head(1))
                sl = b.slice(0, n - len(rows))
                cols = [c.to_pylist() for c in sl.columns]
                for vals in zip(*cols):
                    rows.append(Row([_value_to_python(v) for v in vals], names))
                if len(rows) >= n:
                    return rows
        return rows

    def count(self) -> int:
        return sum(b.num_rows for part in self._parts() for b in part)

    def cache(self) -> "DataFrame":
        materialized = [list(part) for part in self._parts()]

        def parts():
            return iter(materialized)

        return self._derive(self._schema, parts, num_partitions=len(materialized))

    persist = cache

    def unpersist(self) -> "DataFrame":
        return self

    def show(self, n: int = 20) -> None:
        for row in itertools.islice(self.collect(), n):
            print(row)


def dataframe_from_partitions(
    session, schema: T.StructType, partitions: list[list[pa.RecordBatch]]
) -> DataFrame:
    def parts():
        return iter(partitions)

    return DataFrame(session, schema, parts, len(partitions))


def _infer_type(value: Any) -> T.DataType:
    if isinstance(value, bool):
        return T.BooleanType()
    if isinstance(value, (int, np.integer)):
        return T.LongType()
    if isinstance(value, (float, np.floating)):
        return T.DoubleType()
    if isinstance(value, str):
        return T.StringType()
    if isinstance(value, (list, tuple, np.ndarray)):
        if len(value) == 0:
            return T.ArrayType(T.DoubleType())
        return T.ArrayType(_infer_type(value[0]))
    raise TypeError(f"cannot infer localspark type for {type(value).__name__}")
