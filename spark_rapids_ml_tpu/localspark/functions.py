"""Column expressions — the ``pyspark.sql.functions`` subset the estimators
plan with (``col``, ``lit``, ``rand``), evaluated per Arrow batch with
``pyarrow.compute`` at materialization time."""

from __future__ import annotations

from typing import Any

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


class Column:
    """A lazily evaluated expression over an Arrow RecordBatch.

    ``evaluate(batch, partition_id, row_offset)`` returns a pyarrow Array of
    batch length. Comparison operators build boolean-valued Columns, so
    ``F.col("w") > 0`` works as a ``where`` predicate.
    """

    def __init__(self, fn, name: str):
        self._fn = fn
        self._name = name

    def evaluate(self, batch: pa.RecordBatch, partition_id: int, row_offset: int):
        return self._fn(batch, partition_id, row_offset)

    def __repr__(self) -> str:
        return f"Column<{self._name}>"

    def _binop(self, other: Any, op, sym: str) -> "Column":
        other_col = other if isinstance(other, Column) else lit(other)

        def fn(batch, pid, off):
            return op(
                self.evaluate(batch, pid, off), other_col.evaluate(batch, pid, off)
            )

        return Column(fn, f"({self._name} {sym} {other_col._name})")

    def __gt__(self, other):
        return self._binop(other, pc.greater, ">")

    def __ge__(self, other):
        return self._binop(other, pc.greater_equal, ">=")

    def __lt__(self, other):
        return self._binop(other, pc.less, "<")

    def __le__(self, other):
        return self._binop(other, pc.less_equal, "<=")

    def __eq__(self, other):  # noqa: D105 - Spark semantics: == builds an expr
        return self._binop(other, pc.equal, "=")

    def __ne__(self, other):
        return self._binop(other, pc.not_equal, "!=")

    def __and__(self, other):
        return self._binop(other, pc.and_kleene, "AND")

    def __or__(self, other):
        return self._binop(other, pc.or_kleene, "OR")

    def __hash__(self):
        return id(self)


def col(name: str) -> Column:
    def fn(batch: pa.RecordBatch, pid: int, off: int):
        idx = batch.schema.get_field_index(name)
        if idx < 0:
            raise KeyError(f"no such column: {name!r}")
        return batch.column(idx)

    return Column(fn, name)


def lit(value: Any) -> Column:
    def fn(batch: pa.RecordBatch, pid: int, off: int):
        return pa.scalar(value)

    return Column(fn, repr(value))


def rand(seed: int = 0) -> Column:
    """Uniform [0, 1) per row, deterministic given (seed, partition, row) —
    the contract Spark's ``rand`` documents (stable under re-execution of a
    partition, different across partitions)."""

    def fn(batch: pa.RecordBatch, pid: int, off: int):
        rng = np.random.default_rng((seed, pid))
        # jump the stream to this batch's offset instead of regenerating the
        # prefix (PCG64 consumes one 64-bit draw per double, so advance(off)
        # lands exactly where `off` prior rows would have left the stream)
        rng.bit_generator.advance(off)
        return pa.array(rng.random(batch.num_rows))

    return Column(fn, f"rand({seed})")
