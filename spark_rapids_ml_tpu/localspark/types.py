"""Spark-SQL-shaped type objects and their Arrow mapping.

Mirrors the ``pyspark.sql.types`` subset the Spark-facing estimators build
schemas with, so the same estimator code drives pyspark and localspark
DataFrames. Each type knows its Arrow equivalent — the contract at the
``mapInArrow`` boundary where Spark maps ArrayType(DoubleType) to
``list_(float64())`` etc.
"""

from __future__ import annotations

import pyarrow as pa


class DataType:
    def __eq__(self, other) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self).__name__)

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"

    def to_arrow(self) -> pa.DataType:
        raise NotImplementedError

    def simpleString(self) -> str:
        return type(self).__name__.replace("Type", "").lower()


class DoubleType(DataType):
    def to_arrow(self) -> pa.DataType:
        return pa.float64()


class FloatType(DataType):
    def to_arrow(self) -> pa.DataType:
        return pa.float32()


class LongType(DataType):
    def to_arrow(self) -> pa.DataType:
        return pa.int64()

    def simpleString(self) -> str:
        return "bigint"


class IntegerType(DataType):
    def to_arrow(self) -> pa.DataType:
        return pa.int32()

    def simpleString(self) -> str:
        return "int"


class StringType(DataType):
    def to_arrow(self) -> pa.DataType:
        return pa.string()


class BooleanType(DataType):
    def to_arrow(self) -> pa.DataType:
        return pa.bool_()


class ArrayType(DataType):
    def __init__(self, elementType: DataType, containsNull: bool = True):
        self.elementType = elementType
        self.containsNull = containsNull

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ArrayType) and self.elementType == other.elementType
        )

    def __hash__(self) -> int:
        return hash(("ArrayType", self.elementType))

    def __repr__(self) -> str:
        return f"ArrayType({self.elementType!r})"

    def to_arrow(self) -> pa.DataType:
        return pa.list_(self.elementType.to_arrow())

    def simpleString(self) -> str:
        return f"array<{self.elementType.simpleString()}>"


class StructField:
    def __init__(self, name: str, dataType: DataType, nullable: bool = True):
        self.name = name
        self.dataType = dataType
        self.nullable = nullable

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, StructField)
            and self.name == other.name
            and self.dataType == other.dataType
        )

    def __repr__(self) -> str:
        return f"StructField({self.name!r}, {self.dataType!r})"

    def to_arrow(self) -> pa.Field:
        return pa.field(self.name, self.dataType.to_arrow(), nullable=self.nullable)


class StructType(DataType):
    def __init__(self, fields: list[StructField] | None = None):
        self.fields = list(fields or [])

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def add(self, name: str, dataType: DataType, nullable: bool = True) -> "StructType":
        self.fields.append(StructField(name, dataType, nullable))
        return self

    def __getitem__(self, key):
        if isinstance(key, str):
            for f in self.fields:
                if f.name == key:
                    return f
            raise KeyError(key)
        return self.fields[key]

    def __iter__(self):
        return iter(self.fields)

    def __len__(self) -> int:
        return len(self.fields)

    def __eq__(self, other) -> bool:
        return isinstance(other, StructType) and self.fields == other.fields

    def __repr__(self) -> str:
        return f"StructType({self.fields!r})"

    def to_arrow(self) -> pa.Schema:
        return pa.schema([f.to_arrow() for f in self.fields])


_ARROW_TO_SPARK = [
    (pa.types.is_float64, DoubleType),
    (pa.types.is_float32, FloatType),
    (pa.types.is_int64, LongType),
    (pa.types.is_int32, IntegerType),
    (pa.types.is_string, StringType),
    (pa.types.is_boolean, BooleanType),
]


def from_arrow_type(t: pa.DataType) -> DataType:
    if pa.types.is_list(t) or pa.types.is_fixed_size_list(t) or pa.types.is_large_list(t):
        return ArrayType(from_arrow_type(t.value_type))
    for pred, cls in _ARROW_TO_SPARK:
        if pred(t):
            return cls()
    raise TypeError(f"unsupported Arrow type for localspark: {t}")


def from_arrow_schema(schema: pa.Schema) -> StructType:
    return StructType(
        [StructField(f.name, from_arrow_type(f.type), f.nullable) for f in schema]
    )
