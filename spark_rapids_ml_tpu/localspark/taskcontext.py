"""Barrier task context — localspark's analog of pyspark.BarrierTaskContext.

Spark's barrier execution mode (``mapInArrow(..., barrier=True)``) launches
ALL partition tasks of a stage simultaneously and gives each a
``BarrierTaskContext`` with a global rendezvous: ``barrier()`` blocks until
every task arrives, ``allGather(msg)`` additionally exchanges one string per
task. That primitive is exactly what an SPMD mesh program needs from the
scheduler: a simultaneous launch plus one bootstrap round to agree on the
``jax.distributed`` coordinator (SURVEY.md §7 hard part 2 — Spark tasks vs
SPMD mesh).

localspark's implementation rendezvouses through the filesystem: the driver
assigns every concurrently-running task a shared private directory, and each
``allGather`` round writes one ``round-R/rank.msg`` file per task then polls
for all of them. No sockets, no extra protocol — and the failure mode of a
lost peer is a bounded timeout with a diagnosis, not a hang (the same
fail-fast stance as utils/devicepolicy.py).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class BarrierTimeout(RuntimeError):
    """A barrier round did not complete — a peer task died or stalled."""


class BarrierTaskContext:
    """Per-task context installed by the worker for barrier-mode tasks.

    Surface mirrors the pyspark class the estimators' plan functions use:
    ``get()``, ``partitionId()``, ``getTaskInfos()`` (length == number of
    tasks), ``barrier()``, ``allGather(message)``.
    """

    _current: Optional["BarrierTaskContext"] = None

    def __init__(self, partition_id: int, num_tasks: int, barrier_dir: str,
                 timeout: float = 120.0):
        self._partition_id = partition_id
        self._num_tasks = num_tasks
        self._barrier_dir = barrier_dir
        self._timeout = timeout
        self._round = 0

    # -- pyspark surface -----------------------------------------------------

    @classmethod
    def get(cls) -> "BarrierTaskContext":
        if cls._current is None:
            raise RuntimeError(
                "not inside a barrier task (mapInArrow(..., barrier=True))"
            )
        return cls._current

    def partitionId(self) -> int:
        return self._partition_id

    def getTaskInfos(self) -> list:
        # pyspark returns one BarrierTaskInfo (with .address) per task; the
        # estimators only use len() and indexing existence
        class _Info:
            address = "127.0.0.1"

        return [_Info() for _ in range(self._num_tasks)]

    def barrier(self) -> None:
        self.allGather("")

    def allGather(self, message: str = "") -> list[str]:
        """Exchange one string per task; returns messages ordered by rank."""
        round_dir = os.path.join(self._barrier_dir, f"round-{self._round}")
        self._round += 1
        os.makedirs(round_dir, exist_ok=True)
        mine = os.path.join(round_dir, f"{self._partition_id}.msg")
        tmp = mine + ".tmp"
        with open(tmp, "w") as f:
            json.dump(message, f)
        os.replace(tmp, mine)  # atomic publish
        deadline = time.monotonic() + self._timeout
        paths = [
            os.path.join(round_dir, f"{r}.msg") for r in range(self._num_tasks)
        ]
        while True:
            missing = [p for p in paths if not os.path.exists(p)]
            if not missing:
                break
            if time.monotonic() > deadline:
                raise BarrierTimeout(
                    f"barrier round {self._round - 1}: "
                    f"{len(missing)}/{self._num_tasks} tasks never arrived "
                    f"within {self._timeout}s (missing ranks "
                    f"{[os.path.basename(p) for p in missing[:8]]}); a peer "
                    "task likely failed — check the driver for its error"
                )
            time.sleep(0.005)
        out = []
        for p in paths:
            # publish is atomic (os.replace), so a visible file is complete
            with open(p) as f:
                out.append(json.load(f))
        return out

    # -- worker-side install -------------------------------------------------

    @classmethod
    def _install(cls, ctx: Optional["BarrierTaskContext"]) -> None:
        cls._current = ctx


class TaskContext:
    """Minimal non-barrier task context (pyspark.TaskContext analog)."""

    _partition_id: int = 0

    @classmethod
    def get(cls) -> "TaskContext":
        return cls()

    def partitionId(self) -> int:
        return self._partition_id
