"""Pipeline — chained stages, Spark ML shape.

Enables BASELINE config 4 end-to-end: ``Pipeline(stages=[StandardScaler(...),
PCA(...)])`` fits preprocessing + decomposition as one unit and transforms
in sequence.
"""

from __future__ import annotations

from typing import Any

from spark_rapids_ml_tpu.models.base import Estimator, Model, Saveable, Transformer
from spark_rapids_ml_tpu.utils import persistence


class Pipeline(Estimator):
    def __init__(self, uid: str | None = None, stages: list | None = None):
        super().__init__(uid)
        self.stages = list(stages or [])

    def setStages(self, stages: list) -> "Pipeline":
        self.stages = list(stages)
        return self

    def getStages(self) -> list:
        return self.stages

    def fit(self, dataset: Any) -> "PipelineModel":
        """Fit estimator stages in order, transforming the running dataset
        through each fitted model (Spark Pipeline semantics)."""
        fitted = []
        current = dataset
        for stage in self.stages:
            if isinstance(stage, Estimator):
                model = stage.fit(current)
                fitted.append(model)
                current = model.transform(current)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                current = stage.transform(current)
            else:
                raise TypeError(f"pipeline stage {stage!r} is not a stage")
        model = PipelineModel(uid=self.uid, stages=fitted)
        return model

    # -- persistence: stages in numbered subdirectories ----------------------
    def save(self, path: str, overwrite: bool = False, layout: str = "native") -> None:
        if layout != "native":
            raise ValueError("pipelines support only the native layout")
        fs = persistence._FS(path)
        if fs.exists():
            if not overwrite:
                raise FileExistsError(
                    f"{path} already exists (use overwrite=True or "
                    "write().overwrite())"
                )
            fs.rmtree()
        persistence.save_metadata(path, self, extra={"numStages": len(self.stages)})
        for i, stage in enumerate(self.stages):
            stage.save(fs.join(f"stage_{i}"))

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        meta = persistence.load_metadata(path)
        fs = persistence._FS(path)
        stages = [
            Saveable.load(fs.join(f"stage_{i}")) for i in range(meta["numStages"])
        ]
        obj = cls(uid=meta["uid"], stages=stages)
        obj._restoreParamState(meta)
        return obj


class PipelineModel(Model):
    def __init__(self, uid: str | None = None, stages: list | None = None):
        super().__init__(uid)
        self.stages = list(stages or [])

    def transform(self, dataset: Any) -> Any:
        current = dataset
        for stage in self.stages:
            current = stage.transform(current)
        return current

    save = Pipeline.save  # same numbered-subdir layout

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        meta = persistence.load_metadata(path)
        fs = persistence._FS(path)
        stages = [
            Saveable.load(fs.join(f"stage_{i}")) for i in range(meta["numStages"])
        ]
        obj = cls(uid=meta["uid"], stages=stages)
        obj._restoreParamState(meta)
        return obj
