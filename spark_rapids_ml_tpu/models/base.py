"""Estimator / Transformer / Model base classes with save/load.

The Spark ML pipeline-stage contract the reference builds on:
``Estimator.fit(dataset) -> Model``, ``Transformer.transform(dataset)``,
``MLWritable.save/MLReadable.load`` (RapidsPCA.scala:52-88,102-185).

Two persistence layouts (utils/persistence.py): the native
metadata.json+data.parquet format, and ``layout="spark"`` — the stock
pyspark.ml on-disk shape, for models that declare a Spark ML class mapping
(PCAModel, StandardScalerModel). ``load`` auto-detects which layout a path
holds, so a model directory written by stock pyspark.ml loads here with the
same ``PCAModel.load(path)`` call.
"""

from __future__ import annotations

import functools
import importlib
import threading
from typing import Any

import numpy as np

from spark_rapids_ml_tpu.models.params import Params
from spark_rapids_ml_tpu.utils import persistence

# Stock Spark ML class name → our implementing class, for loading
# Spark-layout saves produced by pyspark.ml (or by layout="spark" here).
_SPARK_ML_CLASSES: dict[str, str] = {
    "org.apache.spark.ml.feature.PCAModel": "spark_rapids_ml_tpu.models.pca.PCAModel",
    "org.apache.spark.ml.feature.StandardScalerModel": "spark_rapids_ml_tpu.models.scaler.StandardScalerModel",
    "org.apache.spark.ml.feature.MinMaxScalerModel": "spark_rapids_ml_tpu.models.scaler.MinMaxScalerModel",
    "org.apache.spark.ml.feature.MaxAbsScalerModel": "spark_rapids_ml_tpu.models.scaler.MaxAbsScalerModel",
    "org.apache.spark.ml.feature.RobustScalerModel": "spark_rapids_ml_tpu.models.scaler.RobustScalerModel",
    "org.apache.spark.ml.feature.VarianceThresholdSelectorModel": "spark_rapids_ml_tpu.models.selector.VarianceThresholdSelectorModel",
}


def _resolve_load_class(cls, klass, path: str):
    """THE load-time class policy, shared by both layouts: the recorded
    (or mapped) class wins when it satisfies the caller; a caller that is
    a RICHER subclass upgrades the load (wrappers add behavior, not state
    — the train-local / serve-on-Spark handoff depends on this); anything
    else is a mismatch. ``Saveable`` itself accepts everything."""
    if cls is Saveable or issubclass(klass, cls):
        return klass
    if issubclass(cls, klass):
        return cls
    raise TypeError(f"{path} holds a {klass.__name__}, not a {cls.__name__}")


class MLWriter:
    """Spark-style fluent writer: ``model.write().overwrite().save(path)``.

    ``overwrite()`` arms replacement of an existing save (previously a stub
    that nothing read — VERDICT r2 weak #7); ``option/format`` accept the
    Spark-layout switch: ``model.write().format("spark").save(path)``.
    """

    def __init__(self, instance: "Saveable"):
        self._instance = instance
        self._overwrite = False
        self._layout = "native"

    def overwrite(self) -> "MLWriter":
        self._overwrite = True
        return self

    def format(self, layout: str) -> "MLWriter":
        if layout not in ("native", "spark"):
            raise ValueError("format must be 'native' or 'spark'")
        self._layout = layout
        return self

    def save(self, path: str) -> None:
        self._instance.save(path, overwrite=self._overwrite, layout=self._layout)


class Saveable(Params):
    """DefaultParamsWritable/Readable analog.

    Subclasses override ``_saveData``/``_loadData`` for ndarray payloads
    (models); pure-params stages (estimators, Normalizer) need nothing else.
    Models with a stock-Spark twin additionally implement
    ``_saveSparkML``/``_fromSparkML`` for ``layout="spark"``.
    """

    def save(
        self, path: str, overwrite: bool = False, layout: str = "native"
    ) -> None:
        # validate EVERYTHING before touching the filesystem: an overwrite
        # must never delete the old save and then fail to write a new one
        if layout not in ("native", "spark"):
            raise ValueError("layout must be 'native' or 'spark'")
        if layout == "spark" and type(self)._saveSparkML is Saveable._saveSparkML:
            raise NotImplementedError(
                f"{type(self).__name__} has no stock Spark ML twin; "
                "use the native layout"
            )
        fs = persistence._FS(path)
        if fs.exists():
            if not overwrite:
                raise FileExistsError(
                    f"{path} already exists (use overwrite=True or "
                    "write().overwrite())"
                )
            fs.rmtree()
        if layout == "spark":
            self._saveSparkML(path)
            return
        persistence.save_metadata(path, self)
        data = self._saveData()
        if data:
            persistence.save_arrays(path, data)

    def write(self) -> MLWriter:
        return MLWriter(self)

    def _saveData(self) -> dict[str, np.ndarray]:
        return {}

    def _saveSparkML(self, path: str) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} has no stock Spark ML twin; "
            "use the native layout"
        )

    @classmethod
    def load(cls, path: str) -> Any:
        if persistence.is_spark_ml_layout(path):
            return cls._load_spark_layout(path)
        meta = persistence.load_metadata(path)
        module, _, qualname = meta["class"].rpartition(".")
        klass = getattr(importlib.import_module(module), qualname)
        klass = _resolve_load_class(cls, klass, path)
        # composite models (PipelineModel, OneVsRestModel, ...) persist
        # sub-models in subdirectories their own ``load`` knows how to
        # read; the generic array path would return them EMPTY. Delegate
        # whenever the resolved class overrides load — unless that class
        # is the entry point itself (it already runs its own body).
        if (
            klass is not cls
            and getattr(klass.load, "__func__", None)
            is not Saveable.load.__func__
        ):
            return klass.load(path)
        data = {}
        if persistence._FS(path).exists("data.parquet"):
            data = persistence.load_arrays(path)
        instance = klass._fromSaved(meta["uid"], data)
        instance._restoreParamState(meta)
        return instance

    @classmethod
    def _load_spark_layout(cls, path: str) -> Any:
        meta = persistence.load_spark_ml_metadata(path)
        spark_class = meta.get("class", "")
        target = _SPARK_ML_CLASSES.get(spark_class)
        if target is None:
            raise TypeError(
                f"{path} holds a Spark ML {spark_class!r} save with no "
                f"mapped implementation here (mapped: "
                f"{sorted(_SPARK_ML_CLASSES)})"
            )
        module, _, qualname = target.rpartition(".")
        klass = getattr(importlib.import_module(module), qualname)
        klass = _resolve_load_class(cls, klass, path)
        instance = klass._fromSparkML(meta, persistence.load_spark_ml_data(path))
        _restore_spark_params(instance, meta)
        return instance

    @classmethod
    def _fromSaved(cls, uid: str, data: dict[str, np.ndarray]):
        return cls(uid=uid)

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> Any:
        raise NotImplementedError


def _restore_spark_params(instance: Params, meta: dict) -> None:
    """Apply a Spark-layout metadata's param maps onto ``instance``, keeping
    only param names this implementation knows (Spark-only params like
    ``handleInvalid`` are dropped silently — they have no effect here)."""
    known = {p.name for p in type(instance).params()}
    for k, v in meta.get("defaultParamMap", {}).items():
        if k in known:
            instance._defaultParamMap[k] = v
    for k, v in meta.get("paramMap", {}).items():
        if k in known:
            instance._paramMap[k] = v


def spark_set_params(instance: Params) -> dict:
    """The explicitly-set params of ``instance``, JSON-shaped — what a
    Spark-layout save records in ``paramMap``."""
    return {k: persistence._jsonable(v) for k, v in instance._paramMap.items()}


# Transform-nesting depth per thread: PipelineModel.transform → per-stage
# transforms, OneVsRestModel → per-class model transforms. Mirror of
# ``_fit_depth`` below; only the outermost transform exports.
_transform_depth = threading.local()


def _is_lazy_plan(out: Any) -> bool:
    """A localspark DataFrame: a lazy plan whose partition generator
    (``_parts``) executes at action time and can be re-pointed."""
    return callable(getattr(out, "_parts", None)) and hasattr(out, "_derive")


def _defer_transform_finalize(df: Any, cap, finalize) -> None:
    """Arrange for ``finalize`` to run when ``df`` first materializes.

    ``transform`` on a localspark DataFrame returns a *plan* — no partition
    function has run yet, so finalizing at return would report zero rows.
    Re-point the instance's ``_parts`` generator: the wrapper restores the
    transform_id contextvar for the duration of execution (so worker-merge
    telemetry and log records stamp correctly) and closes the capture once
    the plan is first exhausted. Derived frames (select/filter over the
    result) read ``self._parts`` at iteration time, so they hit the wrapper
    too.
    """
    from spark_rapids_ml_tpu import telemetry

    orig = df._parts

    def parts_with_capture():
        token = telemetry.set_current_transform_id(cap.transform_id)
        try:
            yield from orig()
        finally:
            try:
                telemetry.reset_current_transform_id(token)
            except ValueError:  # pragma: no cover - foreign-context reuse
                pass
            finalize()

    df._parts = parts_with_capture


def _instrumented_transform(transform):
    """Wrap one class's ``transform`` with serve-side telemetry capture.

    Applied by ``Transformer.__init_subclass__`` to every subclass that
    defines its own ``transform`` — models and feature transformers get
    TransformReport/JSONL behavior with zero per-class code, mirroring
    ``_instrumented_fit``. Eager results (arrays, in-core paths) finalize at
    return; lazy localspark plans finalize at first materialization (see
    ``_defer_transform_finalize``); other lazy frames (real pyspark)
    finalize at return with planning-only numbers.
    """

    @functools.wraps(transform)
    def transform_with_telemetry(self, *args, **kwargs):
        from spark_rapids_ml_tpu import telemetry

        depth = getattr(_transform_depth, "value", 0)
        _transform_depth.value = depth + 1
        cap = telemetry.begin_transform(
            type(self).__name__, getattr(self, "uid", "") or ""
        )
        done = False

        def finalize():
            nonlocal done
            if done:
                return
            done = True
            report = telemetry.end_transform(cap)
            telemetry.attach_transform_report(self, report)
            if depth == 0:
                telemetry.export_transform_report(report)
                telemetry.export_timeline(
                    telemetry.TIMELINE.events(since_seq=cap.tl_seq),
                    transform_id=report.transform_id,
                    estimator=report.transformer,
                    uid=report.uid,
                )

        try:
            out = transform(self, *args, **kwargs)
        except BaseException:
            _transform_depth.value = depth
            finalize()
            raise
        _transform_depth.value = depth
        if depth == 0 and _is_lazy_plan(out):
            # restore context now (the report window stays open until the
            # plan runs); the _parts wrapper re-establishes transform_id
            # around execution
            telemetry.release_transform_context(cap)
            _defer_transform_finalize(out, cap, finalize)
        else:
            finalize()
        return out

    transform_with_telemetry._telemetry_wrapped = True
    return transform_with_telemetry


class Transformer(Saveable):
    """Pipeline stage with ``transform``.

    ``transform_report`` is the
    :class:`~spark_rapids_ml_tpu.telemetry.TransformReport` of the last
    ``transform()`` call on this instance (per-partition rows/bytes,
    partition latency percentiles, analytical kernel cost); ``None`` before
    the first transform. For lazy localspark results it appears once the
    returned DataFrame materializes.
    """

    transform_report = None

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        transform = cls.__dict__.get("transform")
        if transform is not None and not getattr(
            transform, "_telemetry_wrapped", False
        ):
            cls.transform = _instrumented_transform(transform)

    def transform(self, dataset: Any) -> Any:
        raise NotImplementedError


# Fit-nesting depth per thread: SparkPCA.fit → core PCA.fit, CrossValidator
# → sub-estimator fits. Every level gets its own FitReport (the inner one is
# a sub-window of the outer), but only the OUTERMOST fit exports to the
# JSONL sink — one user-visible fit() is one sink line.
_fit_depth = threading.local()


def _instrumented_fit(fit):
    """Wrap one class's ``fit`` with telemetry capture.

    Applied by ``Estimator.__init_subclass__`` to every subclass that
    defines its own ``fit`` — the 20+ estimators get FitReport/JSONL
    behavior with zero per-estimator code. The telemetry import is deferred
    to call time so importing ``models.base`` never pulls in jax.
    """

    @functools.wraps(fit)
    def fit_with_telemetry(self, *args, **kwargs):
        from spark_rapids_ml_tpu import telemetry

        depth = getattr(_fit_depth, "value", 0)
        _fit_depth.value = depth + 1
        try:
            cap = telemetry.begin_fit(
                type(self).__name__, getattr(self, "uid", "") or ""
            )
        except BaseException:
            # begin_fit can refuse the fit (health-driven admission
            # control); the depth must not leak or every later fit in this
            # thread would be treated as nested and never exported
            _fit_depth.value = depth
            raise
        try:
            model = fit(self, *args, **kwargs)
        finally:
            _fit_depth.value = depth
            report = telemetry.end_fit(cap)
        telemetry.attach_report(model, report)
        if depth == 0:
            telemetry.export_fit_report(report)
            # flight-recorder window for this outermost fit: everything
            # recorded since begin_fit's watermark, including worker events
            # merged in via the task-protocol telemetry trailer
            telemetry.export_timeline(
                telemetry.TIMELINE.events(since_seq=cap.tl_seq),
                fit_id=report.fit_id,
                estimator=report.estimator,
                uid=report.uid,
                overlap_fraction=report.overlap_fraction,
            )
        return model

    fit_with_telemetry._telemetry_wrapped = True
    return fit_with_telemetry


class Estimator(Saveable):
    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        fit = cls.__dict__.get("fit")
        if fit is not None and not getattr(fit, "_telemetry_wrapped", False):
            cls.fit = _instrumented_fit(fit)

    def fit(self, dataset: Any) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator.

    ``fit_report`` is the :class:`~spark_rapids_ml_tpu.telemetry.FitReport`
    of the fit that produced this model (phase latency percentiles,
    rows/bytes ingested, compile cost, peak device memory); ``None`` on
    loaded models — telemetry describes a fit, not a file.
    """

    fit_report = None
