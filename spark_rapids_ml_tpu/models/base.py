"""Estimator / Transformer / Model base classes with save/load.

The Spark ML pipeline-stage contract the reference builds on:
``Estimator.fit(dataset) -> Model``, ``Transformer.transform(dataset)``,
``MLWritable.save/MLReadable.load`` (RapidsPCA.scala:52-88,102-185).
"""

from __future__ import annotations

import importlib
from pathlib import Path
from typing import Any

import numpy as np

from spark_rapids_ml_tpu.models.params import Params
from spark_rapids_ml_tpu.utils import persistence


class Saveable(Params):
    """DefaultParamsWritable/Readable analog.

    Subclasses override ``_saveData``/``_loadData`` for ndarray payloads
    (models); pure-params stages (estimators, Normalizer) need nothing else.
    """

    def save(self, path: str, overwrite: bool = False) -> None:
        p = Path(path)
        if p.exists() and not overwrite:
            raise FileExistsError(f"{path} already exists (use overwrite=True)")
        persistence.save_metadata(p, self)
        data = self._saveData()
        if data:
            persistence.save_arrays(p, data)

    # Spark-style fluent alias: model.write().overwrite().save(path) collapses
    # to save(path, overwrite=True) here.
    def write(self) -> "Saveable":
        return self

    def overwrite(self) -> "Saveable":
        self._overwrite = True
        return self

    def _saveData(self) -> dict[str, np.ndarray]:
        return {}

    @classmethod
    def load(cls, path: str) -> Any:
        meta = persistence.load_metadata(path)
        module, _, qualname = meta["class"].rpartition(".")
        klass = getattr(importlib.import_module(module), qualname)
        if not issubclass(klass, cls) and cls is not Saveable:
            raise TypeError(f"{path} holds a {klass.__name__}, not a {cls.__name__}")
        data = {}
        if (Path(path) / "data.parquet").exists():
            data = persistence.load_arrays(path)
        instance = klass._fromSaved(meta["uid"], data)
        instance._restoreParamState(meta)
        return instance

    @classmethod
    def _fromSaved(cls, uid: str, data: dict[str, np.ndarray]):
        return cls(uid=uid)


class Transformer(Saveable):
    def transform(self, dataset: Any) -> Any:
        raise NotImplementedError


class Estimator(Saveable):
    def fit(self, dataset: Any) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""
