"""A Spark-ML-shaped Params system.

The reference's estimator layer leans on Spark ML's ``Params`` machinery —
typed ``Param`` objects with defaults, fluent ``setX`` builders, ``copy``
with uid preservation, and JSON round-tripping through
``DefaultParamsWriter/Reader`` (RapidsPCA.scala:34-45,193-229). This module
provides the same contract natively in Python so estimators here feel
byte-identical to the reference's API surface
(``PCA().setInputCol("features").setK(3).fit(df)``).
"""

from __future__ import annotations

import copy as _copy
import uuid
from typing import Any, Callable, Generic, TypeVar

T = TypeVar("T")


class Param(Generic[T]):
    """A typed parameter descriptor owned by a Params class."""

    def __init__(self, name: str, doc: str, convert: Callable[[Any], T] | None = None):
        self.name = name
        self.doc = doc
        self.convert = convert

    def __repr__(self):
        return f"Param({self.name})"


class _ParamsMeta(type):
    """Applies constructor param kwargs AFTER the whole ``__init__`` chain.

    ``Params.__init__`` runs first in every subclass chain, so applying
    kwargs there means setters fire before any subclass ``_setDefault`` —
    a setter that reads a sibling param via ``getOrDefault`` during
    validation would KeyError at construction. Deferring to post-``__init__``
    gives setters the fully-defaulted instance the fluent spelling
    (``PCA().setK(3)``) gives them.
    """

    def __call__(cls, *args, **kwargs):
        obj = super().__call__(*args, **kwargs)
        pending = obj.__dict__.pop("_pendingCtorKwargs", None)
        if pending:
            obj._applyCtorKwargs(pending)
        return obj


class Params(metaclass=_ParamsMeta):
    """Base class carrying a param map + default map keyed by param name.

    Mirrors Spark ML semantics: explicitly-set values shadow defaults
    (``getOrDefault``), ``copy()`` deep-copies the maps but keeps class
    identity, and ``uid`` identifies instances across save/load.
    """

    def __init__(self, uid: str | None = None, **kwargs):
        self.uid = uid or f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self._paramMap: dict[str, Any] = {}
        self._defaultParamMap: dict[str, Any] = {}
        # pyspark.ml-style constructor params: PCA(k=3) == PCA().setK(3).
        # Stashed here and applied by _ParamsMeta once the full __init__
        # chain (including every subclass _setDefault) has run.
        self._pendingCtorKwargs = kwargs

    def _applyCtorKwargs(self, kwargs: dict[str, Any]) -> None:
        # Values route through the fluent setter when the class defines one,
        # so setter-side validation (setInitMode's allowed values, ...) holds
        # for both spellings; None means "leave unset", as in pyspark.
        # Applied in the caller's keyword order.
        for name, value in kwargs.items():
            if value is None:
                continue
            self._param(name)  # unknown params raise KeyError
            setter = getattr(self, f"set{name[0].upper()}{name[1:]}", None)
            if callable(setter):
                setter(value)
            else:
                self._set(**{name: value})

    # -- param discovery ----------------------------------------------------
    @classmethod
    def params(cls) -> list[Param]:
        out = []
        for klass in cls.__mro__:
            for v in vars(klass).values():
                if isinstance(v, Param) and v not in out:
                    out.append(v)
        return out

    def _param(self, name: str) -> Param:
        for p in type(self).params():
            if p.name == name:
                return p
        raise KeyError(f"{type(self).__name__} has no param {name!r}")

    # -- get/set ------------------------------------------------------------
    def _set(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            p = self._param(name)
            if value is not None and p.convert is not None:
                value = p.convert(value)
            self._paramMap[name] = value
        return self

    def _setDefault(self, **kwargs) -> "Params":
        self._defaultParamMap.update(kwargs)
        return self

    def isSet(self, name: str) -> bool:
        return name in self._paramMap

    def hasDefault(self, name: str) -> bool:
        return name in self._defaultParamMap

    def getOrDefault(self, name: str) -> Any:
        if name in self._paramMap:
            return self._paramMap[name]
        if name in self._defaultParamMap:
            return self._defaultParamMap[name]
        raise KeyError(f"param {name!r} is not set and has no default")

    # -- lifecycle ----------------------------------------------------------
    def copy(self) -> "Params":
        other = _copy.copy(self)
        other._paramMap = dict(self._paramMap)
        other._defaultParamMap = dict(self._defaultParamMap)
        return other

    def _copyValues(self, to: "Params") -> "Params":
        """Propagate this instance's params onto ``to`` (estimator → model),
        like Spark's ``copyValues`` (used at RapidsPCA.scala:79)."""
        for p in type(to).params():
            if p.name in self._paramMap:
                to._paramMap[p.name] = self._paramMap[p.name]
        return to

    def explainParams(self) -> str:
        lines = []
        for p in type(self).params():
            cur = self._paramMap.get(p.name, self._defaultParamMap.get(p.name))
            lines.append(f"{p.name}: {p.doc} (current: {cur})")
        return "\n".join(lines)

    # -- persistence hooks (see utils.persistence) --------------------------
    def _paramState(self) -> dict:
        return {"paramMap": dict(self._paramMap), "defaultParamMap": dict(self._defaultParamMap)}

    def _restoreParamState(self, state: dict) -> None:
        self._paramMap.update(state.get("paramMap", {}))
        self._defaultParamMap.update(state.get("defaultParamMap", {}))


# ---------------------------------------------------------------------------
# Shared param mixins (Spark ML's HasInputCol / HasOutputCol / PCAParams shape)
# ---------------------------------------------------------------------------


class HasInputCol(Params):
    inputCol = Param("inputCol", "name of the input ArrayType column", str)

    def setInputCol(self, value: str):
        return self._set(inputCol=value)

    def getInputCol(self) -> str:
        return self.getOrDefault("inputCol")


class HasOutputCol(Params):
    outputCol = Param("outputCol", "name of the output column", str)

    def setOutputCol(self, value: str):
        return self._set(outputCol=value)

    def getOutputCol(self) -> str:
        return self.getOrDefault("outputCol")


class HasFeaturesCol(Params):
    featuresCol = Param("featuresCol", "name of the features ArrayType column", str)

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)

    def getFeaturesCol(self) -> str:
        return self.getOrDefault("featuresCol")


class HasLabelCol(Params):
    labelCol = Param("labelCol", "name of the scalar label column", str)

    def setLabelCol(self, value: str):
        return self._set(labelCol=value)

    def getLabelCol(self) -> str:
        return self.getOrDefault("labelCol")


class HasPredictionCol(Params):
    predictionCol = Param("predictionCol", "name of the prediction output column", str)

    def setPredictionCol(self, value: str):
        return self._set(predictionCol=value)

    def getPredictionCol(self) -> str:
        return self.getOrDefault("predictionCol")
