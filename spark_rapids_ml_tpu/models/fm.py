"""FMRegressor / FMClassifier — pyspark.ml's factorization machines.

The degree-2 FM score (Rendle 2010, the formulation Spark implements):

    ŷ(x) = b + wᵀx + ½ Σ_f [ (Σ_i v_{if} x_i)² − Σ_i v_{if}² x_i² ]

— the pairwise-interaction term is two matmuls via the (Σvx)² − Σ(vx)²
identity, which is exactly the MXU-friendly recast that makes FMs a
natural fit here. Training mirrors the MLP module's shape: the WHOLE
optimization (Spark's adamW default or gd) runs as one
``lax.while_loop`` XLA program over the full-batch loss — squared for
the regressor, logistic for the classifier — with ``regParam`` applied
as DECOUPLED weight decay under adamW (Spark's semantics) or loss-side
L2 under gd, plus ``factorSize``, ``fitIntercept``/``fitLinear``, and
``initStd`` matching Spark's param surface. (Spark additionally offers
``miniBatchFraction``; full batch — its default 1.0 — is the one mode
here, documented.)
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    Param,
)
from spark_rapids_ml_tpu.ops.linalg import DEFAULT_PRECISION
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

_SOLVERS = ("adamW", "gd")


def _split(flat, n_feat: int, k: int):
    """flat = [b, w (n), V (n·k)] — Spark's layout order reversed for
    convenience; the model re-exposes the pieces by name."""
    b = flat[0]
    w = flat[1 : 1 + n_feat]
    v = flat[1 + n_feat :].reshape(n_feat, k)
    return b, w, v


def fm_score(flat, x, *, n_feat: int, k: int, precision=DEFAULT_PRECISION):
    """[rows] FM scores via the two-matmul interaction identity."""
    b, w, v = _split(flat, n_feat, k)
    linear = jnp.matmul(x, w, precision=precision)
    xv = jnp.matmul(x, v, precision=precision)  # [rows, k]
    x2v2 = jnp.matmul(x * x, v * v, precision=precision)
    inter = 0.5 * jnp.sum(xv * xv - x2v2, axis=1)
    return b + linear + inter


@partial(
    jax.jit,
    static_argnames=(
        "n_feat", "k", "solver", "max_iter", "classification",
        "fit_intercept", "fit_linear",
    ),
)
def train_fm(
    flat0,
    x,
    y,
    w,
    *,
    n_feat: int,
    k: int,
    solver: str,
    max_iter: int,
    classification: bool,
    fit_intercept: bool,
    fit_linear: bool,
    step_size: float = 1.0,
    reg_param: float = 0.0,
    tol: float = 1e-6,
):
    """Full-batch FM training as one XLA program → (flat, loss, iters)."""
    import optax

    w_sum = jnp.maximum(jnp.sum(w), 1.0)
    # mask freezes disabled parameter groups at zero (Spark's
    # fitIntercept/fitLinear switches)
    mask = jnp.concatenate(
        [
            jnp.asarray([1.0 if fit_intercept else 0.0], flat0.dtype),
            jnp.full((n_feat,), 1.0 if fit_linear else 0.0, flat0.dtype),
            jnp.ones((n_feat * k,), flat0.dtype),
        ]
    )

    # Spark's adamW semantics: regParam is DECOUPLED weight decay (the
    # thing AdamW exists for), never an L2 term routed through Adam's
    # moment normalization; 'gd' keeps the equivalent loss-side L2.
    # Frozen parameter groups sit at exactly 0, so decay is a no-op there.
    l2_in_loss = reg_param if solver == "gd" else 0.0

    def loss_fn(flat):
        s = fm_score(flat * mask, x, n_feat=n_feat, k=k)
        if classification:
            yy = 2.0 * y - 1.0  # logistic loss on ±1
            data = jnp.sum(w * jnp.logaddexp(0.0, -yy * s)) / w_sum
        else:
            data = jnp.sum(w * (y - s) ** 2) / w_sum
        return data + l2_in_loss * jnp.sum((flat * mask) ** 2)

    opt = (
        optax.adamw(step_size, weight_decay=reg_param)
        if solver == "adamW"
        else optax.sgd(step_size)
    )

    def cond(carry):
        _, _, it, prev, cur = carry
        return (it < max_iter) & (jnp.abs(prev - cur) > tol)

    def body(carry):
        flat, state, it, _, cur = carry
        value, grad = jax.value_and_grad(loss_fn)(flat)
        updates, state = opt.update(grad * mask, state, flat)
        flat = optax.apply_updates(flat, updates) * mask
        return flat, state, it + 1, value, loss_fn(flat)

    state0 = opt.init(flat0)
    inf = jnp.asarray(jnp.inf, flat0.dtype)
    flat, _, it, _, loss = jax.lax.while_loop(
        cond, body, (flat0 * mask, state0, jnp.int32(0), inf, loss_fn(flat0))
    )
    return flat, loss, it


class _FMParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    factorSize = Param("factorSize", "latent factor dimension k", int)
    fitIntercept = Param("fitIntercept", "fit the global bias", bool)
    fitLinear = Param("fitLinear", "fit the 1-way (linear) term", bool)
    regParam = Param("regParam", "L2 regularization", float)
    maxIter = Param("maxIter", "maximum optimizer iterations", int)
    stepSize = Param("stepSize", "optimizer learning rate", float)
    tol = Param("tol", "convergence tolerance on the loss decrease", float)
    solver = Param("solver", "'adamW' (default, Spark's) or 'gd'", str)
    initStd = Param("initStd", "factor-init standard deviation", float)
    seed = Param("seed", "factor-initialization seed", int)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            featuresCol="features", labelCol="label",
            predictionCol="prediction",
            factorSize=8, fitIntercept=True, fitLinear=True, regParam=0.0,
            maxIter=100, stepSize=1.0, tol=1e-6, solver="adamW",
            initStd=0.01, seed=0,
        )

    def getFactorSize(self) -> int:
        return self.getOrDefault("factorSize")


class _FMEstimator(_FMParams, Estimator):
    _classification: bool

    def setFactorSize(self, value: int):
        if value < 1:
            raise ValueError(f"factorSize must be >= 1, got {value}")
        return self._set(factorSize=value)

    def setFitIntercept(self, value: bool):
        return self._set(fitIntercept=bool(value))

    def setFitLinear(self, value: bool):
        return self._set(fitLinear=bool(value))

    def setRegParam(self, value: float):
        if value < 0:
            raise ValueError(f"regParam must be >= 0, got {value}")
        return self._set(regParam=float(value))

    def setMaxIter(self, value: int):
        return self._set(maxIter=value)

    def setStepSize(self, value: float):
        if value <= 0:
            raise ValueError(f"stepSize must be > 0, got {value}")
        return self._set(stepSize=float(value))

    def setTol(self, value: float):
        return self._set(tol=float(value))

    def setSolver(self, value: str):
        if value not in _SOLVERS:
            raise ValueError(f"solver must be one of {_SOLVERS}, got {value!r}")
        return self._set(solver=value)

    def setInitStd(self, value: float):
        if value <= 0:
            raise ValueError(f"initStd must be > 0, got {value}")
        return self._set(initStd=float(value))

    def setSeed(self, value: int):
        return self._set(seed=value)

    def fit(self, dataset: Any, num_partitions: int | None = None):
        """``num_partitions`` accepted for signature uniformity; training
        is one full-batch XLA program."""
        parts = columnar.labeled_partitions(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("labelCol"),
            None,
            weight_col=None,
        )
        x = np.concatenate([p[0] for p in parts])
        y = np.concatenate([p[1] for p in parts])
        w = (
            np.concatenate([p[2] for p in parts])
            if parts[0][2] is not None
            else None
        )
        if self._classification:
            classes = np.unique(y)
            if not np.all(np.isin(classes, (0.0, 1.0))):
                raise ValueError(
                    f"FMClassifier requires binary 0/1 labels, got {classes[:8]}"
                )
        n_feat = x.shape[1]
        k = self.getFactorSize()
        padded, yv, wv, _ = columnar.pad_labeled_batch(x, y, w)
        fdt = jax.dtypes.canonicalize_dtype(padded.dtype)

        key = jax.random.PRNGKey(self.getOrDefault("seed"))
        flat0 = jnp.concatenate(
            [
                jnp.zeros((1 + n_feat,), fdt),
                self.getOrDefault("initStd")
                * jax.random.normal(key, (n_feat * k,), fdt),
            ]
        )
        with trace_range("fm train"):
            flat, loss, it = train_fm(
                flat0,
                jnp.asarray(padded),
                jnp.asarray(yv),
                jnp.asarray(wv),
                n_feat=n_feat,
                k=k,
                solver=self.getOrDefault("solver"),
                max_iter=self.getOrDefault("maxIter"),
                classification=self._classification,
                fit_intercept=self.getOrDefault("fitIntercept"),
                fit_linear=self.getOrDefault("fitLinear"),
                step_size=self.getOrDefault("stepSize"),
                reg_param=self.getOrDefault("regParam"),
                tol=self.getOrDefault("tol"),
            )
        weights = np.asarray(flat)
        if not np.isfinite(weights).all():
            raise ValueError(
                "FM training diverged to non-finite weights; lower stepSize"
            )
        model = self._model_cls(
            uid=self.uid, flatWeights=weights, numFeatures=n_feat,
            trainLoss=float(loss), iterations=int(it),
        )
        return self._copyValues(model)


class _FMModel(_FMParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        flatWeights: np.ndarray | None = None,
        numFeatures: int = 0,
        trainLoss: float = float("nan"),
        iterations: int = 0,
    ):
        super().__init__(uid)
        self.flatWeights = (
            None if flatWeights is None else np.asarray(flatWeights)
        )
        self._num_features = int(numFeatures)
        self.trainLoss = float(trainLoss)
        self.iterations = int(iterations)

    @property
    def numFeatures(self) -> int:
        return self._num_features

    @property
    def intercept(self) -> float:
        return float(self.flatWeights[0])

    @property
    def linear(self) -> np.ndarray:
        return self.flatWeights[1 : 1 + self._num_features]

    @property
    def factors(self) -> np.ndarray:
        k = self.getFactorSize()
        return self.flatWeights[1 + self._num_features :].reshape(
            self._num_features, k
        )

    def _scores(self, mat: np.ndarray) -> np.ndarray:
        if mat.shape[1] != self._num_features:
            raise ValueError(
                f"input has {mat.shape[1]} features but the model was "
                f"fitted on {self._num_features}"
            )
        fdt = columnar.float_dtype_for(mat.dtype)
        padded, true_rows = columnar.pad_rows(mat.astype(fdt, copy=False))
        out = _fm_score_jitted(
            jnp.asarray(self.flatWeights.astype(fdt)),
            jnp.asarray(padded),
            n_feat=self._num_features,
            k=self.getFactorSize(),
        )
        return np.asarray(out)[:true_rows]

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "flatWeights": self.flatWeights,
            "meta": np.asarray(
                [
                    float(self._num_features),
                    self.trainLoss,
                    float(self.iterations),
                ]
            ),
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            flatWeights=data["flatWeights"],
            numFeatures=int(data["meta"][0]),
            trainLoss=float(data["meta"][1]),
            iterations=int(data["meta"][2]),
        )


#: module-level jit: jax caches compilations per (shape, static args)
_fm_score_jitted = jax.jit(fm_score, static_argnames=("n_feat", "k"))


class FMRegressor(_FMEstimator):
    _classification = False

    @property
    def _model_cls(self):
        return FMRegressionModel


class FMRegressionModel(_FMModel):
    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        return self._scores(mat)

    def transform(self, dataset: Any) -> Any:
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )

    def predict(self, row) -> float:
        return float(
            self._predict_matrix(np.asarray(row, dtype=np.float64)[None, :])[0]
        )


class _FMClassifierCols:
    probabilityCol = Param("probabilityCol", "class-probability column", str)
    rawPredictionCol = Param(
        "rawPredictionCol", "margin column [−s, s]", str
    )

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            probabilityCol="probability", rawPredictionCol="rawPrediction"
        )

    def setProbabilityCol(self, value: str):
        return self._set(probabilityCol=value)

    def setRawPredictionCol(self, value: str):
        return self._set(rawPredictionCol=value)


class FMClassifier(_FMClassifierCols, _FMEstimator):
    _classification = True

    @property
    def _model_cls(self):
        return FMClassificationModel


class FMClassificationModel(_FMClassifierCols, _FMModel):
    @property
    def numClasses(self) -> int:
        return 2

    @staticmethod
    def _outputs_from_scores(s: np.ndarray):
        """THE decision rule in one place: (proba [rows, 2], preds)."""
        from scipy.special import expit  # overflow-free sigmoid

        p1 = expit(s)
        return np.stack([1.0 - p1, p1], axis=1), (s > 0).astype(np.float64)

    def proba_and_predictions(self, mat: np.ndarray):
        return self._outputs_from_scores(self._scores(mat))

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        return self._outputs_from_scores(self._scores(mat))[1]

    def transform(self, dataset: Any) -> Any:
        if columnar.has_named_columns(dataset):
            mat = columnar.extract_matrix(
                dataset, self.getOrDefault("featuresCol")
            )
            s = self._scores(mat)
            proba, preds = self._outputs_from_scores(s)
            return columnar.append_columns(
                dataset,
                [
                    (
                        self.getOrDefault("rawPredictionCol"),
                        np.stack([-s, s], axis=1),
                    ),
                    (self.getOrDefault("probabilityCol"), proba),
                    (self.getOrDefault("predictionCol"), preds),
                ],
            )
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )

    def predict(self, row) -> float:
        return float(
            self._predict_matrix(np.asarray(row, dtype=np.float64)[None, :])[0]
        )
