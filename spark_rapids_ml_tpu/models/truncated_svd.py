"""TruncatedSVD estimator — direct low-rank factorization of uncentered X.

A sibling of PCA for the LSA/recommender use case: identical partition
architecture (per-partition device statistics, tree-reduced; SURVEY.md §3.1
shape) but the model is the SVD of X itself — which, for uncentered data, is
exactly what the reference's PCA *actually* computes (its meanCentering is a
TODO stub, RapidsRowMatrix.scala:111-117), here exposed under the name that
matches the semantics. Differences from PCA:

- no centering param at all — TruncatedSVD is defined on raw X;
- the model carries ``singularValues`` (σᵢ of X, the √λ the reference
  computes in ``calSVD``'s seqRoot step, rapidsml_jni.cu:254) instead of the
  normalized explainedVariance ratio;
- ``explained_variance_ratio`` is still derivable and provided as a method.

Solvers mirror PCA's: 'gram' (Gram + refined eigh — the reference-shaped
route), 'svd' (TSQR direct, cond(X) accuracy), 'randomized' (HMT on the
Gram), 'auto'.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import HasInputCol, HasOutputCol, Param
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range


class TruncatedSVDParams(HasInputCol, HasOutputCol):
    k = Param("k", "number of singular vectors to keep", int)
    precision = Param(
        "precision",
        "MXU matmul precision for the Gram pass ('highest'/'high'/'default')",
        str,
    )
    solver = Param(
        "solver",
        "decomposition solver: 'gram' (Gram + refined eigh), 'svd' (TSQR "
        "direct), 'randomized' (HMT subspace iteration), 'auto'",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        from spark_rapids_ml_tpu.utils.config import get_config

        self._setDefault(
            outputCol="svd_features",
            precision=get_config().default_precision,
            solver="gram",
        )

    def getK(self) -> int:
        return self.getOrDefault("k")


_gram = jax.jit(L.gram, static_argnames=("precision",))
_qr_r = jax.jit(L.qr_r)
_combine_r = jax.jit(L.combine_r)
_project = jax.jit(L.project)


_svd_values_from_r_jit = jax.jit(L.svd_components_from_r, static_argnums=(1,))

def _decompose_gram(g: jax.Array, k: int, solver: str):
    """Gram → (components [n, k], singular values [n or l])."""
    n = g.shape[0]
    if solver == "auto":
        solver = "randomized" if L.randomized_profitable(n, k) else "gram"
    if solver == "randomized":
        u, s, _ = L.randomized_eigh_descending(g, k)
        return u, s
    if solver != "gram":
        # setSolver validates, but constructor kwargs / ParamGridBuilder maps
        # bypass it — fail loudly rather than silently running the eigh path.
        raise ValueError(f"unknown solver {solver!r}")
    components, s = L.eigh_descending(g)
    return components[:, :k], s


_decompose_gram_jit = jax.jit(_decompose_gram, static_argnums=(1, 2))


class TruncatedSVD(TruncatedSVDParams, Estimator):
    """Top-k SVD of the (uncentered) input matrix.

    >>> model = TruncatedSVD().setInputCol("f").setK(10).fit(df)
    >>> reduced = model.transform(df)
    """

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)

    def setK(self, value: int) -> "TruncatedSVD":
        return self._set(k=value)

    def setPrecision(self, value: str) -> "TruncatedSVD":
        if value not in L.PRECISIONS:
            raise ValueError(f"precision must be one of {sorted(L.PRECISIONS)}")
        return self._set(precision=value)

    def setSolver(self, value: str) -> "TruncatedSVD":
        if value not in ("gram", "svd", "randomized", "auto"):
            raise ValueError(
                "solver must be 'gram', 'svd', 'randomized', or 'auto'"
            )
        return self._set(solver=value)

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "TruncatedSVDModel":
        input_col = self._paramMap.get("inputCol") or self._defaultParamMap.get(
            "inputCol"
        )
        ds = columnar.PartitionedDataset.from_any(dataset, input_col, num_partitions)
        k = self.getK()
        solver = self.getOrDefault("solver")

        from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks
        from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce

        with trace_range("tsvd reduce"):
            mats = list(ds.matrices())
            n_cols = mats[0].shape[1]
            for m in mats[1:]:
                if m.shape[1] != n_cols:
                    raise ValueError(
                        f"inconsistent feature dim: {m.shape[1]} != {n_cols}"
                    )
            if k > n_cols:
                raise ValueError(f"k={k} must be <= number of features {n_cols}")

            if solver == "svd":

                def task(mat):
                    padded, _ = columnar.pad_rows(mat)
                    return _qr_r(jnp.asarray(padded))

                reduced = tree_reduce(run_partition_tasks(task, mats), _combine_r)
            else:
                prec = L.PRECISIONS[self.getOrDefault("precision")]

                def task(mat):
                    padded, _ = columnar.pad_rows(mat)
                    return _gram(jnp.asarray(padded), precision=prec)

                reduced = tree_reduce(
                    run_partition_tasks(task, mats), lambda a, b: a + b
                )

        with trace_range("tsvd decompose"):
            if solver == "svd":
                components, s = _svd_values_from_r_jit(reduced, k)
            else:
                components, evals_sqrt = _decompose_gram_jit(reduced, k, solver)
                s = evals_sqrt

        model = TruncatedSVDModel(
            uid=self.uid,
            components=np.asarray(components),
            singularValues=np.asarray(s[:k]),
        )
        return self._copyValues(model)


class TruncatedSVDModel(TruncatedSVDParams, Model):
    """Fitted model: ``components`` [n, k], ``singularValues`` [k] (σ of X)."""

    def __init__(
        self,
        uid: str | None = None,
        components: np.ndarray | None = None,
        singularValues: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.components = None if components is None else np.asarray(components)
        self.singularValues = (
            None if singularValues is None else np.asarray(singularValues)
        )

    def explained_variance_ratio(self) -> np.ndarray:
        """σᵢ/Σσ over the *retained* spectrum — note the reference's PCA
        normalizes over the full spectrum; a truncated model only has k
        values, so this ratio is relative to what was kept."""
        total = self.singularValues.sum()
        return self.singularValues / (total if total > 0 else 1.0)

    def _project_matrix(self, mat: np.ndarray) -> np.ndarray:
        padded, true_rows = columnar.pad_rows(mat)
        xd = jnp.asarray(padded)
        out = _project(xd, jnp.asarray(self.components, dtype=xd.dtype))
        return np.asarray(out)[:true_rows]

    def transform(self, dataset: Any) -> Any:
        with trace_range("tsvd transform"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._project_matrix,
            )

    def transform_rows(self, rows) -> list[np.ndarray]:
        ct = self.components.T
        return [ct @ np.asarray(r) for r in rows]

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"components": self.components, "singularValues": self.singularValues}

    @classmethod
    def _fromSaved(cls, uid: str, data: dict[str, np.ndarray]) -> "TruncatedSVDModel":
        return cls(
            uid=uid,
            components=data["components"],
            singularValues=data["singularValues"],
        )
