"""KMeans estimator/model — the stretch estimator (BASELINE.json config 5).

Spark MLlib-shaped params (``k``, ``maxIter``, ``tol``, ``seed``,
``initMode``); Lloyd iterations run as per-partition device passes producing
``KMeansStats`` monoids, tree-reduced across partitions — structurally
identical to PCA's fit, so the same mesh/psum reducer swaps in for SPMD
execution. Seeding is k-means++ on a bounded row sample (the role Spark's
k-means|| plays at cluster scale).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.autotune.policy import resolve_policy
from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import HasInputCol, HasOutputCol, Param
from spark_rapids_ml_tpu.ops import kmeans as KM
from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

_MAX_INIT_SAMPLE = 16384

#: module-level jit so transform/computeCost reuse one compiled program per
#: shape bucket instead of retracing per call (tpulint TPL003 convention)
_assign_clusters_jit = jax.jit(KM.assign_clusters)


def _resume_kmeans_checkpoint(checkpoint_dir: str | None, k: int):
    """(centers-or-None, start_iter, cost, checkpointer-or-None) for a Lloyd
    loop, resuming from the newest durable checkpoint when one exists — the
    ONE resume contract both the core and Spark-path fits share (the KMeans
    analog of linear.py's ``_resume_newton_checkpoint``)."""
    if checkpoint_dir is None:
        return None, 0, np.inf, None
    from spark_rapids_ml_tpu.utils.checkpoint import TrainingCheckpointer

    ckpt = TrainingCheckpointer(checkpoint_dir)
    resumed = ckpt.latest()
    if resumed is None:
        return None, 0, np.inf, ckpt
    step, arrays, state = resumed
    if arrays["centers"].shape[0] != k:
        raise ValueError(
            f"checkpoint at {checkpoint_dir} holds "
            f"{arrays['centers'].shape[0]} centers but k={k}; "
            "point checkpoint_dir at a fresh directory to train "
            "with different params"
        )
    return arrays["centers"], step + 1, float(state.get("cost", np.inf)), ckpt


class _KMeansParams(HasInputCol, HasOutputCol):
    k = Param("k", "number of clusters", int)
    maxIter = Param("maxIter", "maximum Lloyd iterations", int)
    tol = Param("tol", "convergence tolerance on max centroid movement", float)
    seed = Param("seed", "random seed", int)
    initMode = Param(
        "initMode",
        "'k-means||' (distributed oversampling init, Bahmani et al. — "
        "Spark MLlib's default; scales to large k because candidates come "
        "from cost-proportional passes over ALL rows), 'k-means++' (on a "
        "bounded driver-side sample), or 'random'",
        str,
    )
    initSteps = Param(
        "initSteps", "number of k-means|| oversampling rounds (Spark: 2)", int
    )
    weightCol = Param(
        "weightCol",
        "optional instance-weight column (Spark ML weightCol contract); "
        "weighted Lloyd sums/counts/cost ride the same per-row vector that "
        "masks shape-bucketing padding",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            maxIter=20, tol=1e-4, seed=0, initMode="k-means++", initSteps=2,
            outputCol="prediction",
        )

    def getK(self) -> int:
        return self.getOrDefault("k")

    def getMaxIter(self) -> int:
        return self.getOrDefault("maxIter")

    def getTol(self) -> float:
        return self.getOrDefault("tol")

    def getSeed(self) -> int:
        return self.getOrDefault("seed")

    def getInitMode(self) -> str:
        return self.getOrDefault("initMode")

    def getInitSteps(self) -> int:
        return self.getOrDefault("initSteps")


class KMeans(_KMeansParams, Estimator):
    def setK(self, value: int) -> "KMeans":
        return self._set(k=value)

    def setMaxIter(self, value: int) -> "KMeans":
        return self._set(maxIter=value)

    def setTol(self, value: float) -> "KMeans":
        return self._set(tol=value)

    def setSeed(self, value: int) -> "KMeans":
        return self._set(seed=value)

    def setInitMode(self, value: str) -> "KMeans":
        if value not in ("k-means||", "k-means++", "random"):
            raise ValueError(
                "initMode must be 'k-means||', 'k-means++', or 'random'"
            )
        return self._set(initMode=value)

    def setInitSteps(self, value: int) -> "KMeans":
        if value < 1:
            raise ValueError(f"initSteps must be >= 1, got {value}")
        return self._set(initSteps=value)

    def setWeightCol(self, value: str) -> "KMeans":
        return self._set(weightCol=value)

    def _init_centers(
        self,
        mats: list[np.ndarray],
        k: int,
        part_weights=None,
    ) -> np.ndarray:
        if self.getInitMode() == "k-means||":
            return self._kmeans_parallel_init(mats, part_weights, k)
        rng = np.random.default_rng(self.getSeed())
        # bounded sample across partitions for seeding; zero-weight rows are
        # excluded instances and must never seed a center (a zero-count
        # center would survive Lloyd updates unchanged)
        if part_weights is not None:
            mats = [m[w > 0] for m, w in zip(mats, part_weights)]
            mats = [m for m in mats if len(m)]
        total = sum(len(m) for m in mats)
        take = min(total, _MAX_INIT_SAMPLE)
        sample = np.concatenate(
            [m[rng.choice(len(m), max(1, int(take * len(m) / total)), replace=False)]
             for m in mats]
        )
        if self.getInitMode() == "random":
            idx = rng.choice(len(sample), k, replace=False)
            return sample[idx]
        key = jax.random.PRNGKey(self.getSeed())
        centers = KM.kmeans_plus_plus_init(key, jnp.asarray(sample), k)
        return np.asarray(centers)

    def _kmeans_parallel_init(
        self, mats: list[np.ndarray], part_weights, k: int
    ) -> np.ndarray:
        """k-means‖ (Bahmani et al., VLDB'12 — Spark MLlib's default init):
        ``initSteps`` rounds of cost-proportional oversampling (ℓ = 2k
        expected candidates per round) where EVERY row of every partition is
        a Bernoulli trial with p = ℓ·w·d²/φ, then a candidate-weighting pass
        (rows owned per candidate) and a weighted k-means++ reduction to k.
        Unlike the bounded-sample k-means++ path, candidate quality does not
        degrade with k: at k=1000 the candidate pool is ~2·initSteps·k points
        drawn from the full dataset's cost distribution (the r2 verdict's
        config-5 gap)."""
        rng = np.random.default_rng(self.getSeed())
        ell = 2.0 * k
        pairs = []
        for i, m in enumerate(mats):
            w = (
                np.ones(len(m), dtype=np.float64)
                if part_weights is None
                else np.asarray(part_weights[i], dtype=np.float64)
            )
            keep = w > 0
            if keep.any():
                pairs.append((m[keep], w[keep]))
        if not pairs:
            raise ValueError("no rows with positive weight to seed from")

        # first candidate: one weight-proportional row
        totals = np.array([w.sum() for _, w in pairs])
        pi = rng.choice(len(pairs), p=totals / totals.sum())
        m0, w0 = pairs[pi]
        candidates = [m0[rng.choice(len(m0), p=w0 / w0.sum())]]

        for _ in range(self.getInitSteps()):
            c = np.stack(candidates)
            d2s = [
                np.asarray(
                    KM.min_sq_dists(jnp.asarray(m), jnp.asarray(c, dtype=m.dtype))
                )
                for m, _ in pairs
            ]
            phi = sum(float(np.dot(d2, w)) for d2, (_, w) in zip(d2s, pairs))
            if phi <= 0.0:  # every row coincides with a candidate
                break
            for d2, (m, w) in zip(d2s, pairs):
                p_sel = np.minimum(1.0, ell * w * d2 / phi)
                sel = rng.random(len(m)) < p_sel
                if sel.any():
                    candidates.extend(m[sel])

        cand = np.stack(candidates)
        if len(cand) <= k:
            # degenerate oversampling (tiny data or phi collapsed): top up
            # with uniform rows so exactly k centers come out
            extra_pool = np.concatenate([m for m, _ in pairs])
            need = k - len(cand)
            if need > 0:
                idx = rng.choice(len(extra_pool), need, replace=False)
                cand = np.concatenate([cand, extra_pool[idx]])
            return cand[:k]

        # weighting pass: instance-weighted row counts owned by each candidate
        counts = np.zeros(len(cand), dtype=np.float64)
        for m, w in pairs:
            labels, _ = KM.assign_clusters(
                jnp.asarray(m), jnp.asarray(cand, dtype=m.dtype)
            )
            np.add.at(counts, np.asarray(labels), w)
        key = jax.random.PRNGKey(self.getSeed())
        centers = KM.weighted_kmeans_plus_plus_init(
            key, jnp.asarray(cand), jnp.asarray(counts), k
        )
        return np.asarray(centers)

    def fit(
        self,
        dataset: Any,
        num_partitions: int | None = None,
        *,
        sample_weight=None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
    ) -> "KMeansModel":
        """Lloyd training with optional mid-training checkpoint/resume.

        With ``checkpoint_dir`` set, training state (centers, iteration,
        cost) is durably checkpointed every ``checkpoint_every`` iterations,
        and an interrupted fit pointed at the same directory resumes from the
        newest checkpoint instead of re-seeding — a capability the reference
        lacks entirely (model persistence only, SURVEY.md §5).
        """
        if checkpoint_every < 1:
            raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
        input_col = self._paramMap.get("inputCol")
        ds = columnar.PartitionedDataset.from_any(dataset, input_col, num_partitions)
        k = self.getK()
        tol_sq = self.getTol() ** 2
        mats = list(ds.matrices())  # materialize ONCE (extraction may copy)
        part_weights = columnar.resolve_partition_weights(
            dataset, mats, self._paramMap.get("weightCol"), sample_weight
        )

        centers, start_iter, cost, ckpt = _resume_kmeans_checkpoint(
            checkpoint_dir, k
        )
        if centers is None:
            with trace_range("kmeans init"):
                centers = self._init_centers(mats, k, part_weights)

        # pre-pad partitions once; the weight vector masks padding (0) and
        # carries instance weights (1.0 when unweighted) on true rows
        padded = []
        for i, mat in enumerate(mats):
            pm, true_rows = columnar.pad_rows(mat)
            w = np.zeros(pm.shape[0], columnar.float_dtype_for(pm.dtype))
            w[:true_rows] = 1.0 if part_weights is None else part_weights[i]
            padded.append((jnp.asarray(pm), jnp.asarray(w)))

        n_cols = padded[0][0].shape[1]
        if centers.shape[1] != n_cols:
            raise ValueError(
                f"checkpoint/init centers have {centers.shape[1]} features but "
                f"the dataset has {n_cols}; is checkpoint_dir stale?"
            )

        # env-selected distance policy (bf16 or int8 cross terms); the
        # Lloyd accumulators inside kmeans_stats stay full precision
        dist_policy = resolve_policy(None)
        with trace_range("kmeans lloyd"):
            for it in range(start_iter, self.getMaxIter()):
                c = jnp.asarray(centers)
                partials = [
                    KM.kmeans_stats(x, c, w, policy=dist_policy)
                    for x, w in padded
                ]
                stats = tree_reduce(partials, KM.combine_kmeans_stats)
                new_centers = np.asarray(KM.update_centers(stats, c))
                cost = float(stats.cost)
                shift = float(KM.center_shift_sq(c, jnp.asarray(new_centers)))
                centers = new_centers
                if ckpt is not None and (it + 1) % checkpoint_every == 0:
                    ckpt.save(it, {"centers": centers}, {"cost": cost})
                if shift <= tol_sq:
                    break

        model = KMeansModel(uid=self.uid, clusterCenters=centers, trainingCost=cost)
        return self._copyValues(model)


class KMeansModel(_KMeansParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        clusterCenters: np.ndarray | None = None,
        trainingCost: float = float("nan"),
    ):
        super().__init__(uid)
        self.clusterCenters = (
            None if clusterCenters is None else np.asarray(clusterCenters)
        )
        self.trainingCost = trainingCost

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        padded, true_rows = columnar.pad_rows(mat)
        xd = jnp.asarray(padded)
        labels, _ = _assign_clusters_jit(
            xd, jnp.asarray(self.clusterCenters, dtype=xd.dtype)
        )
        return np.asarray(labels)[:true_rows]

    def transform(self, dataset: Any) -> Any:
        """Append an integer ``prediction`` column (Spark KMeansModel shape)."""
        with trace_range("kmeans transform"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._predict_matrix,
            )

    def predict(self, row) -> int:
        """Single-row prediction (host path)."""
        d = np.sum((self.clusterCenters - np.asarray(row)[None, :]) ** 2, axis=1)
        return int(np.argmin(d))

    def computeCost(self, dataset: Any) -> float:
        """Sum of squared distances to nearest centroid (inertia)."""
        input_col = self._paramMap.get("inputCol")
        ds = columnar.PartitionedDataset.from_any(dataset, input_col)
        total = 0.0
        for mat in ds.matrices():
            padded, true_rows = columnar.pad_rows(mat)
            xd = jnp.asarray(padded)
            _, dists = _assign_clusters_jit(
                xd, jnp.asarray(self.clusterCenters, dtype=xd.dtype)
            )
            total += float(jnp.sum(dists[:true_rows]))
        return total

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "clusterCenters": self.clusterCenters,
            "trainingCost": np.asarray([self.trainingCost]),
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            clusterCenters=data["clusterCenters"],
            trainingCost=float(data["trainingCost"][0]),
        )
