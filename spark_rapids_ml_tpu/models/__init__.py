"""Estimator/Model layer — Spark-ML-shaped API over the JAX kernel core."""
