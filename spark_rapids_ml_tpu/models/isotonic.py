"""IsotonicRegression — pyspark.ml's monotone 1-D regression.

Spark's surface mirrored: ``isotonic`` (True = non-decreasing, False =
antitonic), ``featureIndex`` (which feature of a vector column is the
predictor), ``weightCol``; the model holds the stepwise (boundaries,
predictions) pair and predicts by the same interpolation rule Spark
documents (linear between boundaries, clamped outside).

Fit is pool-adjacent-violators (PAV) on the weighted points after
sorting by feature — O(n log n) host work on three 1-D arrays. This is a
deliberate host-side solve: PAV's data-dependent pool merging is the
antithesis of XLA's static control flow, and the arrays are tiny next to
any feature matrix this framework touches (the accelerator story for
this estimator is the ingestion path it shares with everything else).
The sklearn differential in the tests is exact: both implement the same
L2 PAV.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    Param,
)
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range


def _pav(y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Weighted L2 pool-adjacent-violators: the non-decreasing fit of y.

    Classic stack algorithm: maintain merged blocks (weighted mean, total
    weight, count); a new point merges backward while it violates
    monotonicity. O(n) after the sort the caller did."""
    means: list[float] = []
    weights: list[float] = []
    counts: list[int] = []
    for yi, wi in zip(y, w):
        m, ww, c = float(yi), float(wi), 1
        while means and means[-1] > m:
            pm, pw, pc = means.pop(), weights.pop(), counts.pop()
            total = pw + ww
            m = (pm * pw + m * ww) / total if total > 0 else m
            ww = total
            c += pc
        means.append(m)
        weights.append(ww)
        counts.append(c)
    return np.repeat(means, counts)


class _IsotonicParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    isotonic = Param(
        "isotonic", "True = non-decreasing (default), False = antitonic", bool
    )
    featureIndex = Param(
        "featureIndex", "feature column index used as the predictor", int
    )
    weightCol = Param("weightCol", "optional instance-weight column", str)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            featuresCol="features", labelCol="label",
            predictionCol="prediction", isotonic=True, featureIndex=0,
        )

    def getIsotonic(self) -> bool:
        return self.getOrDefault("isotonic")

    def getFeatureIndex(self) -> int:
        return self.getOrDefault("featureIndex")


class IsotonicRegression(_IsotonicParams, Estimator):
    def setIsotonic(self, value: bool) -> "IsotonicRegression":
        return self._set(isotonic=bool(value))

    def setFeatureIndex(self, value: int) -> "IsotonicRegression":
        if value < 0:
            raise ValueError(f"featureIndex must be >= 0, got {value}")
        return self._set(featureIndex=value)

    def setWeightCol(self, value: str) -> "IsotonicRegression":
        return self._set(weightCol=value)

    def fit(self, dataset: Any, num_partitions: int | None = None):
        # num_partitions is accepted for Estimator-signature uniformity but
        # ignored: PAV is a host-side 1-D solve with no partitioned phase
        parts = columnar.labeled_partitions(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("labelCol"),
            None,
            weight_col=self._paramMap.get("weightCol"),
        )
        fi = self.getFeatureIndex()
        xs = np.concatenate([p[0] for p in parts])
        if not 0 <= fi < xs.shape[1]:
            raise ValueError(
                f"featureIndex={fi} out of range for {xs.shape[1]} features"
            )
        x = xs[:, fi].astype(np.float64)
        y = np.concatenate([p[1] for p in parts]).astype(np.float64)
        w = (
            np.concatenate([p[2] for p in parts]).astype(np.float64)
            if parts[0][2] is not None
            else np.ones(len(x))
        )
        with trace_range("isotonic pav"):
            # zero-weight points carry no information (sklearn drops them)
            live = w > 0
            x, y, w = x[live], y[live], w[live]
            order = np.argsort(x, kind="stable")
            xs_sorted, ys_sorted, ws_sorted = x[order], y[order], w[order]
            # pool duplicate x into one weighted point BEFORE PAV — the
            # isotonic optimum (sklearn's make_unique / SPARK-28727); a
            # post-PAV average of individually-fitted tie points is NOT
            # the L2 minimizer
            uniq_x, first_idx = np.unique(xs_sorted, return_index=True)
            w_pool = np.add.reduceat(ws_sorted, first_idx)
            y_pool = (
                np.add.reduceat(ws_sorted * ys_sorted, first_idx) / w_pool
            )
            sign = 1.0 if self.getIsotonic() else -1.0
            preds = sign * _pav(sign * y_pool, w_pool)
        model = IsotonicRegressionModel(
            uid=self.uid, boundaries=uniq_x, predictions=preds
        )
        return self._copyValues(model)


class IsotonicRegressionModel(_IsotonicParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        boundaries: np.ndarray | None = None,
        predictions: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.boundaries = (
            None if boundaries is None else np.asarray(boundaries)
        )
        self.predictions = (
            None if predictions is None else np.asarray(predictions)
        )

    def _predict_values(self, v: np.ndarray) -> np.ndarray:
        """Spark's prediction rule: linear interpolation between
        boundaries, clamped to the edge predictions outside the range."""
        return np.interp(v, self.boundaries, self.predictions)

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        fi = self.getFeatureIndex()
        if not 0 <= fi < mat.shape[1]:
            raise ValueError(
                f"featureIndex={fi} out of range for {mat.shape[1]} features"
            )
        return self._predict_values(mat[:, fi].astype(np.float64))

    def transform(self, dataset: Any) -> Any:
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )

    def predict(self, value: float) -> float:
        return float(self._predict_values(np.asarray([value]))[0])

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "boundaries": self.boundaries,
            "predictions": self.predictions,
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            boundaries=data["boundaries"],
            predictions=data["predictions"],
        )
