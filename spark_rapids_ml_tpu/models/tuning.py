"""Model selection: param grids, evaluators, cross-validation.

Spark ML's ``pyspark.ml.tuning``/``pyspark.ml.evaluation`` surface for this
framework — a capability the reference module lacks entirely (its user does
model selection by hand around `fit`). API mirrors Spark: ``ParamGridBuilder``
→ list of param maps, ``CrossValidator``/``TrainValidationSplit`` estimators
whose fitted models delegate ``transform`` to the best sub-model.

TPU note: every candidate fit reuses the same jitted kernels (jax.jit caches
by shape, and the fold row-counts are bucket-padded by the estimators), so a
k-fold × m-candidate sweep compiles each kernel once, not k·m times.
"""

from __future__ import annotations

import warnings
from typing import Any

import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import (
    HasLabelCol,
    HasPredictionCol,
    Param,
    Params,
)
from spark_rapids_ml_tpu.utils import columnar

try:
    import pyarrow as pa
except Exception:  # pragma: no cover
    pa = None


# ---------------------------------------------------------------------------
# Dataset row helpers (container-generic)
# ---------------------------------------------------------------------------


def _is_spark_df(dataset: Any) -> bool:
    return columnar.is_spark_dataframe(dataset)


def _column_names(dataset) -> list[str]:
    """Column names of any supported container ([] when nameless)."""
    schema = getattr(dataset, "schema", None)
    if schema is not None and hasattr(schema, "names"):
        return list(schema.names)  # Spark-likes AND arrow tables/batches
    cols = getattr(dataset, "columns", None)  # pandas-likes
    return list(cols) if cols is not None else []


def _df_columns(df, *cols: str) -> list[np.ndarray]:
    """Collect the named DataFrame columns in ONE job (separate collects
    would re-execute the lineage per column and rely on cross-job row-order
    stability for metric alignment). Scalar columns come back as [rows]
    vectors, array/Vector columns as [rows, n] matrices; toArrow fast path
    when the backend has it."""
    selected = df.select(*cols)
    if hasattr(selected, "toArrow"):
        table = selected.toArrow()
        out = []
        for c in cols:
            col = table.column(c)
            typ = col.type
            if pa.types.is_floating(typ) or pa.types.is_integer(typ):
                out.append(
                    np.asarray(col.to_numpy(zero_copy_only=False), dtype=np.float64)
                )
            else:
                out.append(columnar.extract_matrix(table, c))
        return out
    rows = selected.collect()
    out = []
    for i, _ in enumerate(cols):
        vals = [r[i] for r in rows]
        if vals and (
            np.isscalar(vals[0]) or isinstance(vals[0], (int, float))
        ):
            out.append(np.asarray(vals, dtype=np.float64))
        else:
            out.append(
                np.stack([columnar.row_vector_to_ndarray(v) for v in vals])
            )
    return out


def n_rows(dataset: Any) -> int:
    if _is_spark_df(dataset):
        return dataset.count()
    if isinstance(dataset, tuple) and len(dataset) in (2, 3):
        return len(np.asarray(dataset[0]))
    if pa is not None and isinstance(dataset, (pa.Table, pa.RecordBatch)):
        return dataset.num_rows
    if isinstance(dataset, columnar.PartitionedDataset):
        return sum(m.shape[0] for m in dataset.matrices())
    if hasattr(dataset, "iloc"):
        return len(dataset)
    arr = np.asarray(dataset)
    if arr.ndim == 0:
        raise TypeError(
            f"unsupported dataset container for row splitting: {type(dataset).__name__}"
        )
    return len(arr)


def row_slice(dataset: Any, idx: np.ndarray) -> Any:
    """Take rows by integer index, preserving the container type.

    PartitionedDataset callers: collect once (``_collect_for_split``) before
    repeated slicing — this branch re-concatenates the partitions per call.
    """
    idx = np.asarray(idx)
    if isinstance(dataset, tuple) and len(dataset) in (2, 3):
        # (X, y), weighted (X, y, w), or unweighted (X, y, None)
        return tuple(
            None if part is None else np.asarray(part)[idx] for part in dataset
        )
    if pa is not None and isinstance(dataset, (pa.Table, pa.RecordBatch)):
        return dataset.take(pa.array(idx))
    if isinstance(dataset, columnar.PartitionedDataset):
        return columnar.PartitionedDataset(
            [dataset.collect_matrix()[idx]], dataset.input_col
        )
    if hasattr(dataset, "iloc"):
        return dataset.iloc[idx]
    arr = np.asarray(dataset)
    if arr.ndim == 0:
        raise TypeError(
            f"unsupported dataset container for row splitting: {type(dataset).__name__}"
        )
    return arr[idx]


def _collect_for_split(dataset: Any) -> Any:
    """Normalize containers that are expensive to slice repeatedly: a
    PartitionedDataset is collected to one matrix ONCE per fit (k-fold CV
    slices 2k times; re-concatenating every time would copy the whole
    dataset O(k) times). Partitioning is a fit-time distribution detail the
    candidate estimators re-establish via ``num_partitions`` anyway."""
    if isinstance(dataset, columnar.PartitionedDataset):
        return dataset.collect_matrix()
    return dataset


def _labels_of(dataset: Any, label_col: str) -> np.ndarray:
    if isinstance(dataset, tuple) and len(dataset) in (2, 3):
        return np.asarray(dataset[1], dtype=np.float64)
    if _is_spark_df(dataset):
        return _df_columns(dataset, label_col)[0]
    return columnar.extract_vector(dataset, label_col)


# ---------------------------------------------------------------------------
# Param grid
# ---------------------------------------------------------------------------


class ParamGridBuilder:
    """Cartesian-product grids of param settings.

    >>> grid = (ParamGridBuilder()
    ...         .addGrid("regParam", [0.0, 0.1])
    ...         .addGrid("fitIntercept", [True, False])
    ...         .build())
    """

    def __init__(self):
        self._grid: dict[str, list] = {}
        self._base: dict[str, Any] = {}

    def addGrid(self, param: "Param | str", values) -> "ParamGridBuilder":
        name = param.name if isinstance(param, Param) else param
        self._grid[name] = list(values)
        return self

    def baseOn(self, **kwargs) -> "ParamGridBuilder":
        self._base.update(kwargs)
        return self

    def build(self) -> list[dict[str, Any]]:
        maps = [dict(self._base)]
        for name, values in self._grid.items():
            maps = [{**m, name: v} for m in maps for v in values]
        return maps


# ---------------------------------------------------------------------------
# Evaluators
# ---------------------------------------------------------------------------


class Evaluator(Params):
    """Base evaluator. ``weightCol`` (Spark 3.0+ evaluator surface) weights
    every metric by per-instance weights when set: DataFrames read the
    named column, ``(X, y, w)`` tuples use their third slot, other
    containers extract the column by name. Empty (default) = unweighted."""

    weightCol = Param(
        "weightCol", "instance-weight column ('' = unweighted)", str
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(weightCol="")

    def setWeightCol(self, value: str):
        return self._set(weightCol=value)

    def evaluate(self, dataset: Any, predictions: np.ndarray | None = None) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True

    def _labeled_pair(self, dataset, predictions):
        """(labels, predictions, weights-or-None) host vectors — ONE
        DataFrame job for every column including ``weightCol`` (separate
        collects would re-execute the transform lineage and could pair
        weights with the wrong rows under a nondeterministic plan)."""
        label_col = self.getOrDefault("labelCol")
        pred_col = self.getOrDefault("predictionCol")
        weight_col = self.getOrDefault("weightCol")
        if predictions is not None:
            y = _labels_of(dataset, label_col)
            p = np.asarray(predictions, dtype=np.float64).reshape(-1)
            return y, p, self._weights_of(dataset, len(y))
        if _is_spark_df(dataset):
            cols = [label_col, pred_col] + ([weight_col] if weight_col else [])
            got = _df_columns(dataset, *cols)
            w = (
                columnar.validate_weights(got[2], len(got[0]))
                if weight_col
                else None
            )
            return got[0], got[1], w
        y = _labels_of(dataset, label_col)
        return (
            y,
            columnar.extract_vector(dataset, pred_col),
            self._weights_of(dataset, len(y)),
        )

    def _weights_of(self, dataset, n: int) -> np.ndarray | None:
        """[n] validated instance weights when ``weightCol`` is set, else
        None. Tuple containers use their third slot (the framework's
        ``(X, y, w)`` convention) regardless of the column name. For
        DataFrames prefer the pair helpers, which fetch weights in the
        SAME job as the metric columns; this standalone path is the
        fallback for externally-supplied predictions."""
        weight_col = self.getOrDefault("weightCol")
        if not weight_col:
            return None
        if isinstance(dataset, tuple):
            if len(dataset) < 3 or dataset[2] is None:
                raise ValueError(
                    f"weightCol={weight_col!r} is set but the (X, y) tuple "
                    "carries no weight slot; pass (X, y, w)"
                )
            w = np.asarray(dataset[2], dtype=np.float64)
        elif _is_spark_df(dataset):
            w = _df_columns(dataset, weight_col)[0]
        else:
            w = columnar.extract_vector(dataset, weight_col)
        return columnar.validate_weights(w, n)


class RegressionEvaluator(Evaluator, HasLabelCol, HasPredictionCol):
    """rmse (default) / mse / mae / r2 on (labelCol, predictionCol)."""

    metricName = Param("metricName", "rmse|mse|mae|r2|var", str)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(metricName="rmse", labelCol="label", predictionCol="prediction")

    def setMetricName(self, value: str) -> "RegressionEvaluator":
        if value not in ("rmse", "mse", "mae", "r2", "var"):
            raise ValueError("metricName must be rmse, mse, mae, r2, or var")
        return self._set(metricName=value)

    def isLargerBetter(self) -> bool:
        return self.getOrDefault("metricName") in ("r2", "var")

    def evaluate(self, dataset, predictions=None) -> float:
        y, p, w = self._labeled_pair(dataset, predictions)
        if w is None:
            w = np.ones_like(y)
        wsum = w.sum()
        err = y - p
        metric = self.getOrDefault("metricName")
        if metric == "mse":
            return float(np.sum(w * err**2) / wsum)
        if metric == "rmse":
            return float(np.sqrt(np.sum(w * err**2) / wsum))
        if metric == "mae":
            return float(np.sum(w * np.abs(err)) / wsum)
        ybar = float(np.sum(w * y) / wsum)
        if metric == "var":
            # Spark's explainedVariance: mean (pred - label-mean)^2
            return float(np.sum(w * (p - ybar) ** 2) / wsum)
        ss_tot = float(np.sum(w * (y - ybar) ** 2))
        return 1.0 - float(np.sum(w * err**2)) / (ss_tot if ss_tot > 0 else 1.0)


def _tied_group_weights(
    p: np.ndarray, w: np.ndarray, pos_mask: np.ndarray, *, descending: bool
) -> tuple[np.ndarray, np.ndarray]:
    """Per-tied-score-group (positive-weight, negative-weight) sums in
    score order — the ONE sort/group/accumulate kernel both binary curve
    metrics (ROC's Mann–Whitney, PR's threshold sweep) share, so tie and
    weight handling can never diverge between them."""
    key = -p if descending else p
    order = np.argsort(key, kind="mergesort")
    ks, ws, pm = key[order], w[order], pos_mask[order]
    _, group = np.unique(ks, return_inverse=True)
    n_groups = group.max() + 1
    g_pos = np.zeros(n_groups)
    g_neg = np.zeros(n_groups)
    np.add.at(g_pos, group, np.where(pm, ws, 0.0))
    np.add.at(g_neg, group, np.where(~pm, ws, 0.0))
    return g_pos, g_neg


class BinaryClassificationEvaluator(Evaluator, HasLabelCol, HasPredictionCol):
    """areaUnderROC (default, rank statistic over scores), areaUnderPR
    (trapezoid over the per-threshold precision/recall curve), or accuracy.

    For areaUnderROC, scores come from ``rawPredictionCol`` when the
    dataset carries it — a probability or raw-margin VECTOR column (the
    pyspark.ml convention; the last element is the positive-class score —
    so a LogisticRegression ``probabilityCol`` output plugs in directly)
    or a scalar score column. AUC is a rank statistic, invariant to any
    monotone transform, so margins and probabilities score identically.
    Falls back to ``predictionCol`` when absent (hard labels give the
    degenerate two-level AUC). ``accuracy`` always uses ``predictionCol``.
    """

    metricName = Param(
        "metricName", "areaUnderROC|areaUnderPR|accuracy", str
    )
    rawPredictionCol = Param(
        "rawPredictionCol",
        "score column for areaUnderROC: vector (last element used) or "
        "scalar; falls back to predictionCol when the column is absent",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            metricName="areaUnderROC", labelCol="label",
            predictionCol="prediction", rawPredictionCol="rawPrediction",
        )

    def setMetricName(self, value: str) -> "BinaryClassificationEvaluator":
        if value not in ("areaUnderROC", "areaUnderPR", "accuracy"):
            raise ValueError(
                "metricName must be areaUnderROC, areaUnderPR, or accuracy"
            )
        return self._set(metricName=value)

    def setRawPredictionCol(self, value: str) -> "BinaryClassificationEvaluator":
        return self._set(rawPredictionCol=value)

    def _score_pair(self, dataset):
        """(labels, scores) with a score column preferred for ranking.

        Column choice: ``rawPredictionCol`` if present, else a
        ``probability`` column (this framework's classifiers emit
        probabilityCol, conventionally named 'probability', and never a
        'rawPrediction' column — without this fallback the out-of-the-box
        evaluator would silently rank on hard labels), else degrade to
        ``predictionCol`` with a warning (hard labels give the degenerate
        two-level AUC)."""
        label_col = self.getOrDefault("labelCol")
        weight_col = self.getOrDefault("weightCol")
        columns = _column_names(dataset)
        score_col = None
        for candidate in (self.getOrDefault("rawPredictionCol"), "probability"):
            if candidate and candidate in columns:
                score_col = candidate
                break
        if score_col is not None:
            w = None
            if _is_spark_df(dataset):
                cols = [label_col, score_col] + (
                    [weight_col] if weight_col else []
                )
                got = _df_columns(dataset, *cols)  # ONE job incl. weights
                y, s = got[0], got[1]
                if weight_col:
                    w = columnar.validate_weights(got[2], len(y))
            else:
                y = _labels_of(dataset, label_col)
                try:  # vector column ([rows, C] probability/margins)...
                    s = columnar.extract_matrix(dataset, score_col)
                except (TypeError, ValueError):  # ...or a scalar score
                    s = columnar.extract_vector(dataset, score_col)
                w = self._weights_of(dataset, len(y))
            s = np.asarray(s, dtype=np.float64)
            if s.ndim == 2:
                s = s[:, -1]  # positive-class score, pyspark.ml convention
            return y, s, w
        warnings.warn(
            "BinaryClassificationEvaluator: no score column found (looked "
            f"for {self.getOrDefault('rawPredictionCol')!r} and "
            "'probability'); areaUnderROC/areaUnderPR degrade to the "
            "two-level curve of "
            "hard labels. Point rawPredictionCol at your model's "
            "probability output (e.g. setRawPredictionCol('probability') "
            "with LogisticRegression().setProbabilityCol('probability')).",
            stacklevel=3,
        )
        return self._labeled_pair(dataset, None)

    def evaluate(self, dataset, predictions=None) -> float:
        if self.getOrDefault("metricName") == "accuracy":
            y, p, w = self._labeled_pair(dataset, predictions)
            hits = ((p >= 0.5) == (y >= 0.5)).astype(np.float64)
            if w is None:
                return float(np.mean(hits))
            return float(np.sum(w * hits) / w.sum())
        if predictions is not None:
            y, p, w = self._labeled_pair(dataset, predictions)
        else:
            y, p, w = self._score_pair(dataset)
        if w is None:
            w = np.ones_like(p)
        if self.getOrDefault("metricName") == "areaUnderPR":
            return self._area_under_pr(y, p, w)
        pos_mask = y >= 0.5
        w_pos_total = float(w[pos_mask].sum())
        w_neg_total = float(w[~pos_mask].sum())
        if w_pos_total == 0.0 or w_neg_total == 0.0:
            return 0.5
        # Weighted Mann–Whitney with tie correction:
        # AUC = Σ_{i∈pos} w_i·(W_neg(score<s_i) + ½·W_neg(score=s_i)) / (W⁺·W⁻)
        # computed by one sort over tied-score groups.
        gw_pos, gw_neg = _tied_group_weights(p, w, pos_mask, descending=False)
        cum_neg_before = np.concatenate([[0.0], np.cumsum(gw_neg)[:-1]])
        auc_num = float(np.sum(gw_pos * (cum_neg_before + 0.5 * gw_neg)))
        return auc_num / (w_pos_total * w_neg_total)

    @staticmethod
    def _area_under_pr(y, p, w) -> float:
        """Weighted PR AUC by trapezoid over the per-threshold
        (recall, precision) points, descending thresholds, with the curve
        anchored at (0, precision-of-first-group) — Spark's linear
        interpolation convention (BinaryClassificationMetrics.pr), vs the
        step interpolation some libraries use; differences show up only in
        the last decimals on tied-score data. A positive-free dataset
        scores 0.0."""
        pos = y >= 0.5
        w_pos_total = float(w[pos].sum())
        if w_pos_total == 0.0:
            return 0.0
        g_tp, g_neg = _tied_group_weights(p, w, pos, descending=True)
        tp = np.cumsum(g_tp)
        retrieved = np.cumsum(g_tp + g_neg)
        # leading groups made ENTIRELY of zero-weight rows carry no mass:
        # keeping them would anchor the curve at 0/0 = NaN and poison the
        # trapezoid (validate_weights allows individual zero weights)
        nz = retrieved > 0
        tp, retrieved = tp[nz], retrieved[nz]
        recall = tp / w_pos_total
        precision = tp / retrieved
        r = np.concatenate([[0.0], recall])
        pr = np.concatenate([[precision[0]], precision])
        return float(np.sum(np.diff(r) * 0.5 * (pr[1:] + pr[:-1])))


class MulticlassClassificationEvaluator(Evaluator, HasLabelCol, HasPredictionCol):
    """Spark's ``pyspark.ml.evaluation.MulticlassClassificationEvaluator``
    surface: f1 (default, class-frequency-weighted), accuracy,
    weightedPrecision, weightedRecall on (labelCol, predictionCol), and
    logLoss on (labelCol, probabilityCol) — the metric set that makes the
    multinomial softmax estimator tunable by CV/TVS.

    Weighted metrics follow Spark's definition: per-class scores averaged
    with TRUE-label frequencies as weights (a class predicted but never
    present contributes 0 weight). ``logLoss`` clips probabilities to
    ``eps`` like Spark (MulticlassMetrics logLoss eps=1e-15).
    """

    metricName = Param(
        "metricName",
        "f1|accuracy|weightedPrecision|weightedRecall|logLoss",
        str,
    )
    probabilityCol = Param(
        "probabilityCol",
        "[rows, C] class-probability vector column (logLoss only)",
        str,
    )
    eps = Param("eps", "probability clip floor for logLoss", float)

    _METRICS = ("f1", "accuracy", "weightedPrecision", "weightedRecall", "logLoss")

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            metricName="f1", labelCol="label", predictionCol="prediction",
            probabilityCol="probability", eps=1e-15,
        )

    def setMetricName(self, value: str) -> "MulticlassClassificationEvaluator":
        if value not in self._METRICS:
            raise ValueError(f"metricName must be one of {self._METRICS}")
        return self._set(metricName=value)

    def setProbabilityCol(self, value: str) -> "MulticlassClassificationEvaluator":
        return self._set(probabilityCol=value)

    def isLargerBetter(self) -> bool:
        return self.getOrDefault("metricName") != "logLoss"

    def _prob_pair(self, dataset, predictions):
        """(labels, [rows, C] probabilities) for logLoss."""
        label_col = self.getOrDefault("labelCol")
        prob_col = self.getOrDefault("probabilityCol")
        if predictions is not None:
            probs = np.asarray(predictions, dtype=np.float64)
            if probs.ndim == 1 and probs.size and 0.0 <= probs.min() and probs.max() <= 1.0:
                # binary models surface P(class 1) as a [rows] vector
                # (LogisticRegressionModel.predict_proba_matrix's 2-class
                # contract) — promote to the [rows, 2] layout Spark's
                # probability column uses so logLoss works on binary data
                probs = np.stack([1.0 - probs, probs], axis=1)
            if probs.ndim != 2:
                raise ValueError(
                    "logLoss needs a [rows, C] probability matrix (or a "
                    "[rows] binary P(class 1) vector); got shape "
                    f"{probs.shape}. Pass the model's probability output, "
                    "or evaluate the transformed DataFrame carrying "
                    f"{prob_col!r}"
                )
            y = _labels_of(dataset, label_col)
            return y, probs, self._weights_of(dataset, len(y))
        if prob_col not in _column_names(dataset):
            raise ValueError(
                f"logLoss needs probability column {prob_col!r}; set the "
                "model's probabilityCol (e.g. "
                "LogisticRegression().setProbabilityCol('probability')) or "
                "this evaluator's setProbabilityCol"
            )
        weight_col = self.getOrDefault("weightCol")
        if _is_spark_df(dataset):
            cols = [label_col, prob_col] + ([weight_col] if weight_col else [])
            got = _df_columns(dataset, *cols)  # ONE job incl. weights
            y, probs = got[0], got[1]
            w = (
                columnar.validate_weights(got[2], len(y))
                if weight_col
                else None
            )
        else:
            y = _labels_of(dataset, label_col)
            probs = columnar.extract_matrix(dataset, prob_col)
            w = self._weights_of(dataset, len(y))
        return y, np.asarray(probs, dtype=np.float64), w

    def evaluate(self, dataset, predictions=None) -> float:
        metric = self.getOrDefault("metricName")
        if metric == "logLoss":
            y, probs, iw = self._prob_pair(dataset, predictions)
            cls = np.asarray(y, dtype=np.int64)
            if cls.min() < 0 or cls.max() >= probs.shape[1]:
                raise ValueError(
                    f"labels span {cls.min()}..{cls.max()} but the "
                    f"probability column has {probs.shape[1]} classes"
                )
            eps = self.getOrDefault("eps")
            picked = np.clip(probs[np.arange(len(cls)), cls], eps, 1.0)
            if iw is None:
                return float(-np.mean(np.log(picked)))
            return float(-np.sum(iw * np.log(picked)) / iw.sum())
        y, p, iw = self._labeled_pair(dataset, predictions)
        if iw is None:
            iw = np.ones_like(y, dtype=np.float64)
        if metric == "accuracy":
            return float(np.sum(iw * (y == p)) / iw.sum())
        classes = np.unique(y)
        true_w = np.array([float(iw[y == c].sum()) for c in classes])
        weights = true_w / true_w.sum()  # class frequency, instance-weighted
        prec = np.zeros(len(classes))
        rec = np.zeros(len(classes))
        for i, c in enumerate(classes):
            tp = float(iw[(p == c) & (y == c)].sum())
            pred_c = float(iw[p == c].sum())
            prec[i] = tp / pred_c if pred_c > 0 else 0.0
            rec[i] = tp / true_w[i] if true_w[i] > 0 else 0.0
        if metric == "weightedPrecision":
            return float(np.sum(weights * prec))
        if metric == "weightedRecall":
            return float(np.sum(weights * rec))
        denom = prec + rec
        f1 = np.where(denom > 0, 2.0 * prec * rec / np.maximum(denom, 1e-300), 0.0)
        return float(np.sum(weights * f1))


class ClusteringEvaluator(Evaluator):
    """Mean silhouette (squared-Euclidean) on (featuresCol, predictionCol).

    Row pairs are O(rows²); rows are subsampled to ``maxRows`` (deterministic)
    above that — the Spark evaluator makes the same tradeoff via its
    squared-Euclidean variant. With ``weightCol`` the per-row a/b means and
    the final silhouette mean are instance-weighted (Spark 3.1 surface);
    the subsample itself stays uniform, so a cap-exceeding weighted
    evaluation is an estimate of the weighted metric.
    """

    featuresCol = Param("featuresCol", "features column", str)
    predictionCol = Param("predictionCol", "cluster assignment column", str)
    maxRows = Param("maxRows", "subsample cap for the pairwise pass", int)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(featuresCol="features", predictionCol="prediction", maxRows=2048)

    def evaluate(self, dataset, predictions=None) -> float:
        feats = self.getOrDefault("featuresCol")
        pred_col = self.getOrDefault("predictionCol")
        weight_col = self.getOrDefault("weightCol")
        cap = self.getOrDefault("maxRows")
        w = None
        if _is_spark_df(dataset) and predictions is None:
            # push the subsample into the PLAN: never materialize more than
            # ~2*cap rows on the driver for a cap-bounded metric
            total = dataset.count()
            if total > cap:
                dataset = dataset.sample(
                    fraction=min(1.0, 2.0 * cap / total), seed=0
                )
            cols = [feats, pred_col] + ([weight_col] if weight_col else [])
            got = _df_columns(dataset, *cols)  # ONE job incl. weights
            x, p = got[0], got[1].astype(np.int64)
            if weight_col:
                w = columnar.validate_weights(got[2], len(x))
        else:
            if isinstance(dataset, tuple):  # (X, _, w?) container
                x = np.asarray(dataset[0], dtype=np.float64)
            elif _is_spark_df(dataset):
                x = _df_columns(dataset, feats)[0]
            else:
                x = columnar.extract_matrix(dataset, feats)
            if predictions is not None:
                p = np.asarray(predictions, dtype=np.float64).reshape(-1).astype(np.int64)
            elif _is_spark_df(dataset):
                p = _df_columns(dataset, pred_col)[0].astype(np.int64)
            else:
                p = columnar.extract_vector(dataset, pred_col).astype(np.int64)
            w = self._weights_of(dataset, len(x))
        if w is None:
            w = np.ones(len(x))
        if len(x) > cap:
            sel = np.random.default_rng(0).choice(len(x), cap, replace=False)
            x, p, w = x[sel], p[sel], w[sel]
        # Gram identity keeps the pairwise pass at one [rows, rows] matrix
        # (the [rows, rows, dims] broadcast would be GBs at default maxRows).
        sq = (x * x).sum(-1)
        d2 = np.maximum(sq[:, None] + sq[None, :] - 2.0 * (x @ x.T), 0.0)
        labels = np.unique(p)
        if len(labels) < 2:
            return 0.0
        sil = np.zeros(len(x))
        for i in range(len(x)):
            same = p == p[i]
            same[i] = False
            w_same = float(w[same].sum())
            if w_same <= 0:
                continue  # (weighted-)singleton cluster: silhouette is 0
            a = float(np.dot(w[same], d2[i, same])) / w_same
            others = [
                float(np.dot(w[p == c], d2[i, p == c])) / float(w[p == c].sum())
                for c in labels
                if c != p[i] and w[p == c].sum() > 0
            ]
            if not others:
                continue  # every other cluster is weight-empty
            b = min(others)
            sil[i] = 0.0 if max(a, b) == 0 else (b - a) / max(a, b)
        return float(np.dot(w, sil) / w.sum())


# ---------------------------------------------------------------------------
# Validators
# ---------------------------------------------------------------------------


def _fit_and_eval(estimator, params, evaluator, train, val):
    est = estimator.copy()
    if params:
        est._set(**params)
    model = est.fit(train)
    # AUC ranks SCORES; a thresholded 0/1 prediction column collapses it to
    # balanced accuracy. When the model exposes a probability surface
    # (LogisticRegression), rank that instead — the Spark evaluator makes
    # the same choice by reading rawPrediction rather than prediction.
    wants_probability_surface = (
        (
            isinstance(evaluator, BinaryClassificationEvaluator)
            and evaluator.getOrDefault("metricName")
            in ("areaUnderROC", "areaUnderPR")
        )
        or (
            isinstance(evaluator, MulticlassClassificationEvaluator)
            and evaluator.getOrDefault("metricName") == "logLoss"
        )
    )
    if wants_probability_surface and hasattr(model, "predict_proba_matrix"):
        fcol = model.getOrDefault("featuresCol")
        lcol = evaluator.getOrDefault("labelCol")
        if isinstance(val, tuple):
            feats = np.asarray(val[0])
            scores = model.predict_proba_matrix(feats)
            return model, evaluator.evaluate(val, predictions=scores)
        if _is_spark_df(val):
            # one job for every column INCLUDING weightCol, so weighted CV
            # ranks on the same probability surface as unweighted CV (the
            # (X, y, w) tuple container carries the weights through)
            wcol = evaluator.getOrDefault("weightCol")
            cols = [fcol, lcol] + ([wcol] if wcol else [])
            got = _df_columns(val, *cols)
            scores = model.predict_proba_matrix(got[0])
            container = tuple(got)
            return model, evaluator.evaluate(container, predictions=scores)
        feats = columnar.extract_matrix(val, fcol)
        scores = model.predict_proba_matrix(feats)
        return model, evaluator.evaluate(val, predictions=scores)
    if isinstance(val, tuple):
        pred = model.transform(val[0])
        return model, evaluator.evaluate(val, predictions=np.asarray(pred))
    out = model.transform(val)
    if isinstance(out, np.ndarray):  # bare-matrix containers: predictions only
        return model, evaluator.evaluate(val, predictions=out)
    return model, evaluator.evaluate(out)


class _ValidatorParams(Params):
    seed = Param("seed", "fold shuffle seed", int)

    def _candidates(self):
        maps = self._maps
        return maps if maps else [{}]


class CrossValidator(_ValidatorParams, Estimator):
    """k-fold cross-validation over a param grid.

    >>> cv = CrossValidator(estimator=LinearRegression(),
    ...                     estimatorParamMaps=grid,
    ...                     evaluator=RegressionEvaluator(),
    ...                     numFolds=3)
    >>> best = cv.fit((x, y)).bestModel
    """

    numFolds = Param("numFolds", "number of folds", int)

    def __init__(
        self,
        uid: str | None = None,
        estimator: Estimator | None = None,
        estimatorParamMaps: list[dict] | None = None,
        evaluator: Evaluator | None = None,
        numFolds: int = 3,
        seed: int = 0,
        collectSubModels: bool = False,
    ):
        super().__init__(uid)
        self._estimator = estimator
        self._maps = estimatorParamMaps or []
        self._evaluator = evaluator
        self._collect = collectSubModels
        self._setDefault(numFolds=3, seed=0)
        self._set(numFolds=numFolds, seed=seed)

    def fit(self, dataset: Any) -> "CrossValidatorModel":
        k = self.getOrDefault("numFolds")
        if k < 2:
            raise ValueError("numFolds must be >= 2")
        if _is_spark_df(dataset):
            # Spark-style fold assignment: one randomSplit plans k disjoint
            # row subsets; each fold's train set is the union of the others.
            # No row ever leaves the cluster for the split itself.
            from functools import reduce

            splits = dataset.randomSplit(
                [1.0 / k] * k, seed=self.getOrDefault("seed")
            )
            if any(sp.first() is None for sp in splits):
                raise ValueError(
                    f"randomSplit produced an empty fold (numFolds={k}); "
                    "the dataset is too small for this many folds"
                )
        else:
            dataset = _collect_for_split(dataset)
            rng = np.random.default_rng(self.getOrDefault("seed"))
            idx = rng.permutation(n_rows(dataset))
            folds = np.array_split(idx, k)
            splits = None
        candidates = self._candidates()
        metrics = np.zeros((len(candidates), k))
        sub_models = [] if self._collect else None
        for f in range(k):
            if splits is not None:
                val = splits[f]
                train = reduce(
                    lambda a, b: a.union(b),
                    [splits[i] for i in range(k) if i != f],
                )
                # cache the fold: iterative candidates (Newton/Lloyd) run
                # many jobs over train, and each would otherwise re-execute
                # the randomSplit filters against the source
                if hasattr(train, "cache"):
                    train = train.cache()
                if hasattr(val, "cache"):
                    val = val.cache()
            else:
                val_idx = folds[f]
                train_idx = np.concatenate(
                    [folds[i] for i in range(k) if i != f]
                )
                train = row_slice(dataset, train_idx)
                val = row_slice(dataset, val_idx)
            try:
                fold_models = []
                for c, params in enumerate(candidates):
                    model, metric = _fit_and_eval(
                        self._estimator, params, self._evaluator, train, val
                    )
                    metrics[c, f] = metric
                    fold_models.append(model)
                if sub_models is not None:
                    sub_models.append(fold_models)
            finally:
                if splits is not None:
                    for df_ in (train, val):
                        if hasattr(df_, "unpersist"):
                            df_.unpersist()
        avg = metrics.mean(axis=1)
        best_idx = int(np.argmax(avg) if self._evaluator.isLargerBetter() else np.argmin(avg))
        best_est = self._estimator.copy()
        if candidates[best_idx]:
            best_est._set(**candidates[best_idx])
        best_model = best_est.fit(dataset)
        return CrossValidatorModel(
            uid=self.uid,
            bestModel=best_model,
            avgMetrics=list(avg),
            bestIndex=best_idx,
            subModels=sub_models,
        )


class CrossValidatorModel(Model):
    def __init__(
        self,
        uid: str | None = None,
        bestModel: Model | None = None,
        avgMetrics: list[float] | None = None,
        bestIndex: int = 0,
        subModels=None,
    ):
        super().__init__(uid)
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics or []
        self.bestIndex = bestIndex
        self.subModels = subModels

    def transform(self, dataset: Any) -> Any:
        return self.bestModel.transform(dataset)


class TrainValidationSplit(_ValidatorParams, Estimator):
    """Single train/validation split over a param grid (cheaper than CV)."""

    trainRatio = Param("trainRatio", "fraction of rows used for training", float)

    def __init__(
        self,
        uid: str | None = None,
        estimator: Estimator | None = None,
        estimatorParamMaps: list[dict] | None = None,
        evaluator: Evaluator | None = None,
        trainRatio: float = 0.75,
        seed: int = 0,
    ):
        super().__init__(uid)
        self._estimator = estimator
        self._maps = estimatorParamMaps or []
        self._evaluator = evaluator
        self._setDefault(trainRatio=0.75, seed=0)
        self._set(trainRatio=trainRatio, seed=seed)

    def fit(self, dataset: Any) -> "TrainValidationSplitModel":
        ratio = self.getOrDefault("trainRatio")
        if not 0.0 < ratio < 1.0:
            raise ValueError("trainRatio must be in (0, 1)")
        if _is_spark_df(dataset):
            train, val = dataset.randomSplit(
                [ratio, 1.0 - ratio], seed=self.getOrDefault("seed")
            )
            if train.first() is None or val.first() is None:
                raise ValueError(
                    "split produced an empty train or validation set"
                )
            if hasattr(train, "cache"):
                train, val = train.cache(), val.cache()
        else:
            dataset = _collect_for_split(dataset)
            rng = np.random.default_rng(self.getOrDefault("seed"))
            idx = rng.permutation(n_rows(dataset))
            cut = int(len(idx) * ratio)
            if cut == 0 or cut == len(idx):
                raise ValueError(
                    "split produced an empty train or validation set"
                )
            train = row_slice(dataset, idx[:cut])
            val = row_slice(dataset, idx[cut:])
        candidates = self._candidates()
        metrics = []
        for params in candidates:
            _, metric = _fit_and_eval(
                self._estimator, params, self._evaluator, train, val
            )
            metrics.append(metric)
        arr = np.asarray(metrics)
        best_idx = int(np.argmax(arr) if self._evaluator.isLargerBetter() else np.argmin(arr))
        best_est = self._estimator.copy()
        if candidates[best_idx]:
            best_est._set(**candidates[best_idx])
        best_model = best_est.fit(dataset)
        return TrainValidationSplitModel(
            uid=self.uid,
            bestModel=best_model,
            validationMetrics=metrics,
            bestIndex=best_idx,
        )


class TrainValidationSplitModel(Model):
    def __init__(
        self,
        uid: str | None = None,
        bestModel: Model | None = None,
        validationMetrics: list[float] | None = None,
        bestIndex: int = 0,
    ):
        super().__init__(uid)
        self.bestModel = bestModel
        self.validationMetrics = validationMetrics or []
        self.bestIndex = bestIndex

    def transform(self, dataset: Any) -> Any:
        return self.bestModel.transform(dataset)
