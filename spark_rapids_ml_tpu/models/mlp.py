"""MultilayerPerceptronClassifier — pyspark.ml's feed-forward network,
TPU-native.

This is the one pyspark.ml estimator that IS a neural network, and the
most natural fit in the package for the MXU: every layer is a matmul.
Spark's architecture is mirrored exactly — sigmoid hidden layers, softmax
output, cross-entropy loss, the ``layers`` param specifying
[inputs, hidden..., classes] — and training follows Spark's solver menu:
``l-bfgs`` (default; optax's jaxopt-derived L-BFGS) or ``gd`` with
``stepSize``. The entire optimization runs as ONE XLA program: a
``lax.while_loop`` whose body is value_and_grad of the full-batch loss
plus the optimizer update — no host round-trips in training.

The fitted model exposes Spark's ``weights`` (one flat vector, layer
matrices then biases in layer order) so a coefficients-level comparison
with a pyspark model is possible.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    Param,
)
from spark_rapids_ml_tpu.ops.linalg import DEFAULT_PRECISION
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

_SOLVERS = ("l-bfgs", "gd")

#: module-level jit so transform/predict hit the compilation cache (the
#: repo convention — a fresh jax.jit per call would retrace every time)
_forward_jit = None  # created lazily below to keep import cheap


def _forward_cached(flat, x, layers):
    global _forward_jit
    if _forward_jit is None:
        # built once behind the None guard — a hand-rolled module cache
        # tpulint: disable=TPL003
        _forward_jit = jax.jit(_forward, static_argnames=("layers",))
    return _forward_jit(flat, x, layers=layers)


def _unflatten(flat: jnp.ndarray, layers: tuple):
    """Spark's weight layout: per layer, the [in, out] matrix then the
    [out] bias, concatenated flat."""
    params = []
    at = 0
    for fan_in, fan_out in zip(layers[:-1], layers[1:]):
        w = flat[at : at + fan_in * fan_out].reshape(fan_in, fan_out)
        at += fan_in * fan_out
        b = flat[at : at + fan_out]
        at += fan_out
        params.append((w, b))
    return params


def _forward(flat, x, layers: tuple, *, precision=DEFAULT_PRECISION):
    """Logits of Spark's topology: sigmoid hidden layers, affine output
    (softmax applied by the loss / probability consumers)."""
    h = x
    params = _unflatten(flat, layers)
    for i, (w, b) in enumerate(params):
        h = jnp.matmul(h, w, precision=precision) + b
        if i < len(params) - 1:
            h = jax.nn.sigmoid(h)
    return h


@partial(
    jax.jit,
    static_argnames=("layers", "solver", "max_iter"),
)
def train_mlp(
    flat0: jnp.ndarray,
    x: jnp.ndarray,
    y: jnp.ndarray,  # [rows] class indices (float ok)
    w: jnp.ndarray,  # [rows] weights; 0 = pad
    *,
    layers: tuple,
    solver: str,
    max_iter: int,
    step_size: float = 0.03,
    tol: float = 1e-6,
):
    """Full-batch training as one XLA program; returns (weights, loss,
    iterations)."""
    import optax

    y_idx = y.astype(jnp.int32)
    w_sum = jnp.maximum(jnp.sum(w), 1.0)

    def loss_fn(flat):
        logits = _forward(flat, x, layers)
        ll = optax.softmax_cross_entropy_with_integer_labels(logits, y_idx)
        return jnp.sum(ll * w) / w_sum

    def cond(carry):
        _, _, it, prev, cur = carry
        # first test runs unconditionally (prev=inf, cur finite → inf>tol)
        return (it < max_iter) & (jnp.abs(prev - cur) > tol)

    if solver == "l-bfgs":
        opt = optax.lbfgs()
        value_and_grad = optax.value_and_grad_from_state(loss_fn)

        def body(carry):
            flat, state, it, _, cur = carry
            value, grad = value_and_grad(flat, state=state)
            updates, state = opt.update(
                grad, state, flat, value=value, grad=grad, value_fn=loss_fn
            )
            flat = optax.apply_updates(flat, updates)
            # convergence compares loss(new) vs loss(old): an extra
            # forward per iteration, the price of a correct stop test
            return flat, state, it + 1, value, loss_fn(flat)

    else:
        opt = optax.sgd(step_size)

        def body(carry):
            flat, state, it, _, cur = carry
            value, grad = jax.value_and_grad(loss_fn)(flat)
            updates, state = opt.update(grad, state, flat)
            flat = optax.apply_updates(flat, updates)
            return flat, state, it + 1, value, loss_fn(flat)

    state0 = opt.init(flat0)
    inf = jnp.asarray(jnp.inf, flat0.dtype)
    flat, _, it, _, loss = jax.lax.while_loop(
        cond, body, (flat0, state0, jnp.int32(0), inf, loss_fn(flat0))
    )
    return flat, loss, it


class _MLPParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    layers = Param(
        "layers",
        "layer sizes [inputs, hidden..., classes] (the Spark spec)",
        list,
    )
    maxIter = Param("maxIter", "maximum optimizer iterations", int)
    tol = Param("tol", "convergence tolerance on the loss decrease", float)
    stepSize = Param("stepSize", "learning rate for solver='gd'", float)
    solver = Param("solver", "'l-bfgs' (default) or 'gd'", str)
    seed = Param("seed", "weight-initialization seed", int)
    probabilityCol = Param("probabilityCol", "class-probability column", str)
    rawPredictionCol = Param("rawPredictionCol", "logits column", str)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            featuresCol="features", labelCol="label",
            predictionCol="prediction", probabilityCol="probability",
            rawPredictionCol="rawPrediction",
            maxIter=100, tol=1e-6, stepSize=0.03, solver="l-bfgs", seed=0,
        )

    def getLayers(self) -> list:
        return self.getOrDefault("layers")

    def getMaxIter(self) -> int:
        return self.getOrDefault("maxIter")


class MultilayerPerceptronClassifier(_MLPParams, Estimator):
    def setLayers(self, value) -> "MultilayerPerceptronClassifier":
        value = [int(v) for v in value]
        if len(value) < 2 or any(v < 1 for v in value):
            raise ValueError(
                f"layers needs >= 2 positive sizes [in, ..., out], got {value}"
            )
        return self._set(layers=value)

    def setMaxIter(self, value: int):
        return self._set(maxIter=value)

    def setTol(self, value: float):
        return self._set(tol=float(value))

    def setStepSize(self, value: float):
        if value <= 0:
            raise ValueError(f"stepSize must be > 0, got {value}")
        return self._set(stepSize=float(value))

    def setSolver(self, value: str):
        if value not in _SOLVERS:
            raise ValueError(f"solver must be one of {_SOLVERS}, got {value!r}")
        return self._set(solver=value)

    def setSeed(self, value: int):
        return self._set(seed=value)

    def fit(self, dataset: Any, num_partitions: int | None = None):
        """``num_partitions`` is accepted for Estimator-signature
        uniformity; training is one full-batch XLA program either way.
        Instance weights ((X, y, w) tuples) weight the loss — an extension
        over pyspark's MLP, which has no weightCol."""
        if "layers" not in self._paramMap:
            raise ValueError("setLayers([...]) before fit (the Spark spec)")
        layers = tuple(self.getLayers())
        parts = columnar.labeled_partitions(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("labelCol"),
            None,
            weight_col=None,
        )
        x = np.concatenate([p[0] for p in parts])
        y = np.concatenate([p[1] for p in parts])
        w = (
            np.concatenate([p[2] for p in parts])
            if parts[0][2] is not None
            else None
        )
        if x.shape[1] != layers[0]:
            raise ValueError(
                f"layers[0]={layers[0]} but the data has {x.shape[1]} features"
            )
        classes = np.unique(y)
        if not np.all(classes == np.round(classes)) or classes.min() < 0:
            raise ValueError(
                f"labels must be integers 0..C-1, got {classes[:8]}"
            )
        if int(classes.max()) + 1 > layers[-1]:
            raise ValueError(
                f"labels imply {int(classes.max()) + 1} classes but "
                f"layers[-1]={layers[-1]}"
            )
        padded, yv, wv, _ = columnar.pad_labeled_batch(x, y, w)
        fdt = jax.dtypes.canonicalize_dtype(padded.dtype)

        # Glorot-uniform init, deterministic by seed
        key = jax.random.PRNGKey(self.getOrDefault("seed"))
        pieces = []
        for fan_in, fan_out in zip(layers[:-1], layers[1:]):
            key, k1 = jax.random.split(key)
            limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
            pieces.append(
                jax.random.uniform(
                    k1, (fan_in * fan_out,), fdt, -limit, limit
                )
            )
            pieces.append(jnp.zeros((fan_out,), fdt))
        flat0 = jnp.concatenate(pieces)

        with trace_range("mlp train"):
            flat, loss, it = train_mlp(
                flat0,
                jnp.asarray(padded),
                jnp.asarray(yv),
                jnp.asarray(wv),
                layers=layers,
                solver=self.getOrDefault("solver"),
                max_iter=self.getMaxIter(),
                step_size=self.getOrDefault("stepSize"),
                tol=self.getOrDefault("tol"),
            )
        weights = np.asarray(flat)
        if not np.isfinite(weights).all():
            raise ValueError(
                "MLP training diverged to non-finite weights; lower "
                "stepSize or check the data for NaN/Inf"
            )
        model = MultilayerPerceptronClassificationModel(
            uid=self.uid, weights=weights,
            trainLoss=float(loss), iterations=int(it),
        )
        return self._copyValues(model)


class MultilayerPerceptronClassificationModel(_MLPParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        weights: np.ndarray | None = None,
        trainLoss: float = float("nan"),
        iterations: int = 0,
    ):
        super().__init__(uid)
        self.weights = None if weights is None else np.asarray(weights)
        self.trainLoss = float(trainLoss)
        self.iterations = int(iterations)

    @property
    def numClasses(self) -> int:
        return int(self.getLayers()[-1])

    def _logits(self, mat: np.ndarray) -> np.ndarray:
        layers = tuple(self.getLayers())
        fdt = columnar.float_dtype_for(mat.dtype)
        padded, true_rows = columnar.pad_rows(mat.astype(fdt, copy=False))
        out = _forward_cached(
            jnp.asarray(self.weights.astype(fdt)),
            jnp.asarray(padded),
            layers,
        )
        return np.asarray(out)[:true_rows]

    @staticmethod
    def _from_logits(logits: np.ndarray):
        """THE softmax/argmax decision rule, in one place."""
        shifted = logits - logits.max(axis=1, keepdims=True)
        e = np.exp(shifted)
        proba = e / e.sum(axis=1, keepdims=True)
        return proba, np.argmax(logits, axis=1).astype(np.float64)

    def proba_and_predictions(self, mat: np.ndarray):
        return self._from_logits(self._logits(mat))

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        # prediction needs only the argmax — no softmax work
        return np.argmax(self._logits(mat), axis=1).astype(np.float64)

    def transform(self, dataset: Any) -> Any:
        if columnar.has_named_columns(dataset):
            mat = columnar.extract_matrix(
                dataset, self.getOrDefault("featuresCol")
            )
            logits = self._logits(mat)
            proba, preds = self._from_logits(logits)
            return columnar.append_columns(
                dataset,
                [
                    (self.getOrDefault("rawPredictionCol"), logits),
                    (self.getOrDefault("probabilityCol"), proba),
                    (self.getOrDefault("predictionCol"), preds),
                ],
            )
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )

    def predict(self, row) -> float:
        return float(
            self._predict_matrix(np.asarray(row, dtype=np.float64)[None, :])[0]
        )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "weights": self.weights,
            "meta": np.asarray([self.trainLoss, float(self.iterations)]),
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            weights=data["weights"],
            trainLoss=float(data["meta"][0]),
            iterations=int(data["meta"][1]),
        )
