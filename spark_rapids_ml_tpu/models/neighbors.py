"""Exact NearestNeighbors estimator/model — the spark-rapids-ml k-NN family.

The modern spark-rapids-ml package exposes a brute-force exact
``NearestNeighbors`` (fit on an item DataFrame, then ``kneighbors`` a query
DataFrame → per-query index/distance arrays) built on RAFT's GPU
pairwise-distance + k-selection kernels. The 22.12 reference this framework
re-designs stops at PCA (SURVEY.md §2), so this is a capability-add in the
same spirit as KMeans: identical API shape, TPU-native internals
(ops/neighbors.py blocked MXU tournament; parallel/neighbors.py for the
mesh-sharded corpus).

Metrics follow the cuML/RAFT brute-force surface:

- ``euclidean`` (default) — √‖x−y‖², ascending;
- ``sqeuclidean`` — ‖x−y‖², ascending;
- ``cosine`` — 1 − cos(x, y), ascending over [0, 2] (rows L2-normalized,
  ranked by the dot-product kernel so zero rows sit at exactly 1 from
  everything — the cuML behavior);
- ``inner_product`` — the raw dot product, DESCENDING (a similarity: the
  k returned items maximize x·y, and the "distances" array holds the dot
  products themselves — cuML's convention).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import HasInputCol, Param
from spark_rapids_ml_tpu.ops import neighbors as NN
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

_METRICS = ("euclidean", "sqeuclidean", "cosine", "inner_product")

#: queries are processed in fixed-size padded chunks so the jitted kernel
#: compiles once per (chunk, corpus-bucket) shape pair, not per call.
_QUERY_CHUNK = 4096


def _kernel_metric(metric: str) -> str:
    # cosine rides the dot kernel on normalized rows: ranking by largest
    # q̂·ĉ IS ranking by smallest 1 − cos, and a zero row (normalized to
    # zero) scores dot 0 → distance exactly 1 from everything
    return "dot" if metric in ("inner_product", "cosine") else "sqeuclidean"


def _prepare_rows(x: np.ndarray, metric: str) -> np.ndarray:
    """Metric-specific row preparation: cosine L2-normalizes (zero rows stay
    zero — they land at distance 1 from everything, the cuML behavior)."""
    if metric != "cosine":
        return x
    norms = np.linalg.norm(x, axis=1, keepdims=True)
    return x / np.where(norms > 0, norms, 1.0)


def _finalize_distances(scores: np.ndarray, metric: str) -> np.ndarray:
    """Kernel scores (descending-is-better) → user-facing distance arrays."""
    if metric == "inner_product":
        return scores  # dot products, already descending
    if metric == "cosine":
        return np.clip(1.0 - scores, 0.0, 2.0)
    sq = np.clip(-scores, 0.0, None)
    if metric == "sqeuclidean":
        return sq
    return np.sqrt(sq)


class _NearestNeighborsParams(HasInputCol):
    k = Param("k", "number of neighbors to return per query", int)
    metric = Param(
        "metric",
        "distance metric: 'euclidean' (default), 'sqeuclidean', 'cosine', "
        "or 'inner_product' (similarity — descending)",
        str,
    )
    idCol = Param(
        "idCol",
        "optional item-id column; when unset, neighbors are identified by "
        "their 0-based row position in the fitted dataset. Ids travel "
        "through a float64 extractor, so integral ids are exact only up "
        "to 2^53",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(k=5, metric="euclidean")

    def getK(self) -> int:
        return self.getOrDefault("k")

    def getMetric(self) -> str:
        return self.getOrDefault("metric")


def _extract_items_and_ids(dataset, ds, id_col, k):
    """THE fit-side ingestion both k-NN estimators share: concatenated item
    matrix + aligned ids (positional when ``id_col`` is None; integral ids
    cast back to int64 after the float64 extractor — exact up to 2^53),
    with the k-vs-items and ids-vs-items validations in one place."""
    items = np.concatenate(list(ds.matrices()), axis=0)
    if items.shape[0] < k:
        raise ValueError(
            f"k={k} exceeds the fitted item count {items.shape[0]}"
        )
    if id_col is not None:
        # a list of columnar partitions (the from_any list branch) has
        # its id column extracted per partition, in partition order
        if isinstance(dataset, (list, tuple)) and not isinstance(
            dataset, np.ndarray
        ):
            ids = np.concatenate(
                [columnar.extract_vector(p, id_col) for p in dataset]
            )
        else:
            ids = columnar.extract_vector(dataset, id_col)
        if ids.shape[0] != items.shape[0]:
            raise ValueError(
                f"idCol {id_col!r} has {ids.shape[0]} values for "
                f"{items.shape[0]} items"
            )
        if np.all(ids == np.round(ids)):  # integral ids stay integral
            ids = ids.astype(np.int64)
    else:
        ids = np.arange(items.shape[0], dtype=np.int64)
    return items, ids


class NearestNeighbors(_NearestNeighborsParams, Estimator):
    """Brute-force exact k-NN over a fitted item set."""

    def setK(self, value: int) -> "NearestNeighbors":
        if value < 1:
            raise ValueError(f"k must be >= 1, got {value}")
        return self._set(k=value)

    def setMetric(self, value: str) -> "NearestNeighbors":
        if value not in _METRICS:
            raise ValueError(f"metric must be one of {_METRICS}, got {value!r}")
        return self._set(metric=value)

    def setIdCol(self, value: str) -> "NearestNeighbors":
        return self._set(idCol=value)

    def fit(
        self, dataset: Any, num_partitions: int | None = None
    ) -> "NearestNeighborsModel":
        """Materialize the item set (and ids) into the model — brute-force
        k-NN has no training phase; ``fit`` is ingestion, exactly as in
        spark-rapids-ml's NearestNeighbors."""
        input_col = self._paramMap.get("inputCol")
        ds = columnar.PartitionedDataset.from_any(
            dataset, input_col, num_partitions
        )
        items, ids = _extract_items_and_ids(
            dataset, ds, self._paramMap.get("idCol"), self.getK()
        )
        model = NearestNeighborsModel(uid=self.uid, items=items, itemIds=ids)
        return self._copyValues(model)


class NearestNeighborsModel(_NearestNeighborsParams, Model):
    """Holds the item matrix; ``kneighbors`` streams query chunks through
    the blocked tournament kernel."""

    def __init__(
        self,
        uid: str | None = None,
        items: np.ndarray | None = None,
        itemIds: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.items = None if items is None else np.asarray(items)
        self.itemIds = None if itemIds is None else np.asarray(itemIds)

    def kneighbors(
        self, dataset: Any, k: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """(distances [q, k], item ids [q, k]) for every query row.

        Distances are ordered best-first per the metric (ascending for the
        distance metrics, descending dot products for ``inner_product``).
        """
        queries = columnar.extract_matrix(
            dataset, self._paramMap.get("inputCol")
        )
        return self._kneighbors_matrix(queries, k)

    def _kneighbors_matrix(
        self, queries: np.ndarray, k: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """The matrix→(distances, ids) body — shared by the local path and
        the Spark wrapper's per-batch executor transform."""
        k = self.getK() if k is None else k
        if not 1 <= k <= self.items.shape[0]:
            raise ValueError(
                f"k={k} must be in [1, {self.items.shape[0]}] "
                "(the fitted item count)"
            )
        metric = self.getMetric()
        if queries.shape[1] != self.items.shape[1]:
            raise ValueError(
                f"queries have {queries.shape[1]} features but the fitted "
                f"items have {self.items.shape[1]}"
            )
        fdt = columnar.float_dtype_for(queries.dtype)
        corpus = _prepare_rows(self.items.astype(fdt, copy=False), metric)
        queries = _prepare_rows(queries.astype(fdt, copy=False), metric)

        # corpus padded once to a shape bucket (valid mask kills pad rows);
        # queries stream through in fixed chunks so the kernel compiles for
        # at most two query shapes (full chunk + final remainder bucket)
        padded_corpus, true_rows = columnar.pad_rows(corpus)
        valid = np.zeros(padded_corpus.shape[0], dtype=bool)
        valid[:true_rows] = True
        cd = jnp.asarray(padded_corpus)
        vd = jnp.asarray(valid)

        out_scores = np.empty((queries.shape[0], k), dtype=fdt)
        out_idx = np.empty((queries.shape[0], k), dtype=np.int32)
        with trace_range("knn kneighbors"):
            for lo in range(0, queries.shape[0], _QUERY_CHUNK):
                chunk = queries[lo : lo + _QUERY_CHUNK]
                qpad, q_rows = columnar.pad_rows(chunk)
                scores, idx = NN.knn_topk(
                    jnp.asarray(qpad),
                    cd,
                    vd,
                    k,
                    metric=_kernel_metric(metric),
                )
                out_scores[lo : lo + q_rows] = np.asarray(scores)[:q_rows]
                out_idx[lo : lo + q_rows] = np.asarray(idx)[:q_rows]

        dists = _finalize_distances(out_scores, metric)
        return dists, self.itemIds[out_idx]

    def transform(self, dataset: Any) -> Any:
        """Append ``indices`` and ``distances`` array columns — the
        DataFrame spelling of ``kneighbors`` (spark-rapids-ml's knn_df)."""
        dists, ids = self.kneighbors(dataset)
        return columnar.append_columns(
            dataset, [("indices", ids), ("distances", dists)]
        )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"items": self.items, "itemIds": self.itemIds}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, items=data["items"], itemIds=data["itemIds"])


# ---------------------------------------------------------------------------
# Approximate nearest neighbors (IVF-Flat)
# ---------------------------------------------------------------------------

_ANN_METRICS = ("euclidean", "sqeuclidean", "cosine")


class _ANNParams(_NearestNeighborsParams):
    nlist = Param(
        "nlist",
        "IVF cluster count (0 = auto: ~sqrt(items), the cuML heuristic)",
        int,
    )
    nprobe = Param("nprobe", "clusters scanned per query", int)
    maxIter = Param("maxIter", "Lloyd iterations for the coarse quantizer", int)
    seed = Param("seed", "random seed for the coarse quantizer", int)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(nlist=0, nprobe=20, maxIter=10, seed=0)

    def getNlist(self) -> int:
        return self.getOrDefault("nlist")

    def getNprobe(self) -> int:
        return self.getOrDefault("nprobe")


class ApproximateNearestNeighbors(_ANNParams, Estimator):
    """IVF-Flat approximate k-NN — spark-rapids-ml's
    ``ApproximateNearestNeighbors(algorithm='ivfflat')``: the corpus is
    clustered by this package's KMeans and queries scan only the
    ``nprobe`` nearest clusters (ops/ivf.py; the module docstring has the
    honest TPU brute-force-vs-IVF trade). ``nprobe == nlist`` degenerates
    to exact search (tested bit-for-bit against NearestNeighbors)."""

    def setK(self, value: int) -> "ApproximateNearestNeighbors":
        if value < 1:
            raise ValueError(f"k must be >= 1, got {value}")
        return self._set(k=value)

    def setMetric(self, value: str) -> "ApproximateNearestNeighbors":
        if value not in _ANN_METRICS:
            raise ValueError(
                f"metric must be one of {_ANN_METRICS}, got {value!r}"
            )
        return self._set(metric=value)

    def setIdCol(self, value: str) -> "ApproximateNearestNeighbors":
        return self._set(idCol=value)

    def setNlist(self, value: int) -> "ApproximateNearestNeighbors":
        if value < 0:
            raise ValueError(f"nlist must be >= 0, got {value}")
        return self._set(nlist=value)

    def setNprobe(self, value: int) -> "ApproximateNearestNeighbors":
        if value < 1:
            raise ValueError(f"nprobe must be >= 1, got {value}")
        return self._set(nprobe=value)

    def setMaxIter(self, value: int) -> "ApproximateNearestNeighbors":
        return self._set(maxIter=value)

    def setSeed(self, value: int) -> "ApproximateNearestNeighbors":
        return self._set(seed=value)

    def fit(
        self, dataset: Any, num_partitions: int | None = None
    ) -> "ApproximateNearestNeighborsModel":
        input_col = self._paramMap.get("inputCol")
        ds = columnar.PartitionedDataset.from_any(
            dataset, input_col, num_partitions
        )
        items, ids = _extract_items_and_ids(
            dataset, ds, self._paramMap.get("idCol"), self.getK()
        )
        return self._fit_items(items, ids)

    def _fit_items(
        self, items: np.ndarray, ids: np.ndarray
    ) -> "ApproximateNearestNeighborsModel":
        """The index build from pre-extracted arrays — shared with the
        Spark wrapper, whose collection path produces (items, ids)
        directly."""
        from spark_rapids_ml_tpu.models.kmeans import KMeans
        from spark_rapids_ml_tpu.ops import ivf as IVF
        from spark_rapids_ml_tpu.ops import kmeans as KM

        metric = self.getMetric()
        fdt = columnar.float_dtype_for(items.dtype)
        prepared = _prepare_rows(items.astype(fdt, copy=False), metric)
        nlist = self.getNlist() or max(
            1, min(items.shape[0], int(np.sqrt(items.shape[0])))
        )
        nlist = min(nlist, items.shape[0])
        with trace_range("ivf build"):
            km = (
                KMeans(uid=f"{self.uid}-quantizer")
                .setK(nlist)
                .setMaxIter(self.getOrDefault("maxIter"))
                .setSeed(self.getOrDefault("seed"))
            )
            kmodel = km.fit(prepared)
            centroids = kmodel.clusterCenters.astype(fdt)
            labels, _ = KM.assign_clusters(
                jnp.asarray(prepared), jnp.asarray(centroids)
            )
            packed = IVF.build_ivf_buckets(
                prepared, np.asarray(labels), nlist
            )
        model = ApproximateNearestNeighborsModel(
            uid=self.uid,
            centroids=centroids,
            bucketItems=packed.bucket_items,
            bucketIds=packed.bucket_ids,
            itemIds=ids,
            spillItems=packed.spill_items,
            spillIds=packed.spill_ids,
        )
        return self._copyValues(model)


class ApproximateNearestNeighborsModel(_ANNParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        centroids: np.ndarray | None = None,
        bucketItems: np.ndarray | None = None,
        bucketIds: np.ndarray | None = None,
        itemIds: np.ndarray | None = None,
        spillItems: np.ndarray | None = None,
        spillIds: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.centroids = None if centroids is None else np.asarray(centroids)
        self.bucketItems = (
            None if bucketItems is None else np.asarray(bucketItems)
        )
        self.bucketIds = None if bucketIds is None else np.asarray(bucketIds)
        self.itemIds = None if itemIds is None else np.asarray(itemIds)
        # pre-spill saves / direct construction: an empty spill list is the
        # exact equivalent of the old pad-to-largest-cluster packing
        if spillItems is None and self.bucketItems is not None:
            spillItems = np.zeros(
                (0, self.bucketItems.shape[2]), dtype=self.bucketItems.dtype
            )
            spillIds = np.full(0, -1, dtype=np.int32)
        self.spillItems = (
            None if spillItems is None else np.asarray(spillItems)
        )
        self.spillIds = None if spillIds is None else np.asarray(spillIds)

    @property
    def numItems(self) -> int:
        return self.itemIds.shape[0]

    def kneighbors(
        self, dataset: Any, k: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        queries = columnar.extract_matrix(
            dataset, self._paramMap.get("inputCol")
        )
        return self._kneighbors_matrix(queries, k)

    def _kneighbors_matrix(self, queries, k=None):
        from spark_rapids_ml_tpu.ops import ivf as IVF

        k = self.getK() if k is None else k
        if not 1 <= k <= self.numItems:
            raise ValueError(
                f"k={k} must be in [1, {self.numItems}] (the fitted item count)"
            )
        metric = self.getMetric()
        if queries.shape[1] != self.centroids.shape[1]:
            raise ValueError(
                f"queries have {queries.shape[1]} features but the fitted "
                f"items have {self.centroids.shape[1]}"
            )
        fdt = self.bucketItems.dtype
        queries = _prepare_rows(queries.astype(fdt, copy=False), metric)
        cd = jnp.asarray(self.centroids)
        bi = jnp.asarray(self.bucketItems)
        bd = jnp.asarray(self.bucketIds)
        si = sd = None
        if self.spillItems is not None and self.spillItems.shape[0] > 0:
            si = jnp.asarray(self.spillItems)
            sd = jnp.asarray(self.spillIds)
        nprobe = self.getNprobe()

        out_scores = np.empty((queries.shape[0], k), dtype=fdt)
        out_idx = np.empty((queries.shape[0], k), dtype=np.int32)
        with trace_range("ivf kneighbors"):
            for lo in range(0, queries.shape[0], _QUERY_CHUNK):
                chunk = queries[lo : lo + _QUERY_CHUNK]
                qpad, q_rows = columnar.pad_rows(chunk)
                scores, idx = IVF.ivf_search(
                    jnp.asarray(qpad), cd, bi, bd, k, nprobe,
                    spill_items=si, spill_ids=sd,
                )
                out_scores[lo : lo + q_rows] = np.asarray(scores)[:q_rows]
                out_idx[lo : lo + q_rows] = np.asarray(idx)[:q_rows]

        # cosine rides normalized sqeuclidean here: 1 − cos = ‖x̂−ŷ‖²/2
        # over [0, 2] (anti-parallel → 2). Caveat vs the exact model's
        # dot-kernel cosine: an all-zero row lands at 0.5, not 1 — the IVF
        # coarse quantizer needs one metric for centroids and members, and
        # zero vectors have no direction to quantize. Unfilled slots
        # (id −1, score −inf) must stay inf, never clip to a legal 2.0.
        if metric == "cosine":
            sq = np.clip(-out_scores, 0.0, None)
            dists = np.where(
                np.isfinite(sq), np.clip(sq / 2.0, 0.0, 2.0), np.inf
            )
        else:
            dists = _finalize_distances(out_scores, metric)
        safe_idx = np.clip(out_idx, 0, None)
        ids = np.where(out_idx >= 0, self.itemIds[safe_idx], -1)
        return dists, ids

    def transform(self, dataset: Any) -> Any:
        dists, ids = self.kneighbors(dataset)
        return columnar.append_columns(
            dataset, [("indices", ids), ("distances", dists)]
        )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "centroids": self.centroids,
            "bucketItems": self.bucketItems,
            "bucketIds": self.bucketIds,
            "itemIds": self.itemIds,
            "spillItems": self.spillItems,
            "spillIds": self.spillIds,
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        spill_ids = data.get("spillIds")
        return cls(
            uid=uid,
            centroids=data["centroids"],
            bucketItems=data["bucketItems"],
            bucketIds=data["bucketIds"].astype(np.int32),
            itemIds=data["itemIds"],
            spillItems=data.get("spillItems"),
            spillIds=None if spill_ids is None else spill_ids.astype(np.int32),
        )
