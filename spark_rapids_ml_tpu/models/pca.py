"""PCA estimator and model — the reference's flagship capability, TPU-native.

API parity targets (SURVEY.md §1 L5/L6):
- ``com.nvidia.spark.ml.feature.PCA`` drop-in surface (PCA.scala:27-37):
  ``setInputCol`` (an **ArrayType** column, not a Vector — README.md:35-37),
  ``setOutputCol``, ``setK``, ``fit``, companion ``load``.
- ``RapidsPCA``/``RapidsPCAModel`` behavior (RapidsPCA.scala:52-185):
  ``meanCentering`` param, dual-path transform (accelerated columnar +
  CPU row fallback), params-JSON + parquet persistence.

Semantics preserved exactly (SURVEY.md §3.1 "numerical semantics"):
- the "covariance" is the scatter-form Gram (no 1/(n-1) scaling),
- components come out in descending eigenvalue order, sign-flipped so each
  column's max-|element| is positive,
- explainedVariance = sᵢ/Σs over the FULL singular-value spectrum (s = √λ),
  truncated to k — the reference's non-textbook definition.

One deliberate deviation, documented: the reference *accepts* meanCentering
but never implements it (TODO stub, RapidsRowMatrix.scala:111-117) — its
observable behavior is always the uncentered Gram. Here the param works.
``meanCentering=False`` (the default, matching observable reference behavior)
reproduces the reference bit-for-bit semantics; ``True`` actually centers.

TPU-first architecture notes: each partition's Gram rides one large MXU
matmul on zero-padded power-of-two row buckets (static shapes ⇒ a handful of
XLA programs, compiled once); partials reduce as a ``GramStats`` monoid
(host tree-aggregate here; ``parallel`` owns the mesh/psum variant); the n×n
decomposition runs on device via the refined eigh (ops.linalg.refine_eigh).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import HasInputCol, HasOutputCol, Param
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import costmodel, trace_range

try:
    import pyarrow as pa
except Exception:  # pragma: no cover
    pa = None


class PCAParams(HasInputCol, HasOutputCol):
    """Shared params — the RapidsPCAParams analog (RapidsPCA.scala:34-45)."""

    k = Param("k", "number of principal components", int)
    meanCentering = Param(
        "meanCentering",
        "center the data before computing the covariance (the reference "
        "accepts this but computes the uncentered Gram regardless; False "
        "reproduces reference behavior exactly)",
        bool,
    )
    precision = Param(
        "precision",
        "MXU matmul precision for the Gram pass: 'highest' (6-pass bf16, "
        "default), 'high' (3-pass, ~1.7x faster, still clears the 0.9999 "
        "eigenvector cosine bar thanks to eigh refinement), or 'default' "
        "(1-pass bf16)",
        str,
    )
    standardize = Param(
        "standardize",
        "fuse StandardScaler into the fit (BASELINE config 4): the "
        "decomposition runs on the covariance of (x−μ)/σ, derived from the "
        "SAME one-pass GramStats — no separate scaling pass over the data — "
        "and transform standardizes before projecting (the model carries "
        "mean/std). Implies centering; sample (m−1) std like StandardScaler",
        bool,
    )
    solver = Param(
        "solver",
        "decomposition solver: 'full' (exact refined eigh, reference "
        "parity), 'randomized' (HMT subspace iteration, O(n²·(k+p)) — "
        "explainedVariance uses a trace-based tail estimate), 'svd' "
        "(direct TSQR→SVD(R): never forms XᵀX, works at cond(X) instead of "
        "cond(X)² — best for ill-conditioned data), or 'auto' (randomized "
        "when n ≥ 256 and k + oversample ≤ n/4)",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        from spark_rapids_ml_tpu.utils.config import get_config

        self._setDefault(
            meanCentering=False,
            standardize=False,
            outputCol="pca_features",
            precision=get_config().default_precision,
            solver="full",
        )

    def getK(self) -> int:
        return self.getOrDefault("k")

    def getMeanCentering(self) -> bool:
        return self.getOrDefault("meanCentering")


# Module-level jitted kernels: jax.jit caches per input shape, and row
# bucketing keeps the set of shapes small.
_gram_stats = jax.jit(L.gram_stats, static_argnames=("precision",))

_PRECISIONS = L.PRECISIONS


def _fit_from_stats(stats: L.GramStats, k: int, mean_centering: bool, solver: str):
    cov = L.covariance_from_stats(stats, mean_centering=mean_centering)
    return L.pca_fit_from_cov(cov, k, solver=solver)


_fit_from_stats_jit = jax.jit(_fit_from_stats, static_argnums=(1, 2, 3))
_project = jax.jit(L.project)
_qr_r = jax.jit(L.qr_r)
_combine_r = jax.jit(L.combine_r)
_svd_from_r_jit = jax.jit(L.svd_from_r, static_argnums=(1,))


class PCA(PCAParams, Estimator):
    """TPU-accelerated PCA with the reference's drop-in API.

    >>> model = PCA().setInputCol("features").setOutputCol("pca").setK(3).fit(df)
    >>> out = model.transform(df)
    """

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)

    def setK(self, value: int) -> "PCA":
        return self._set(k=value)

    def setMeanCentering(self, value: bool) -> "PCA":
        return self._set(meanCentering=value)

    def setStandardize(self, value: bool) -> "PCA":
        return self._set(standardize=value)

    def setPrecision(self, value: str) -> "PCA":
        if value not in _PRECISIONS:
            raise ValueError(f"precision must be one of {sorted(_PRECISIONS)}")
        return self._set(precision=value)

    def setSolver(self, value: str) -> "PCA":
        if value not in ("full", "randomized", "svd", "auto"):
            raise ValueError(
                "solver must be 'full', 'randomized', 'svd', or 'auto'"
            )
        return self._set(solver=value)

    def _reduce_r(self, mats, mean_centering: bool):
        """Reduction stage of the direct TSQR fit: per-partition R factors
        tree-reduced with QR-of-stacked-pair (``ops.linalg.combine_r`` — an
        associative semigroup, exactly like the GramStats monoid). Partitions
        ride the same power-of-two row bucketing as the Gram path (``qr_r``'s
        R is invariant under zero-row padding), so the shape set — and with
        it the number of XLA compiles — stays small. Centering needs the
        global mean first, so it costs one extra cheap pass (column sums
        only) over the partitions, applied *before* padding so pad rows stay
        zero."""
        from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks
        from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce

        mean = None
        if mean_centering:
            count = max(sum(m.shape[0] for m in mats), 1)
            col_sum = sum(m.sum(axis=0, dtype=np.float64) for m in mats)
            mean = col_sum / count

        def partition_task(mat):
            if mean is not None:
                mat = mat - mean.astype(mat.dtype)[None, :]
            padded, _ = columnar.pad_rows(mat)
            return _qr_r(jnp.asarray(padded))

        partials = run_partition_tasks(partition_task, mats)
        return tree_reduce(partials, _combine_r)

    def _stream_gram_stats(self, ds, k: int) -> tuple[L.GramStats, int]:
        """Out-of-core Gram accumulation: partitions drain lazily through
        ``spark.ingest.stream_fold`` into ONE donated device carry
        (ops.linalg.gram_fold_step) — the full [rows, n] set of matrices is
        never resident at once, host or device. The {1,0} pad mask makes
        ragged chunk tails exact (x·1 ≡ x bit-for-bit), so the streamed
        GramStats equal the resident reduction's."""
        from spark_rapids_ml_tpu.spark import ingest

        prec = _PRECISIONS[self.getOrDefault("precision")]
        it = ds.matrices()
        first = next(it)
        n_cols = first.shape[1]
        if k > n_cols:
            raise ValueError(f"k={k} must be <= number of features {n_cols}")

        def chunks():
            yield first
            yield from it

        res = ingest.stream_fold(
            chunks(),
            L.gram_fold_step(prec),
            n=n_cols,
            init=L.init_gram_carry(n_cols, ingest.wire_dtype()),
        )
        return res.carry, n_cols

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "PCAModel":
        """Two-phase fit, mirroring the reference call stack (SURVEY.md §3.1):
        per-partition device Gram accumulation + cross-partition reduce, then
        a single device decomposition. Covariance solvers go out-of-core
        above the ``TPU_ML_STREAM_FIT_MAX_RESIDENT_BYTES`` cutover: chunks
        fold through a donated device accumulator (``_stream_gram_stats``)
        at O(chunk + n²) memory instead of materializing every partition."""
        input_col = self._paramMap.get("inputCol") or self._defaultParamMap.get("inputCol")
        ds = columnar.PartitionedDataset.from_any(dataset, input_col, num_partitions)
        k = self.getK()
        mean_centering = self.getMeanCentering()

        with trace_range("compute cov"):  # NvtxRange analog, RapidsRowMatrix.scala:62
            solver = self.getOrDefault("solver")
            standardize = self.getOrDefault("standardize")
            if standardize and solver == "svd":
                raise ValueError(
                    "standardize=True derives the scaled covariance from "
                    "GramStats and so requires a covariance solver "
                    "('full'/'randomized'/'auto'); solver='svd' decomposes "
                    "R factors of the raw rows"
                )
            if solver != "svd" and columnar.use_streamed_fit(ds):
                stats, n_cols = self._stream_gram_stats(ds, k)
            else:
                mats = list(ds.matrices())
                n_cols = mats[0].shape[1]  # infer nCols like RapidsPCA.scala:74
                for m in mats[1:]:
                    if m.shape[1] != n_cols:
                        raise ValueError(
                            f"inconsistent feature dim: {m.shape[1]} != {n_cols}"
                        )

                if k > n_cols:
                    raise ValueError(
                        f"k={k} must be <= number of features {n_cols}"
                    )
                if solver == "svd":
                    r = self._reduce_r(mats, mean_centering)
                else:
                    prec = _PRECISIONS[self.getOrDefault("precision")]

                    def partition_task(mat):
                        padded, true_rows = columnar.pad_rows(mat)
                        xd = jnp.asarray(padded)
                        costmodel.capture(
                            "linalg.gram_stats", _gram_stats, xd,
                            precision=prec,
                        )
                        stats = _gram_stats(xd, precision=prec)
                        # padding adds zero rows: fix only the count
                        return L.GramStats(
                            stats.xtx,
                            stats.col_sum,
                            jnp.asarray(true_rows, stats.count.dtype),
                        )

                    from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks
                    from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce

                    partials = run_partition_tasks(partition_task, mats)
                    stats = tree_reduce(partials, L.combine_gram_stats)

        mean = std = None
        with trace_range("eigh"):  # "cuSolver SVD" range analog, RapidsRowMatrix.scala:70
            if solver == "svd":
                pc, explained = _svd_from_r_jit(r, k)
            elif standardize:
                cov, mean, std = L.standardized_cov_from_stats(stats)
                pc, explained = L.pca_fit_from_cov(cov, k, solver=solver)
            else:
                pc, explained = _fit_from_stats_jit(stats, k, mean_centering, solver)

        model = PCAModel(
            uid=self.uid,
            pc=np.asarray(pc),
            explainedVariance=np.asarray(explained),
            mean=None if mean is None else np.asarray(mean),
            std=None if std is None else np.asarray(std),
        )
        return self._copyValues(model)


class PCAModel(PCAParams, Model):
    """Fitted PCA model: ``pc`` [n, k] and ``explainedVariance`` [k].

    ``transform`` is dual-path like the reference (RapidsPCA.scala:128-161):
    the columnar path projects whole batches on device; ``transform_rows`` is
    the row-at-a-time CPU fallback (``apply``, RapidsPCA.scala:157-160).
    """

    def __init__(
        self,
        uid: str | None = None,
        pc: np.ndarray | None = None,
        explainedVariance: np.ndarray | None = None,
        mean: np.ndarray | None = None,
        std: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.pc = None if pc is None else np.asarray(pc)
        self.explainedVariance = (
            None if explainedVariance is None else np.asarray(explainedVariance)
        )
        # set on standardize=True fits: transform scales before projecting
        self.mean = None if mean is None else np.asarray(mean)
        self.std = None if std is None else np.asarray(std)

    # -- transform ----------------------------------------------------------
    def _standardize_host(self, mat: np.ndarray) -> np.ndarray:
        """(x − μ)/σ for standardize-fit models, applied BEFORE padding so
        pad rows stay zero (shared rule: columnar.standardize_host)."""
        return columnar.standardize_host(mat, self.mean, self.std)

    def _project_matrix(self, mat: np.ndarray) -> np.ndarray:
        padded, true_rows = columnar.pad_rows(self._standardize_host(mat))
        xd = jnp.asarray(padded)  # device dtype (f32 unless x64 is enabled)
        pc_dev = jnp.asarray(self.pc, dtype=xd.dtype)
        costmodel.capture("linalg.project", _project, xd, pc_dev)
        out = _project(xd, pc_dev)
        return np.asarray(out)[:true_rows]

    def transform(self, dataset: Any) -> Any:
        """Project the input column; returns the same container type with the
        output column appended (ArrayType-shaped, like the reference)."""
        with trace_range("pca transform"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._project_matrix,
            )

    def transform_rows(self, rows, use_native: bool = False) -> list[np.ndarray]:
        """CPU row-fallback path (reference ``apply``, RapidsPCA.scala:157-160):
        pcᵀ·row per row, no accelerator involved. With ``use_native=True`` the
        rows are packed and projected through the C++ bridge instead (the
        native columnar path of the reference's dual-mode UDF)."""
        mat = self._standardize_host(np.stack([np.asarray(r) for r in rows]))
        rows = list(mat)
        if use_native:
            from spark_rapids_ml_tpu import bridge

            packed = bridge.pack_rows(rows)
            return list(bridge.project(packed, self.pc))
        pct = self.pc.T
        return [pct @ r for r in rows]

    # -- persistence ----------------------------------------------------------
    def _saveData(self) -> dict[str, np.ndarray]:
        out = {"pc": self.pc, "explainedVariance": self.explainedVariance}
        if self.mean is not None:
            out["mean"] = self.mean
            out["std"] = self.std
        return out

    @classmethod
    def _fromSaved(cls, uid: str, data: dict[str, np.ndarray]) -> "PCAModel":
        return cls(
            uid=uid,
            pc=data["pc"],
            explainedVariance=data["explainedVariance"],
            mean=data.get("mean"),
            std=data.get("std"),
        )

    # -- stock pyspark.ml interop (layout="spark") ---------------------------
    # Spark's PCAModelWriter persists Row(pc: DenseMatrix, explainedVariance:
    # DenseVector) under data/ plus DefaultParamsWriter metadata — the exact
    # shape the reference writes too (RapidsPCA.scala:193-199). Only params
    # stock Spark's PCAModel knows may appear in the metadata (its loader
    # rejects unknown names).
    _SPARK_ML_CLASS = "org.apache.spark.ml.feature.PCAModel"
    _SPARK_ML_PARAMS = ("k", "inputCol", "outputCol")

    def _saveSparkML(self, path: str) -> None:
        from spark_rapids_ml_tpu.models.base import spark_set_params
        from spark_rapids_ml_tpu.utils import persistence as P

        if self.mean is not None:
            raise NotImplementedError(
                "stock Spark ML's PCAModel cannot represent a "
                "standardize=True model's scaling state (mean/std); save "
                "with the native layout, or fit an explicit "
                "StandardScaler + PCA pipeline for Spark interop"
            )
        params = {
            k: v
            for k, v in spark_set_params(self).items()
            if k in self._SPARK_ML_PARAMS
        }
        params.setdefault("k", int(self.pc.shape[1]))
        P.save_spark_ml_metadata(
            path,
            class_name=self._SPARK_ML_CLASS,
            uid=self.uid,
            param_map=params,
        )
        P.save_spark_ml_data(
            path,
            {
                "pc": P._dense_matrix_struct(self.pc),
                "explainedVariance": P._dense_vector_struct(self.explainedVariance),
            },
            {
                "type": "struct",
                "fields": [
                    {
                        "name": "pc",
                        "type": P._matrix_udt_json(),
                        "nullable": True,
                        "metadata": {},
                    },
                    {
                        "name": "explainedVariance",
                        "type": P._vector_udt_json(),
                        "nullable": True,
                        "metadata": {},
                    },
                ],
            },
        )

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "PCAModel":
        from spark_rapids_ml_tpu.utils import persistence as P

        return cls(
            uid=meta["uid"],
            pc=P.struct_to_matrix(table.column("pc")[0].as_py()),
            explainedVariance=P.struct_to_vector(
                table.column("explainedVariance")[0].as_py()
            ),
        )
