"""RandomForestClassifier / RandomForestRegressor — the tree family.

Spark-ML-shaped API (params, fit/transform, persistence) over the
histogram-tree kernels in ops/forest.py. The modern spark-rapids-ml family
ships both estimators on cuML's GPU forest; the 22.12 reference this
framework re-designs stops at PCA (SURVEY.md §2), so this is a
capability-add with the same API surface Spark MLlib exposes
(pyspark.ml.classification.RandomForestClassifier /
pyspark.ml.regression.RandomForestRegressor).

Spark-semantics choices mirrored here:

- features are quantile-binned to ``maxBins`` histogram bins (Spark MLlib
  itself is a binned-tree implementation with the same param);
- bootstrap draws Poisson(subsamplingRate) per-row counts (Spark's
  BaggedPoint), multiplied into any ``weightCol`` instance weights;
- ``featureSubsetStrategy`` per-NODE feature subsets ('auto' = sqrt(F)
  for classification, F/3 for regression — Spark's defaults);
- classifier probability = average of per-tree leaf class distributions,
  rawPrediction = their sum (Spark RandomForestClassificationModel);
- regressor prediction = mean of per-tree leaf means;
- ``minInstancesPerNode`` gates on WEIGHTED counts (with unweighted data
  and bootstrap counts these are the sampled instance counts).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    Param,
)
from spark_rapids_ml_tpu.ops import forest as FO
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

#: rows sampled (not streamed) for quantile bin-edge estimation — the same
#: bounded-sample role Spark's findSplits sampling plays
_MAX_BIN_SAMPLE = 200_000


def subset_size(strategy: str, n_features: int, *, classification: bool) -> int:
    """Spark featureSubsetStrategy → per-node feature count."""
    s = str(strategy).lower()
    if s == "auto":
        s = "sqrt" if classification else "onethird"
    if s == "all":
        return n_features
    # Spark CEILS the named strategies (RandomForestParams: sqrt → ceil(√F),
    # log2 → ceil(log₂F), onethird → ceil(F/3)) — floor under-samples, e.g.
    # F=10 must give 4 features for 'sqrt', not 3
    if s == "sqrt":
        return max(1, math.ceil(math.sqrt(n_features)))
    if s == "log2":
        return max(1, math.ceil(math.log2(n_features)))
    if s == "onethird":
        return max(1, math.ceil(n_features / 3.0))
    try:
        v = float(s)
    except ValueError:
        raise ValueError(
            f"featureSubsetStrategy must be auto/all/sqrt/log2/onethird or "
            f"a number, got {strategy!r}"
        ) from None
    if v >= 1.0:
        return min(n_features, int(v))
    if v > 0.0:
        # Spark ceils fractional strategies (RandomForest.getFeatureSubsetNumber)
        return min(n_features, max(1, math.ceil(v * n_features)))
    raise ValueError(f"featureSubsetStrategy must be > 0, got {strategy!r}")


def quantile_bin_edges(
    x: np.ndarray, n_bins: int, seed: int, w: np.ndarray | None = None
) -> np.ndarray:
    """[F, n_bins−1] interior quantile edges from a bounded row sample.

    Zero-weight rows are EXCLUDED before the quantile pass — an excluded
    instance must not stretch the bin grid any more than it may vote in a
    histogram (positive fractional weights still count one row each, the
    same approximation Spark's unweighted findSplits sampling makes)."""
    if w is not None:
        x = x[np.asarray(w) > 0]
    if x.shape[0] > _MAX_BIN_SAMPLE:
        rng = np.random.default_rng(seed)
        x = x[rng.choice(x.shape[0], _MAX_BIN_SAMPLE, replace=False)]
    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return np.quantile(x, qs, axis=0).T.astype(np.float64)


def tree_feature_importances(
    trees: FO.TreeArrays, n_features: int
) -> np.ndarray:
    """Spark's TreeEnsembleModel.featureImportances: per tree, sum each
    split node's n-scaled impurity gain by feature and normalize to 1;
    average the per-tree vectors; normalize again. Shared by the forest
    and GBT models (both carry gains in the same heap arrays)."""
    T = trees.feature.shape[0]
    out = np.zeros((T, n_features))
    for t in range(T):
        feat = trees.feature[t]
        split = feat >= 0
        np.add.at(out[t], feat[split], trees.gain[t][split])
        tot = out[t].sum()
        if tot > 0:
            out[t] /= tot
    avg = out.mean(0)
    s = avg.sum()
    return avg / s if s > 0 else avg


def split_thresholds(trees: FO.TreeArrays, edges: np.ndarray) -> np.ndarray:
    """[T, nodes] raw-value split thresholds from (feature, split_bin) —
    bin b splits at edges[f, b] (go right when x > edge); leaves get 0.
    Shared by the forest and GBT fits so inference needs no binning."""
    feat = np.clip(trees.feature, 0, None)
    thresholds = np.take_along_axis(
        edges[feat.reshape(-1)],
        np.clip(trees.split_bin, 0, edges.shape[1] - 1).reshape(-1, 1),
        axis=1,
    ).reshape(trees.feature.shape)
    return np.where(trees.feature >= 0, thresholds, 0.0)


def bin_features(x: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """[rows, F] int32 bin ids: bin b ⇔ edges[b−1] < x ≤ edges[b]."""
    out = np.empty(x.shape, dtype=np.int32)
    for j in range(x.shape[1]):
        out[:, j] = np.searchsorted(edges[j], x[:, j], side="left")
    return out


class _ForestParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    numTrees = Param("numTrees", "number of trees", int)
    maxDepth = Param("maxDepth", "maximum tree depth (root = depth 0)", int)
    maxBins = Param("maxBins", "histogram bins per feature", int)
    minInstancesPerNode = Param(
        "minInstancesPerNode",
        "minimum weighted instance count per child for a split",
        float,
    )
    minInfoGain = Param("minInfoGain", "minimum impurity decrease", float)
    featureSubsetStrategy = Param(
        "featureSubsetStrategy",
        "features considered per node: auto/all/sqrt/log2/onethird or a "
        "count/fraction",
        str,
    )
    subsamplingRate = Param(
        "subsamplingRate", "bootstrap sample rate per tree", float
    )
    bootstrap = Param(
        "bootstrap",
        "Poisson bootstrap per tree (False = every tree sees all rows)",
        bool,
    )
    seed = Param("seed", "random seed", int)
    weightCol = Param(
        "weightCol", "optional instance-weight column", str
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            featuresCol="features", labelCol="label",
            predictionCol="prediction",
            numTrees=20, maxDepth=5, maxBins=32, minInstancesPerNode=1.0,
            minInfoGain=0.0, featureSubsetStrategy="auto",
            subsamplingRate=1.0, bootstrap=True, seed=0,
        )

    def getNumTrees(self) -> int:
        return self.getOrDefault("numTrees")

    def getMaxDepth(self) -> int:
        return self.getOrDefault("maxDepth")

    def getMaxBins(self) -> int:
        return self.getOrDefault("maxBins")

    def getSeed(self) -> int:
        return self.getOrDefault("seed")


class _ForestEstimator(_ForestParams, Estimator):
    _classification: bool  # set by subclasses
    _impurity_choices: tuple

    def setNumTrees(self, value: int):
        if value < 1:
            raise ValueError(f"numTrees must be >= 1, got {value}")
        return self._set(numTrees=value)

    def setMaxDepth(self, value: int):
        if not 0 <= value <= 14:
            raise ValueError(f"maxDepth must be in [0, 14], got {value}")
        return self._set(maxDepth=value)

    def setMaxBins(self, value: int):
        if value < 2:
            raise ValueError(f"maxBins must be >= 2, got {value}")
        return self._set(maxBins=value)

    def setMinInstancesPerNode(self, value: float):
        if value < 1:
            raise ValueError(f"minInstancesPerNode must be >= 1, got {value}")
        return self._set(minInstancesPerNode=float(value))

    def setMinInfoGain(self, value: float):
        return self._set(minInfoGain=float(value))

    def setFeatureSubsetStrategy(self, value):
        return self._set(featureSubsetStrategy=str(value))

    def setSubsamplingRate(self, value: float):
        if not 0.0 < value <= 1.0:
            raise ValueError(f"subsamplingRate must be in (0, 1], got {value}")
        return self._set(subsamplingRate=float(value))

    def setBootstrap(self, value: bool):
        return self._set(bootstrap=bool(value))

    def setSeed(self, value: int):
        return self._set(seed=value)

    def setWeightCol(self, value: str):
        return self._set(weightCol=value)

    def setImpurity(self, value: str):
        if value not in self._impurity_choices:
            raise ValueError(
                f"impurity must be one of {self._impurity_choices}, got {value!r}"
            )
        return self._set(impurity=value)

    def getImpurity(self) -> str:
        return self.getOrDefault("impurity")

    def _make_model(self, x, y, w, builder=None):
        """THE fit-then-wrap handoff — one copy for every tree estimator;
        subclasses choose the model class via ``_model_cls``."""
        trees, thresholds = self._fit_arrays(x, y, w, builder=builder)
        model = self._model_cls(
            uid=self.uid, trees=trees, thresholds=thresholds,
            numFeatures=self._n_features_in,
        )
        return self._copyValues(model)

    def _fit_arrays(
        self,
        x: np.ndarray,
        y: np.ndarray,
        w: np.ndarray | None,
        builder=None,
    ):
        """(trees, thresholds) — the shared fit body. ``builder`` overrides
        the single-device :func:`ops.forest.build_forest` (same signature +
        the static kwargs) so the Spark wrapper can route the build through
        the mesh-sharded program (parallel/forest.py)."""
        n_bins = self.getMaxBins()
        seed = self.getSeed()
        n_trees = self.getNumTrees()
        max_depth = self.getMaxDepth()
        fdt = columnar.float_dtype_for(x.dtype)

        edges = quantile_bin_edges(x, n_bins, seed, w)
        binned = bin_features(x, edges)
        row_stats = self._row_stats(y, fdt)

        rng = np.random.default_rng(seed)
        base_w = np.ones(len(x), fdt) if w is None else w.astype(fdt)
        rate = self.getOrDefault("subsamplingRate")
        if self.getOrDefault("bootstrap"):
            weights = rng.poisson(rate, size=(n_trees, len(x))).astype(fdt)
        elif rate < 1.0:
            # Spark bootstrap=False subsampling is WITHOUT replacement:
            # Bernoulli(rate) per row per tree (BaggedPoint semantics)
            weights = (
                rng.random(size=(n_trees, len(x))) < rate
            ).astype(fdt)
        else:
            weights = np.ones((n_trees, len(x)), fdt)
        weights *= base_w[None, :]

        k_feat = subset_size(
            self.getOrDefault("featureSubsetStrategy"),
            x.shape[1],
            classification=self._classification,
        )
        keys = jax.random.split(jax.random.PRNGKey(seed), n_trees)
        build = FO.build_forest if builder is None else builder
        with trace_range("forest build"):
            trees = build(
                keys,
                jnp.asarray(binned),
                jnp.asarray(row_stats),
                jnp.asarray(weights),
                jnp.asarray(np.asarray(self.getOrDefault("minInstancesPerNode"), fdt)),
                jnp.asarray(np.asarray(self.getOrDefault("minInfoGain"), fdt)),
                max_depth=max_depth,
                n_bins=n_bins,
                k_features=k_feat,
                impurity=self.getImpurity(),
            )
        self._n_features_in = x.shape[1]
        trees = FO.TreeArrays(*(np.asarray(a) for a in trees))
        return trees, split_thresholds(trees, edges)

    def fit(self, dataset: Any, num_partitions: int | None = None):
        parts = columnar.labeled_partitions(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("labelCol"),
            num_partitions,
            weight_col=self._paramMap.get("weightCol"),
        )
        x = np.concatenate([p[0] for p in parts])
        y = np.concatenate([p[1] for p in parts])
        w = (
            np.concatenate([p[2] for p in parts])
            if parts[0][2] is not None
            else None
        )
        return self._make_model(x, y, w)


class _ForestModel(_ForestParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        trees: FO.TreeArrays | None = None,
        thresholds: np.ndarray | None = None,
        numFeatures: int = -1,
    ):
        super().__init__(uid)
        self.trees = trees
        self.thresholds = (
            None if thresholds is None else np.asarray(thresholds)
        )
        self._num_features = int(numFeatures)

    @property
    def numFeatures(self) -> int:
        """Training feature count (Spark model API)."""
        return self._num_features

    def predict(self, row) -> float:
        return float(
            self._predict_matrix(np.asarray(row, dtype=np.float64)[None, :])[0]
        )

    def getNumTrees(self) -> int:  # fitted count, not the param
        return self.trees.feature.shape[0]

    @property
    def totalNumNodes(self) -> int:
        """Materialized (reachable) nodes across the forest — Spark's
        totalNumNodes analog for the heap layout."""
        reachable = np.sum(self.trees.leaf_stats.sum(-1) > 0, axis=1)
        return int(np.sum(np.maximum(reachable, 1)))

    def _leaf_stats_for(self, mat: np.ndarray) -> np.ndarray:
        """[T, rows, S] leaf stats via the device descent kernel."""
        max_depth = int(
            np.log2(self.trees.feature.shape[1] + 1) - 1
        )
        return np.asarray(
            FO.forest_apply(
                FO.TreeArrays(*(jnp.asarray(a) for a in self.trees)),
                jnp.asarray(mat),
                jnp.asarray(self.thresholds),
                max_depth=max_depth,
            )
        )

    @property
    def featureImportances(self) -> np.ndarray:
        """Impurity-based importances, Spark's recipe
        (RandomForest.featureImportances)."""
        return tree_feature_importances(self.trees, self._num_features)

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "feature": self.trees.feature,
            "split_bin": self.trees.split_bin,
            "is_leaf": self.trees.is_leaf,
            "leaf_stats": self.trees.leaf_stats,
            "gain": self.trees.gain,
            "thresholds": self.thresholds,
            "numFeatures": np.asarray([self._num_features]),
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        trees = FO.TreeArrays(
            data["feature"].astype(np.int32),
            data["split_bin"].astype(np.int32),
            data["is_leaf"].astype(bool),
            data["leaf_stats"],
            # pre-gain saves load with zero importances rather than failing
            data.get("gain", np.zeros(data["feature"].shape)),
        )
        return cls(
            uid=uid,
            trees=trees,
            thresholds=data["thresholds"],
            numFeatures=int(data["numFeatures"][0]),
        )


# ---------------------------------------------------------------------------
# Classifier
# ---------------------------------------------------------------------------


class _ClassifierCols:
    probabilityCol = Param("probabilityCol", "class-probability column", str)
    rawPredictionCol = Param(
        "rawPredictionCol", "summed per-tree distribution column", str
    )

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            probabilityCol="probability", rawPredictionCol="rawPrediction",
            impurity="gini",
        )

    def setProbabilityCol(self, value: str):
        return self._set(probabilityCol=value)

    def setRawPredictionCol(self, value: str):
        return self._set(rawPredictionCol=value)


class RandomForestClassifier(_ClassifierCols, _ForestEstimator):
    impurity = Param("impurity", "'gini' or 'entropy'", str)
    _classification = True
    _impurity_choices = ("gini", "entropy")

    def _row_stats(self, y: np.ndarray, fdt) -> np.ndarray:
        classes = np.round(y).astype(np.int64)
        if (classes < 0).any() or not np.allclose(y, classes):
            raise ValueError(
                "classification labels must be non-negative integers "
                "(Spark ML label contract)"
            )
        return np.eye(int(classes.max()) + 1, dtype=fdt)[classes]

    @property
    def _model_cls(self):
        return RandomForestClassificationModel


class RandomForestClassificationModel(_ClassifierCols, _ForestModel):
    impurity = Param("impurity", "'gini' or 'entropy'", str)

    @property
    def numClasses(self) -> int:
        return self.trees.leaf_stats.shape[-1]

    def proba_and_predictions(self, mat):
        """([rows, C] averaged per-tree distributions, [rows] argmax) —
        Spark's RandomForestClassificationModel decision rule."""
        leaf = self._leaf_stats_for(mat)  # [T, rows, C]
        tot = leaf.sum(-1, keepdims=True)
        per_tree = np.divide(
            leaf, np.where(tot > 0, tot, 1.0), dtype=leaf.dtype
        )
        raw = per_tree.sum(0)
        proba = raw / leaf.shape[0]
        return proba, np.argmax(proba, axis=1).astype(np.float64)

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        return self.proba_and_predictions(mat)[1]

    def transform(self, dataset: Any) -> Any:
        if columnar.has_named_columns(dataset):
            mat = columnar.extract_matrix(
                dataset, self.getOrDefault("featuresCol")
            )
            proba, preds = self.proba_and_predictions(mat)
            cols = [
                (self.getOrDefault("rawPredictionCol"), proba * len(self.trees.feature)),
                (self.getOrDefault("probabilityCol"), proba),
                (self.getOrDefault("predictionCol"), preds),
            ]
            return columnar.append_columns(dataset, cols)
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )


# ---------------------------------------------------------------------------
# Regressor
# ---------------------------------------------------------------------------


class RandomForestRegressor(_ForestEstimator):
    impurity = Param("impurity", "'variance'", str)
    _classification = False
    _impurity_choices = ("variance",)

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(impurity="variance")

    def _row_stats(self, y: np.ndarray, fdt) -> np.ndarray:
        y = y.astype(fdt)
        return np.stack([np.ones_like(y), y, y * y], axis=1)

    @property
    def _model_cls(self):
        return RandomForestRegressionModel


class RandomForestRegressionModel(_ForestModel):
    impurity = Param("impurity", "'variance'", str)

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        leaf = self._leaf_stats_for(mat)  # [T, rows, 3]
        w = leaf[..., 0]
        mean = leaf[..., 1] / np.where(w > 0, w, 1.0)
        return mean.mean(0)

    def transform(self, dataset: Any) -> Any:
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )


# ---------------------------------------------------------------------------
# Single decision trees (pyspark.ml parity: a forest of one)
# ---------------------------------------------------------------------------


class _SingleTreeDefaults:
    """pyspark.ml's DecisionTree* estimators are exactly the forest
    machinery at numTrees=1, no bootstrap, all features per node — the
    deterministic CART the forest randomizes. Depth of the model's single
    tree and its importances come from the shared ensemble arrays."""

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            numTrees=1, bootstrap=False, featureSubsetStrategy="all"
        )

    def setNumTrees(self, value):  # a decision tree IS one tree
        raise AttributeError(
            "DecisionTree estimators fit exactly one tree; use the "
            "RandomForest estimators for ensembles"
        )


class DecisionTreeClassifier(_SingleTreeDefaults, RandomForestClassifier):
    @property
    def _model_cls(self):
        return DecisionTreeClassificationModel


def _require_single_tree(data):
    """DecisionTree*Model.load must reject multi-tree (forest) saves — the
    richer-subclass upgrade rule assumes added behavior, not structure."""
    n_trees = data["feature"].shape[0]
    if n_trees != 1:
        raise TypeError(
            f"save holds {n_trees} trees; a DecisionTree model is exactly "
            "one — load it through the RandomForest model class"
        )


class DecisionTreeClassificationModel(RandomForestClassificationModel):
    @classmethod
    def _fromSaved(cls, uid, data):
        _require_single_tree(data)
        return super()._fromSaved(uid, data)

    @property
    def depth(self) -> int:
        """Depth of the fitted tree (deepest materialized split + 1)."""
        split_nodes = np.flatnonzero(self.trees.feature[0] >= 0)
        if len(split_nodes) == 0:
            return 0
        return int(np.floor(np.log2(split_nodes.max() + 1)) + 1)


class DecisionTreeRegressor(_SingleTreeDefaults, RandomForestRegressor):
    @property
    def _model_cls(self):
        return DecisionTreeRegressionModel


class DecisionTreeRegressionModel(RandomForestRegressionModel):
    depth = DecisionTreeClassificationModel.depth

    @classmethod
    def _fromSaved(cls, uid, data):
        _require_single_tree(data)
        return super()._fromSaved(uid, data)
