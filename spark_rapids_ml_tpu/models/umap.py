"""UMAP estimator/model — the spark-rapids-ml manifold-learning family.

API mirrors spark-rapids-ml's cuML-backed UMAP: ``fit`` learns an
embedding of the training set (held on the model as ``embedding_``),
``transform`` embeds NEW rows against the fitted reference set, params
follow the cuML/umap-learn names (nNeighbors, nComponents, minDist,
spread, nEpochs, learningRate, negativeSampleRate, init, seed).

Pipeline (ops/umap.py has the kernel story):
1. exact k-NN graph (ops/neighbors.knn_topk — MXU tournament);
2. vectorized-bisection (rho, sigma) calibration + fuzzy set union;
3. spectral (scipy eigsh on the k-sparse Laplacian) or random init;
4. the SGD force layout as ONE lax.fori_loop XLA program.

``transform`` is the reference's out-of-sample recipe: k-NN of the new
rows against the TRAINING set, init at the membership-weighted mean of
neighbor embeddings, then a short reference-frozen optimization
(``move_tails=False``): only the new points move, attracted along their
neighbor edges and repelled by negative samples — umap-learn's transform
semantics.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import HasInputCol, HasOutputCol, Param
from spark_rapids_ml_tpu.models.neighbors import _finalize_distances
from spark_rapids_ml_tpu.ops import neighbors as NN
from spark_rapids_ml_tpu.ops import umap as UM
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range


class _UMAPParams(HasInputCol, HasOutputCol):
    nNeighbors = Param("nNeighbors", "k of the fuzzy k-NN graph", int)
    nComponents = Param("nComponents", "embedding dimensionality", int)
    nEpochs = Param(
        "nEpochs",
        "SGD epochs (0 = auto: 500 small / 200 large, the umap-learn rule)",
        int,
    )
    learningRate = Param("learningRate", "initial SGD learning rate", float)
    minDist = Param("minDist", "minimum embedded pair distance", float)
    spread = Param("spread", "embedding scale of the membership curve", float)
    negativeSampleRate = Param(
        "negativeSampleRate", "negative samples per positive edge", int
    )
    init = Param("init", "'spectral' (default) or 'random'", str)
    seed = Param("seed", "random seed", int)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            nNeighbors=15, nComponents=2, nEpochs=0, learningRate=1.0,
            minDist=0.1, spread=1.0, negativeSampleRate=5, init="spectral",
            seed=0, outputCol="embedding",
        )

    def getNNeighbors(self) -> int:
        return self.getOrDefault("nNeighbors")

    def getNComponents(self) -> int:
        return self.getOrDefault("nComponents")


class UMAP(_UMAPParams, Estimator):
    def setNNeighbors(self, value: int) -> "UMAP":
        if value < 2:
            raise ValueError(f"nNeighbors must be >= 2, got {value}")
        return self._set(nNeighbors=value)

    def setNComponents(self, value: int) -> "UMAP":
        if value < 1:
            raise ValueError(f"nComponents must be >= 1, got {value}")
        return self._set(nComponents=value)

    def setNEpochs(self, value: int) -> "UMAP":
        return self._set(nEpochs=value)

    def setLearningRate(self, value: float) -> "UMAP":
        return self._set(learningRate=float(value))

    def setMinDist(self, value: float) -> "UMAP":
        return self._set(minDist=float(value))

    def setSpread(self, value: float) -> "UMAP":
        return self._set(spread=float(value))

    def setNegativeSampleRate(self, value: int) -> "UMAP":
        return self._set(negativeSampleRate=value)

    def setInit(self, value: str) -> "UMAP":
        if value not in ("spectral", "random"):
            raise ValueError(f"init must be 'spectral' or 'random', got {value!r}")
        return self._set(init=value)

    def setSeed(self, value: int) -> "UMAP":
        return self._set(seed=value)

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "UMAPModel":
        input_col = self._paramMap.get("inputCol")
        ds = columnar.PartitionedDataset.from_any(
            dataset, input_col, num_partitions
        )
        x = np.concatenate(list(ds.matrices()), axis=0)
        n = x.shape[0]
        k = self.getNNeighbors()
        if n <= k:
            raise ValueError(
                f"nNeighbors={k} needs more than {k} rows, got {n}"
            )
        fdt = columnar.float_dtype_for(x.dtype)
        xf = x.astype(fdt, copy=False)
        seed = self.getOrDefault("seed")
        dim = self.getNComponents()

        with trace_range("umap knn graph"):
            scores, idx = NN.knn_topk(
                jnp.asarray(xf),
                jnp.asarray(xf),
                jnp.asarray(np.ones(n, bool)),
                k + 1,  # self lands in the list; calibration treats d=0 as self
            )
            knn_d = _finalize_distances(np.asarray(scores), "euclidean")[:, 1:]
            knn_i = np.asarray(idx)[:, 1:]

        with trace_range("umap fuzzy graph"):
            rho, sigma = UM.smooth_knn_calibration(jnp.asarray(knn_d))
            w = np.asarray(
                UM.membership_strengths(jnp.asarray(knn_d), rho, sigma)
            )
            heads, tails, weights = UM.fuzzy_union_edges(knn_i, w)

        n_epochs = self.getOrDefault("nEpochs") or (500 if n < 10_000 else 200)
        # drop edges too weak to ever fire (umap-learn's threshold)
        keep = weights >= weights.max() / float(n_epochs)
        heads, tails, weights = heads[keep], tails[keep], weights[keep]
        # the reference's symmetric COO carries BOTH (i,j) and (j,i): every
        # point appears as head, so every point receives negative-sample
        # repulsion and each pair fires at the reference rate. The
        # undirected list (kept for spectral init) is doubled here.
        heads_d = np.concatenate([heads, tails])
        tails_d = np.concatenate([tails, heads])
        weights_d = np.concatenate([weights, weights])
        eps_per_sample = weights_d.max() / weights_d

        a, b = UM.find_ab_params(
            self.getOrDefault("spread"), self.getOrDefault("minDist")
        )
        with trace_range("umap init"):
            if self.getOrDefault("init") == "spectral":
                emb0 = UM.spectral_init(heads, tails, weights, n, dim, seed)
            else:
                emb0 = np.random.default_rng(seed).uniform(
                    -10, 10, size=(n, dim)
                )

        with trace_range("umap layout"):
            emb = np.asarray(
                UM.optimize_layout(
                    jax.random.PRNGKey(seed),
                    jnp.asarray(emb0.astype(fdt)),
                    jnp.asarray(heads_d),
                    jnp.asarray(tails_d),
                    jnp.asarray(eps_per_sample.astype(fdt)),
                    jnp.asarray(np.asarray(a, fdt)),
                    jnp.asarray(np.asarray(b, fdt)),
                    n_epochs=int(n_epochs),
                    n_neg=int(self.getOrDefault("negativeSampleRate")),
                    initial_lr=float(self.getOrDefault("learningRate")),
                )
            )
        model = UMAPModel(
            uid=self.uid, rawData=xf, embedding=emb,
            a=float(a), b=float(b),
        )
        return self._copyValues(model)


class UMAPModel(_UMAPParams, Model):
    """Holds the training data + its embedding (cuML UMAPModel shape:
    ``embedding_`` is the fitted layout; transform embeds new rows)."""

    def __init__(
        self,
        uid: str | None = None,
        rawData: np.ndarray | None = None,
        embedding: np.ndarray | None = None,
        a: float = 1.577,
        b: float = 0.895,
    ):
        super().__init__(uid)
        self.rawData = None if rawData is None else np.asarray(rawData)
        self.embedding_ = None if embedding is None else np.asarray(embedding)
        self.a = float(a)
        self.b = float(b)

    def _embed_matrix(self, mat: np.ndarray) -> np.ndarray:
        """Out-of-sample embedding: neighbor-weighted init + short
        reference-frozen refinement (new points move under both attraction
        and negative-sample repulsion; reference points stay fixed)."""
        fdt = self.rawData.dtype
        q = mat.astype(fdt, copy=False)
        if q.shape[1] != self.rawData.shape[1]:
            raise ValueError(
                f"rows have {q.shape[1]} features but the model was fitted "
                f"on {self.rawData.shape[1]}"
            )
        k = min(self.getNNeighbors(), self.rawData.shape[0])
        nq = q.shape[0]
        scores, idx = NN.knn_topk(
            jnp.asarray(q),
            jnp.asarray(self.rawData),
            jnp.asarray(np.ones(self.rawData.shape[0], bool)),
            k,
        )
        knn_d = _finalize_distances(np.asarray(scores), "euclidean")
        knn_i = np.asarray(idx)
        rho, sigma = UM.smooth_knn_calibration(jnp.asarray(knn_d))
        w = np.asarray(
            UM.membership_strengths(jnp.asarray(knn_d), rho, sigma)
        )
        w = w / np.maximum(w.sum(axis=1, keepdims=True), 1e-12)
        init = np.einsum("qk,qkd->qd", w, self.embedding_[knn_i])

        # short refinement: new points (heads, offset by the reference
        # count) attract to their neighbors; reference points stay frozen
        n_ref = self.embedding_.shape[0]
        heads = np.repeat(np.arange(nq, dtype=np.int32), k) + n_ref
        tails = knn_i.reshape(-1).astype(np.int32)
        weights = w.reshape(-1)
        keep = weights > 1e-12
        heads, tails, weights = heads[keep], tails[keep], weights[keep]
        eps_per_sample = weights.max() / weights
        combined = np.concatenate([self.embedding_, init]).astype(fdt)
        out = np.asarray(
            UM.optimize_layout(
                jax.random.PRNGKey(self.getOrDefault("seed") + 1),
                jnp.asarray(combined),
                jnp.asarray(heads),
                jnp.asarray(tails),
                jnp.asarray(eps_per_sample.astype(fdt)),
                jnp.asarray(np.asarray(self.a, fdt)),
                jnp.asarray(np.asarray(self.b, fdt)),
                n_epochs=30,
                n_neg=self.getOrDefault("negativeSampleRate"),
                initial_lr=float(self.getOrDefault("learningRate")) / 4.0,
                move_tails=False,
            )
        )
        return out[n_ref:]

    def transform(self, dataset: Any) -> Any:
        with trace_range("umap transform"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOrDefault("outputCol"),
                self._embed_matrix,
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "rawData": self.rawData,
            "embedding": self.embedding_,
            "ab": np.asarray([self.a, self.b]),
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            rawData=data["rawData"],
            embedding=data["embedding"],
            a=float(data["ab"][0]),
            b=float(data["ab"][1]),
        )
