"""NaiveBayes — pyspark.ml's three-flavor NB from one statistics pass.

Spark's surface mirrored: ``modelType`` 'multinomial' (default) /
'bernoulli' / 'gaussian', ``smoothing`` λ (Laplace/Lidstone), Spark ML's
``weightCol`` contract, and the model's ``pi`` (log class priors),
``theta`` (log feature parameters, [C, F]) and ``sigma`` (gaussian
variances). Training is ONE distributed NBStats monoid pass
(ops/naive_bayes.py) + a closed-form host solve; prediction is one
matmul against theta (+ the flavor's additive corrections).

Closed forms (all sklearn-identical — the tests assert parameter-level
equality against MultinomialNB / BernoulliNB / GaussianNB):

- multinomial: θ = log((S_cf + λ) / (Σ_f S_cf + λF));
- bernoulli:   p = (S_cf + λ) / (N_c + 2λ); raw adds both log p and
  log(1−p) legs (features must be 0/1, validated like Spark);
- gaussian:    μ = S/N from the first pass; σ² from a SECOND centered
  pass Σw(x−μ_c)²/N (numerically stable on offset-heavy features);
  raw = π − ½Σ(log 2πσ² + (x−μ)²/σ²).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    Param,
)
from spark_rapids_ml_tpu.ops import naive_bayes as NB
from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

_MODEL_TYPES = ("multinomial", "bernoulli", "gaussian")


class _NBParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    modelType = Param(
        "modelType", "'multinomial' (default), 'bernoulli', or 'gaussian'", str
    )
    smoothing = Param("smoothing", "Laplace smoothing λ", float)
    probabilityCol = Param("probabilityCol", "class-probability column", str)
    rawPredictionCol = Param(
        "rawPredictionCol", "per-class log-likelihood column", str
    )
    weightCol = Param("weightCol", "optional instance-weight column", str)
    distribution = Param(
        "distribution",
        "'driver-merge' (host tree-reduce of per-partition NBStats) or "
        "'mesh-local' (rows concatenated onto THIS process's device mesh; "
        "both statistics passes reduce via psum collectives) — identical "
        "results, the framework-wide distribution contract",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            featuresCol="features", labelCol="label",
            predictionCol="prediction", probabilityCol="probability",
            rawPredictionCol="rawPrediction",
            modelType="multinomial", smoothing=1.0,
            distribution="driver-merge",
        )

    def getModelType(self) -> str:
        return self.getOrDefault("modelType")

    def getSmoothing(self) -> float:
        return self.getOrDefault("smoothing")


class NaiveBayes(_NBParams, Estimator):
    def setModelType(self, value: str) -> "NaiveBayes":
        if value not in _MODEL_TYPES:
            raise ValueError(
                f"modelType must be one of {_MODEL_TYPES}, got {value!r}"
            )
        return self._set(modelType=value)

    def setSmoothing(self, value: float) -> "NaiveBayes":
        if value < 0:
            raise ValueError(f"smoothing must be >= 0, got {value}")
        return self._set(smoothing=float(value))

    def setWeightCol(self, value: str) -> "NaiveBayes":
        return self._set(weightCol=value)

    def setProbabilityCol(self, value: str) -> "NaiveBayes":
        return self._set(probabilityCol=value)

    def setRawPredictionCol(self, value: str) -> "NaiveBayes":
        return self._set(rawPredictionCol=value)

    def setDistribution(self, value: str) -> "NaiveBayes":
        if value not in ("driver-merge", "mesh-local"):
            raise ValueError(
                "distribution must be 'driver-merge' or 'mesh-local', "
                f"got {value!r}"
            )
        return self._set(distribution=value)

    def fit(self, dataset: Any, num_partitions: int | None = None):
        parts = columnar.labeled_partitions(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("labelCol"),
            num_partitions,
            weight_col=self._paramMap.get("weightCol"),
        )
        model_type = self.getModelType()
        all_labels = np.unique(
            np.concatenate([np.unique(y) for _, y, _ in parts])
        )
        if not np.all(all_labels == np.round(all_labels)) or all_labels.min() < 0:
            raise ValueError(
                f"NaiveBayes requires integer class labels 0..C-1, got "
                f"{all_labels[:8]}"
            )
        n_classes = int(all_labels.max()) + 1
        if model_type in ("multinomial", "bernoulli"):
            for x, _, _ in parts:
                if (x < 0).any():
                    raise ValueError(
                        f"modelType='{model_type}' requires non-negative "
                        "features (Spark's requireNonnegativeValues)"
                    )
                if model_type == "bernoulli" and not np.isin(
                    x, (0.0, 1.0)
                ).all():
                    raise ValueError(
                        "modelType='bernoulli' requires 0/1 features "
                        "(Spark's requireZeroOneBernoulliValues)"
                    )

        mesh_local = self.getOrDefault("distribution") == "mesh-local"
        if mesh_local:
            # rows concatenated once onto THIS process's mesh, padded to an
            # equal-shard multiple with weight 0 — both passes psum
            from spark_rapids_ml_tpu.parallel.mesh import create_mesh
            from spark_rapids_ml_tpu.parallel.naive_bayes import (
                sharded_nb_centered_sq,
                sharded_nb_stats,
            )

            x_all = np.concatenate([p[0] for p in parts])
            y_all = np.concatenate([p[1] for p in parts])
            w_all = (
                np.concatenate([p[2] for p in parts])
                if parts[0][2] is not None
                else np.ones(len(x_all))
            )
            ndev = len(jax.devices())
            per = -(-len(x_all) // ndev)
            fdt = columnar.float_dtype_for(x_all.dtype)
            xp = np.zeros((per * ndev, x_all.shape[1]), fdt)
            xp[: len(x_all)] = x_all
            yp = np.zeros(per * ndev, fdt)
            yp[: len(x_all)] = y_all
            wp = np.zeros(per * ndev, fdt)
            wp[: len(x_all)] = w_all
            mesh = create_mesh(data=ndev)
            xd_m, yd_m, wd_m = (
                jnp.asarray(xp), jnp.asarray(yp), jnp.asarray(wp),
            )
            with trace_range("naive bayes stats (mesh)"):
                stats = sharded_nb_stats(xd_m, yd_m, wd_m, n_classes, mesh)
        else:

            def padded_parts():
                for x, y, w in parts:
                    padded, true_rows = columnar.pad_rows(x)
                    fdt = columnar.float_dtype_for(padded.dtype)
                    wv = np.zeros(padded.shape[0], fdt)
                    wv[:true_rows] = 1.0 if w is None else w
                    yv = np.zeros(padded.shape[0], fdt)
                    yv[:true_rows] = y
                    yield jnp.asarray(padded), jnp.asarray(yv), jnp.asarray(wv)

            with trace_range("naive bayes stats"):
                stats = tree_reduce(
                    [
                        NB.nb_stats(xd, yd, wd, n_classes)
                        for xd, yd, wd in padded_parts()
                    ],
                    NB.combine_nb_stats,
                )

        counts = np.asarray(stats.counts, dtype=np.float64)
        feat_sum = np.asarray(stats.feat_sum, dtype=np.float64)
        lam = self.getSmoothing()
        total = counts.sum()
        safe_counts = np.where(counts > 0, counts, 1.0)
        # Spark smooths the class priors with the same λ as the likelihoods
        # (NaiveBayes.scala piLogDenom): π_i = log((n_i + λ)/(N + λ·C)).
        # Unsmoothed log(n_i/N) diverges for classes absent from the sample.
        with np.errstate(divide="ignore"):
            pi = np.log(counts + lam) - np.log(total + lam * len(counts))
        F = feat_sum.shape[1]

        sigma = np.zeros((0, 0))
        if model_type == "multinomial":
            theta = np.log(feat_sum + lam) - np.log(
                feat_sum.sum(axis=1, keepdims=True) + lam * F
            )
        elif model_type == "bernoulli":
            p = (feat_sum + lam) / (counts[:, None] + 2.0 * lam)
            theta = np.log(p)  # log(1-p) is derived at predict time
        else:  # gaussian
            mu = feat_sum / safe_counts[:, None]
            # SECOND centered pass (ops.nb_centered_sq): variance from
            # squared deviations against the reduced class means — the
            # one-pass Sq/N − μ² form cancels catastrophically on
            # offset-heavy features (sklearn computes it this way too)
            with trace_range("naive bayes variance pass"):
                mu_d = jnp.asarray(mu)
                if mesh_local:
                    sq = sharded_nb_centered_sq(
                        xd_m, yd_m, wd_m, mu_d, n_classes, mesh
                    )
                else:
                    sq = tree_reduce(
                        [
                            NB.nb_centered_sq(xd, yd, wd, mu_d, n_classes)
                            for xd, yd, wd in padded_parts()
                        ],
                        lambda a, b: a + b,
                    )
            var = np.asarray(sq, dtype=np.float64) / safe_counts[:, None]
            theta = mu
            sigma = np.maximum(var, 1e-12)

        model = NaiveBayesModel(
            uid=self.uid, pi=pi, theta=theta, sigma=sigma,
        )
        return self._copyValues(model)


class NaiveBayesModel(_NBParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        pi: np.ndarray | None = None,
        theta: np.ndarray | None = None,
        sigma: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.pi = None if pi is None else np.asarray(pi)
        self.theta = None if theta is None else np.asarray(theta)
        self.sigma = None if sigma is None else np.asarray(sigma)

    @property
    def numClasses(self) -> int:
        return self.pi.shape[0]

    def _raw_scores(self, mat: np.ndarray) -> np.ndarray:
        """[rows, C] joint log-likelihoods (Spark's rawPrediction)."""
        model_type = self.getModelType()
        x = mat.astype(np.float64, copy=False)
        if model_type == "multinomial":
            return self.pi[None, :] + x @ self.theta.T
        if model_type == "bernoulli":
            if not np.isin(x, (0.0, 1.0)).all():
                raise ValueError(
                    "Bernoulli naive Bayes requires 0 or 1 feature values "
                    "at predict time (the Spark contract)"
                )
            log_p = self.theta
            log_1mp = np.log1p(-np.exp(self.theta))
            return (
                self.pi[None, :]
                + x @ (log_p - log_1mp).T
                + log_1mp.sum(axis=1)[None, :]
            )
        # gaussian
        mu, var = self.theta, self.sigma
        const = -0.5 * np.log(2.0 * np.pi * var).sum(axis=1)
        quad = -0.5 * (
            (x[:, None, :] - mu[None, :, :]) ** 2 / var[None, :, :]
        ).sum(axis=2)
        return self.pi[None, :] + const[None, :] + quad

    @staticmethod
    def _from_raw(raw: np.ndarray):
        shifted = raw - raw.max(axis=1, keepdims=True)
        e = np.exp(shifted)
        proba = e / e.sum(axis=1, keepdims=True)
        return proba, np.argmax(raw, axis=1).astype(np.float64)

    def proba_and_predictions(self, mat: np.ndarray):
        return self._from_raw(self._raw_scores(mat))

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        return self.proba_and_predictions(mat)[1]

    def transform(self, dataset: Any) -> Any:
        if columnar.has_named_columns(dataset):
            mat = columnar.extract_matrix(
                dataset, self.getOrDefault("featuresCol")
            )
            raw = self._raw_scores(mat)  # ONE scoring pass feeds all three
            proba, preds = self._from_raw(raw)
            return columnar.append_columns(
                dataset,
                [
                    (self.getOrDefault("rawPredictionCol"), raw),
                    (self.getOrDefault("probabilityCol"), proba),
                    (self.getOrDefault("predictionCol"), preds),
                ],
            )
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )

    def predict(self, row) -> float:
        return float(
            self._predict_matrix(np.asarray(row, dtype=np.float64)[None, :])[0]
        )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"pi": self.pi, "theta": self.theta, "sigma": self.sigma}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid, pi=data["pi"], theta=data["theta"], sigma=data["sigma"],
        )
