"""Text features — Tokenizer, HashingTF, IDF (pyspark.ml's classic trio).

The stages that turn raw strings into the numeric arrays every estimator
here consumes, mirroring Spark's surface (divergences documented per
stage):

- Tokenizer: lowercase + whitespace split. Divergence from Spark's
  ``split("\\s")``: runs of whitespace collapse here (Spark emits empty
  tokens for consecutive separators — an artifact most users regex away;
  documented rather than reproduced);
- HashingTF: the hashing trick onto ``numFeatures`` buckets (term
  frequency counts, or ``binary`` presence flags — Spark's params).
  Bucket assignment is an md5-derived stable hash, NOT Spark's Murmur3,
  so vectors are internally consistent and deterministic across
  processes but not bucket-identical to a JVM run (documented trade; the
  downstream math is invariant to the permutation). Output columns here
  are DENSE arrays (this package's columnar layer), so sizing differs
  from Spark's sparse vectors: a guard rejects transforms whose dense
  output would exceed ~2 GB and points at ``setNumFeatures``;
- IDF: log((N+1)/(df+1)) (Spark's exact formula) from a DOCUMENT-
  FREQUENCY monoid pass (per-partition presence-count sums — the same
  tree/psum reduction shape as every statistics pass in this package),
  with ``minDocFreq`` zeroing rare terms like Spark.
"""

from __future__ import annotations

import hashlib
from typing import Any

import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model, Transformer
from spark_rapids_ml_tpu.models.params import HasInputCol, HasOutputCol, Param
from spark_rapids_ml_tpu.utils import columnar


def _string_column(dataset: Any, col: str) -> list:
    """Raw values of a string/token column (the shared columnar dispatch;
    token arrays come back as lists/ndarrays of strings)."""
    try:
        import pyarrow as pa
    except ImportError:  # pragma: no cover
        pa = None
    if pa is not None and isinstance(dataset, (pa.Table, pa.RecordBatch)):
        return dataset.column(col).to_pylist()
    return list(columnar.extract_column_values(dataset, col))


def _bucket(term: str, num_features: int) -> int:
    """Stable non-negative term bucket (md5-derived — deterministic across
    processes and Python runs, unlike built-in str hashing)."""
    digest = hashlib.md5(term.encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "little") % num_features


class Tokenizer(HasInputCol, HasOutputCol, Transformer):
    """Lowercase + whitespace split (pyspark.ml.feature.Tokenizer)."""

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(outputCol="tokens")

    def transform(self, dataset: Any) -> Any:
        texts = _string_column(dataset, self.getOrDefault("inputCol"))
        tokens = [str(t).lower().split() for t in texts]
        return columnar.append_columns(
            dataset, [(self.getOutputCol(), np.asarray(tokens, dtype=object))]
        )


class HashingTF(HasInputCol, HasOutputCol, Transformer):
    numFeatures = Param("numFeatures", "hash bucket count", int)
    binary = Param(
        "binary", "presence flags instead of term counts", bool
    )

    #: dense-output guard: reject transforms whose [docs, numFeatures]
    #: float64 matrix would exceed this (the columnar layer is dense —
    #: Spark's sparse vectors don't pay this; lower numFeatures instead)
    _MAX_DENSE_BYTES = 2 << 30

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            numFeatures=1 << 18, binary=False, outputCol="tf_features"
        )

    def setNumFeatures(self, value: int) -> "HashingTF":
        if value < 1:
            raise ValueError(f"numFeatures must be >= 1, got {value}")
        return self._set(numFeatures=value)

    def getNumFeatures(self) -> int:
        return self.getOrDefault("numFeatures")

    def setBinary(self, value: bool) -> "HashingTF":
        return self._set(binary=bool(value))

    def transform(self, dataset: Any) -> Any:
        docs = _string_column(dataset, self.getOrDefault("inputCol"))
        nf = self.getNumFeatures()
        binary = self.getOrDefault("binary")
        need = len(docs) * nf * 8
        if need > self._MAX_DENSE_BYTES:
            raise ValueError(
                f"HashingTF dense output would be {need / 2**30:.1f} GiB "
                f"({len(docs)} docs x numFeatures={nf}); this package's "
                "columnar layer is dense — lower setNumFeatures (e.g. "
                "1<<14) for large corpora"
            )
        out = np.zeros((len(docs), nf), dtype=np.float64)
        for i, doc in enumerate(docs):
            if isinstance(doc, str):
                raise TypeError(
                    f"HashingTF input column holds raw strings, not token "
                    f"arrays — run Tokenizer first (got {doc[:30]!r})"
                )
            for term in doc:
                j = _bucket(str(term), nf)
                if binary:
                    out[i, j] = 1.0
                else:
                    out[i, j] += 1.0
        return columnar.append_columns(dataset, [(self.getOutputCol(), out)])


class IDF(HasInputCol, HasOutputCol, Estimator):
    minDocFreq = Param(
        "minDocFreq", "terms in fewer documents get IDF 0 (Spark)", int
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(minDocFreq=0, outputCol="tfidf_features")

    def setMinDocFreq(self, value: int) -> "IDF":
        if value < 0:
            raise ValueError(f"minDocFreq must be >= 0, got {value}")
        return self._set(minDocFreq=value)

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "IDFModel":
        ds = columnar.PartitionedDataset.from_any(
            dataset, self._paramMap.get("inputCol"), num_partitions
        )
        # document-frequency monoid: per-partition presence-count sums
        df = None
        n_docs = 0
        for mat in ds.matrices():
            part = (mat > 0).sum(axis=0).astype(np.float64)
            df = part if df is None else df + part
            n_docs += mat.shape[0]
        idf = np.log((n_docs + 1.0) / (df + 1.0))  # Spark's exact formula
        idf = np.where(df >= self.getOrDefault("minDocFreq"), idf, 0.0)
        model = IDFModel(uid=self.uid, idf=idf, docFreq=df, numDocs=n_docs)
        return self._copyValues(model)


class IDFModel(HasInputCol, HasOutputCol, Model):
    minDocFreq = IDF.minDocFreq

    def __init__(
        self,
        uid: str | None = None,
        idf: np.ndarray | None = None,
        docFreq: np.ndarray | None = None,
        numDocs: int = 0,
    ):
        super().__init__(uid)
        self.idf = None if idf is None else np.asarray(idf)
        self.docFreq = None if docFreq is None else np.asarray(docFreq)
        self.numDocs = int(numDocs)
        self._setDefault(minDocFreq=0, outputCol="tfidf_features")

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        if mat.shape[1] != self.idf.shape[0]:
            raise ValueError(
                f"input has {mat.shape[1]} features but the model was "
                f"fitted on {self.idf.shape[0]}"
            )
        return mat * self.idf[None, :]

    def transform(self, dataset: Any) -> Any:
        return columnar.apply_column_transform(
            dataset,
            self._paramMap.get("inputCol"),
            self.getOutputCol(),
            self._scale,
        )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "idf": self.idf,
            "docFreq": self.docFreq,
            "numDocs": np.asarray([self.numDocs]),
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid, idf=data["idf"], docFreq=data["docFreq"],
            numDocs=int(data["numDocs"][0]),
        )
