"""VarianceThresholdSelector — feature selection on the moments monoid.

Spark 3.1+ surface (``featuresCol``/``outputCol``/``varianceThreshold``,
default 0.0): keep features whose SAMPLE variance is strictly greater than
the threshold. The fit is the same one-pass distributed moments statistic
StandardScaler reduces (ops/scaler.py MomentStats), so selection costs one
data pass on any distribution; transform is a column gather.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import (
    HasFeaturesCol,
    HasOutputCol,
    Param,
)
from spark_rapids_ml_tpu.ops import scaler as S
from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

_moment_stats = jax.jit(S.moment_stats)
_finalize = jax.jit(S.finalize_moments)


def select_by_variance(variances: np.ndarray, threshold: float) -> np.ndarray:
    """variances -> sorted selected indices; raises when nothing survives —
    ONE rule shared by the local and Spark fit paths."""
    selected = np.flatnonzero(variances > threshold).astype(np.int32)
    if len(selected) == 0:
        raise ValueError(
            f"varianceThreshold={threshold} rejects every feature (max "
            f"sample variance {variances.max():.6g}); lower the threshold"
        )
    return selected


class _SelectorParams(HasFeaturesCol, HasOutputCol):
    varianceThreshold = Param(
        "varianceThreshold",
        "keep features with sample variance strictly greater than this",
        float,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(varianceThreshold=0.0, outputCol="selected_features")

    def getVarianceThreshold(self) -> float:
        return self.getOrDefault("varianceThreshold")


class VarianceThresholdSelector(_SelectorParams, Estimator):
    def setVarianceThreshold(self, value: float) -> "VarianceThresholdSelector":
        if value < 0:
            raise ValueError(f"varianceThreshold must be >= 0, got {value}")
        return self._set(varianceThreshold=float(value))

    def setFeaturesCol(self, value: str) -> "VarianceThresholdSelector":
        return self._set(featuresCol=value)

    def fit(
        self, dataset: Any, num_partitions: int | None = None
    ) -> "VarianceThresholdSelectorModel":
        features_col = self._paramMap.get("featuresCol")
        ds = columnar.PartitionedDataset.from_any(
            dataset, features_col, num_partitions
        )
        with trace_range("variance selector fit"):

            def task(mat):
                padded, true_rows = columnar.pad_rows(mat)
                st = _moment_stats(jnp.asarray(padded))
                return S.MomentStats(
                    jnp.asarray(true_rows, st.count.dtype),
                    st.total,
                    st.total_sq,
                )

            from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks

            partials = run_partition_tasks(task, list(ds.matrices()))
            stats = tree_reduce(partials, S.combine_moment_stats)
            _, std = _finalize(stats)
        selected = select_by_variance(
            np.asarray(std) ** 2, self.getVarianceThreshold()
        )
        model = VarianceThresholdSelectorModel(
            uid=self.uid, selectedFeatures=selected
        )
        return self._copyValues(model)


class VarianceThresholdSelectorModel(_SelectorParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        selectedFeatures: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.selectedFeatures = (
            None
            if selectedFeatures is None
            else np.asarray(selectedFeatures, dtype=np.int32)
        )

    def _select(self, mat: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(mat[:, self.selectedFeatures])

    def transform(self, dataset: Any) -> Any:
        with trace_range("variance selector transform"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("featuresCol"),
                self.getOutputCol(),
                self._select,
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"selectedFeatures": self.selectedFeatures}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, selectedFeatures=data["selectedFeatures"])

    # -- stock pyspark.ml interop: Row(selectedFeatures: array<int>) --------
    _SPARK_ML_CLASS = (
        "org.apache.spark.ml.feature.VarianceThresholdSelectorModel"
    )
    _SPARK_ML_PARAMS = ("varianceThreshold", "featuresCol", "outputCol")

    def _saveSparkML(self, path: str) -> None:
        import pyarrow as pa

        from spark_rapids_ml_tpu.models.base import spark_set_params
        from spark_rapids_ml_tpu.utils import persistence as P

        params = {
            k: v
            for k, v in spark_set_params(self).items()
            if k in self._SPARK_ML_PARAMS
        }
        P.save_spark_ml_metadata(
            path, class_name=self._SPARK_ML_CLASS, uid=self.uid, param_map=params
        )
        P.save_spark_ml_data(
            path,
            {
                "selectedFeatures": pa.array(
                    [self.selectedFeatures.tolist()], pa.list_(pa.int32())
                )
            },
            {
                "type": "struct",
                "fields": [
                    {
                        "name": "selectedFeatures",
                        "type": {
                            "type": "array",
                            "elementType": "integer",
                            "containsNull": False,
                        },
                        "nullable": True,
                        "metadata": {},
                    }
                ],
            },
        )

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "VarianceThresholdSelectorModel":
        return cls(
            uid=meta["uid"],
            selectedFeatures=np.asarray(
                table.column("selectedFeatures")[0].as_py(), dtype=np.int32
            ),
        )
