"""GBTRegressor / GBTClassifier — gradient-boosted trees on the forest kernels.

pyspark.ml ships GBTs (the spark-rapids-ml ecosystem points GBT users at
xgboost); this module completes the pyspark.ml tree surface natively,
REUSING the random-forest machinery end to end: the estimator inherits
`_ForestEstimator`'s param surface, setters, and labeled fit body; every
boosting stage is the same level-order histogram ``build_tree`` (variance
impurity — stages are regression trees on pseudo-residuals) with the same
heap-layout arrays, raw-threshold conversion, and persistence shape.

Spark MLlib semantics mirrored (GradientBoostedTrees.boost):

- the FIRST tree enters with weight 1.0 and no prior; every later stage
  contributes ``stepSize``·(leaf mean of pseudo-residuals) — the model
  exposes the resulting ``treeWeights`` like Spark's;
- regressor: squared loss, residuals y − F;
- classifier: Friedman's deviance with labels y∈{−1,1} and margin 2F —
  pseudo-residuals r = 2y/(1+exp(2yF)); rawPrediction = [−2F, 2F],
  probability = σ(2F), prediction = 1[F > 0] (the MLlib decision rule).
  DISCLOSED DIVERGENCE: Spark's LogLoss.gradient is −4y/(1+exp(2yF)), so
  its pseudo-residuals are exactly 2× the Friedman-scaled r used here.
  Each stage's leaf values absorb part of that scale (leaf mean of r), so
  ensemble *decisions* (sign of F) track Spark's, but margins — and hence
  probabilities — are NOT comparable to Spark's model-for-model; parity
  with Spark GBTClassifier holds at the decision level only (see the
  README "Parity divergences" table);
- ``featureSubsetStrategy`` 'auto' resolves to 'all' (Spark's GBT rule —
  each stage is a single tree; RF's sqrt/onethird heuristics don't apply);
- ``subsamplingRate`` draws a fresh Bernoulli row sample per STAGE
  (stochastic gradient boosting);
- boosting is inherently sequential, so the distributed story is
  per-stage: each tree build is the same histogram pass the forest uses
  (psum-able via the builder hook, parallel/forest.py); the driver loop
  carries only F [rows] between stages.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Model
from spark_rapids_ml_tpu.models.forest import (
    _ForestEstimator,
    _ForestParams,
    bin_features,
    quantile_bin_edges,
    split_thresholds,
    subset_size,
    tree_feature_importances,
)
from spark_rapids_ml_tpu.models.params import Param
from spark_rapids_ml_tpu.ops import forest as FO
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range


class _GBTParams(_ForestParams):
    stepSize = Param("stepSize", "learning rate per boosting stage", float)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        # Spark GBT defaults: maxIter stages of depth 5, lr 0.1, ALL
        # features per node (numTrees is RF vocabulary — GBT's stage count
        # param maxIter maps onto the shared numTrees storage)
        self._setDefault(
            stepSize=0.1, numTrees=20, featureSubsetStrategy="all",
            impurity="variance",
        )

    def setStepSize(self, value: float):
        if not 0.0 < value <= 1.0:
            raise ValueError(f"stepSize must be in (0, 1], got {value}")
        return self._set(stepSize=float(value))

    def getStepSize(self) -> float:
        return self.getOrDefault("stepSize")

    def setMaxIter(self, value: int):
        if value < 1:
            raise ValueError(f"maxIter must be >= 1, got {value}")
        return self._set(numTrees=value)

    def getMaxIter(self) -> int:
        return self.getOrDefault("numTrees")


class _GBTClassifierCols:
    """probability/rawPrediction columns — shared by GBTClassifier and its
    model (the forest's _ClassifierCols bundles an impurity default GBT
    must not inherit, hence the GBT-local twin)."""

    probabilityCol = Param("probabilityCol", "class-probability column", str)
    rawPredictionCol = Param(
        "rawPredictionCol", "margin column [−2F, 2F] (Spark GBT shape)", str
    )

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            probabilityCol="probability", rawPredictionCol="rawPrediction"
        )

    def setProbabilityCol(self, value: str):
        return self._set(probabilityCol=value)

    def setRawPredictionCol(self, value: str):
        return self._set(rawPredictionCol=value)


class _GBTEstimator(_GBTParams, _ForestEstimator):
    """Shares _ForestEstimator's setters and labeled ``fit`` body; the
    model build is the boosting loop instead of the vmapped forest."""

    impurity = Param("impurity", "'variance' (every stage is regression)", str)
    _impurity_choices = ("variance",)

    def _make_model(self, x, y, w):  # _ForestEstimator.fit's hook
        return self._boost(x, y, w)

    def _boost(self, x: np.ndarray, y: np.ndarray, w: np.ndarray | None):
        if self.getImpurity() != "variance":
            raise ValueError(
                "GBT stages are regression trees; impurity must be "
                f"'variance', got {self.getImpurity()!r}"
            )
        n_bins = self.getMaxBins()
        seed = self.getSeed()
        n_stages = self.getMaxIter()
        max_depth = self.getMaxDepth()
        lr = self.getStepSize()
        fdt = columnar.float_dtype_for(x.dtype)
        rng = np.random.default_rng(seed)

        edges = quantile_bin_edges(x, n_bins, seed, w)
        fdt = jax.dtypes.canonicalize_dtype(fdt)  # no x64-off warnings
        binned = jnp.asarray(bin_features(x, edges))
        rows = x.shape[0]
        base_w = np.ones(rows, fdt) if w is None else w.astype(fdt)
        yj = jnp.asarray(self._targets(y).astype(fdt))
        rate = self.getOrDefault("subsamplingRate")
        strategy = self.getOrDefault("featureSubsetStrategy")
        if str(strategy).lower() == "auto":
            strategy = "all"  # Spark's GBT rule (single tree per stage)
        k_feat = subset_size(strategy, x.shape[1], classification=False)
        static = dict(
            max_depth=max_depth, n_bins=n_bins, k_features=k_feat,
            impurity="variance",
        )
        min_inst = jnp.asarray(
            np.asarray(self.getOrDefault("minInstancesPerNode"), fdt)
        )
        min_gain = jnp.asarray(
            np.asarray(self.getOrDefault("minInfoGain"), fdt)
        )

        # MLlib boost schedule: first tree weight 1.0, later stages lr
        tree_weights = np.asarray(
            [1.0] + [lr] * (n_stages - 1), dtype=np.float64
        )
        F = jnp.zeros((rows,), fdt)
        trees, losses = [], []
        with trace_range("gbt boost"):
            for m in range(n_stages):
                r = self._pseudo_residuals(yj, F)
                stats = jnp.stack([jnp.ones_like(r), r, r * r], axis=1)
                stage_w = jnp.asarray(
                    base_w
                    * (
                        (rng.random(rows) < rate).astype(fdt)
                        if rate < 1.0
                        else 1.0
                    )
                )
                tree = FO.build_tree(
                    jax.random.fold_in(jax.random.PRNGKey(seed), m),
                    binned, stats, stage_w, min_inst, min_gain, **static,
                )
                leaf = FO.tree_apply_binned(tree, binned, max_depth=max_depth)
                # leaf mean over the SAMPLED rows that built the tree;
                # applied to every row routed there (Friedman)
                pred = leaf[:, 1] / jnp.where(leaf[:, 0] > 0, leaf[:, 0], 1.0)
                F = F + float(tree_weights[m]) * pred
                losses.append(float(self._loss(yj, F, jnp.asarray(base_w))))
                trees.append(FO.TreeArrays(*(np.asarray(a) for a in tree)))

        stacked = FO.TreeArrays(
            *(
                np.stack([getattr(t, f) for t in trees])
                for f in FO.TreeArrays._fields
            )
        )
        model = self._model_cls(
            uid=self.uid,
            trees=stacked,
            thresholds=split_thresholds(stacked, edges),
            treeWeights=tree_weights,
            numFeatures=x.shape[1],
            trainLosses=np.asarray(losses),
        )
        return self._copyValues(model)


class _GBTModel(_GBTParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        trees: FO.TreeArrays | None = None,
        thresholds: np.ndarray | None = None,
        treeWeights: np.ndarray | None = None,
        numFeatures: int = -1,
        trainLosses: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.trees = trees
        self.thresholds = (
            None if thresholds is None else np.asarray(thresholds)
        )
        #: per-stage weights ([1.0, lr, lr, ...] — Spark's treeWeights)
        self.treeWeights = (
            None if treeWeights is None else np.asarray(treeWeights)
        )
        self._num_features = int(numFeatures)
        #: per-stage training loss — Spark GBT's summary hook
        self.trainLosses = (
            None if trainLosses is None else np.asarray(trainLosses)
        )

    @property
    def numFeatures(self) -> int:
        return self._num_features

    @property
    def featureImportances(self) -> np.ndarray:
        """Impurity-based importances (Spark's GBT exposes the same
        TreeEnsembleModel recipe as the forest)."""
        return tree_feature_importances(self.trees, self._num_features)

    def getNumTrees(self) -> int:
        return self.trees.feature.shape[0]

    def _margins(self, mat: np.ndarray) -> np.ndarray:
        """[rows] additive prediction F(x) = Σ treeWeights·(leaf mean)."""
        max_depth = int(np.log2(self.trees.feature.shape[1] + 1) - 1)
        leaf = np.asarray(
            FO.forest_apply(
                FO.TreeArrays(*(jnp.asarray(a) for a in self.trees)),
                jnp.asarray(mat),
                jnp.asarray(self.thresholds),
                max_depth=max_depth,
            )
        )  # [T, rows, 3]
        pred = leaf[..., 1] / np.where(leaf[..., 0] > 0, leaf[..., 0], 1.0)
        return self.treeWeights @ pred

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "feature": self.trees.feature,
            "split_bin": self.trees.split_bin,
            "is_leaf": self.trees.is_leaf,
            "leaf_stats": self.trees.leaf_stats,
            "gain": self.trees.gain,
            "thresholds": self.thresholds,
            "treeWeights": self.treeWeights,
            "numFeatures": np.asarray([self._num_features]),
            "trainLosses": (
                self.trainLosses
                if self.trainLosses is not None
                else np.zeros(0)
            ),
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        trees = FO.TreeArrays(
            data["feature"].astype(np.int32),
            data["split_bin"].astype(np.int32),
            data["is_leaf"].astype(bool),
            data["leaf_stats"],
            data["gain"],
        )
        return cls(
            uid=uid, trees=trees, thresholds=data["thresholds"],
            treeWeights=data["treeWeights"],
            numFeatures=int(data["numFeatures"][0]),
            trainLosses=data["trainLosses"],
        )


# ---------------------------------------------------------------------------
# Regressor
# ---------------------------------------------------------------------------


class GBTRegressor(_GBTEstimator):
    _classification = False

    def _targets(self, y: np.ndarray) -> np.ndarray:
        return np.asarray(y, dtype=np.float64)

    def _row_stats(self, y, fdt):  # pragma: no cover - forest hook unused
        raise NotImplementedError("GBT builds per-stage residual stats")

    @staticmethod
    def _pseudo_residuals(y, F):
        return y - F  # squared loss

    @staticmethod
    def _loss(y, F, w):
        return jnp.sum(w * (y - F) ** 2) / jnp.sum(w)

    @property
    def _model_cls(self):
        return GBTRegressionModel


class GBTRegressionModel(_GBTModel):
    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        return self._margins(mat)

    def transform(self, dataset: Any) -> Any:
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )

    def predict(self, row) -> float:
        return float(
            self._predict_matrix(np.asarray(row, dtype=np.float64)[None, :])[0]
        )


# ---------------------------------------------------------------------------
# Classifier
# ---------------------------------------------------------------------------


class GBTClassifier(_GBTClassifierCols, _GBTEstimator):
    _classification = True

    def _targets(self, y: np.ndarray) -> np.ndarray:
        classes = np.unique(y)
        if not np.all(np.isin(classes, (0.0, 1.0))):
            raise ValueError(
                f"GBTClassifier requires binary 0/1 labels, got {classes[:8]}"
            )
        return 2.0 * np.asarray(y, dtype=np.float64) - 1.0  # ±1

    def _row_stats(self, y, fdt):  # pragma: no cover - forest hook unused
        raise NotImplementedError("GBT builds per-stage residual stats")

    @staticmethod
    def _pseudo_residuals(y, F):
        # −∂/∂F log(1+exp(−2yF)) = 2y / (1+exp(2yF)) — Friedman's scaling.
        # Spark's LogLoss.gradient uses margin 2F in the chain rule and
        # lands on 4y/(1+exp(2yF)): 2× these residuals. Decision parity
        # survives (sign of F is scale-free); margin/probability parity
        # does not — disclosed in the module docstring and README table.
        return 2.0 * y / (1.0 + jnp.exp(2.0 * y * F))

    @staticmethod
    def _loss(y, F, w):
        # logistic (deviance) loss, logaddexp for stability
        return jnp.sum(w * jnp.logaddexp(0.0, -2.0 * y * F)) / jnp.sum(w)

    @property
    def _model_cls(self):
        return GBTClassificationModel


class GBTClassificationModel(_GBTClassifierCols, _GBTModel):
    @property
    def numClasses(self) -> int:
        return 2

    def proba_and_predictions(self, mat: np.ndarray):
        from scipy.special import expit  # overflow-free sigmoid

        F = self._margins(mat)
        p1 = expit(2.0 * F)
        proba = np.stack([1.0 - p1, p1], axis=1)
        return proba, (F > 0).astype(np.float64)

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        return self.proba_and_predictions(mat)[1]

    def transform(self, dataset: Any) -> Any:
        if columnar.has_named_columns(dataset):
            mat = columnar.extract_matrix(
                dataset, self.getOrDefault("featuresCol")
            )
            from scipy.special import expit

            F = self._margins(mat)
            raw = np.stack([-2.0 * F, 2.0 * F], axis=1)
            p1 = expit(2.0 * F)
            proba = np.stack([1.0 - p1, p1], axis=1)
            return columnar.append_columns(
                dataset,
                [
                    (self.getOrDefault("rawPredictionCol"), raw),
                    (self.getOrDefault("probabilityCol"), proba),
                    (
                        self.getOrDefault("predictionCol"),
                        (F > 0).astype(np.float64),
                    ),
                ],
            )
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )

    def predict(self, row) -> float:
        return float(
            self._predict_matrix(np.asarray(row, dtype=np.float64)[None, :])[0]
        )
