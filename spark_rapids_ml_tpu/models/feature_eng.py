"""Column-level feature engineering — VectorAssembler, StringIndexer,
OneHotEncoder (the pyspark.ml stages that turn raw tabular DataFrames
into the ArrayType features column every estimator here consumes).

These are host-side column transforms, not accelerator math — they exist
so a Pipeline can start from raw columns exactly as it would in
pyspark.ml. Spark semantics mirrored:

- VectorAssembler: concatenate scalar and array columns in declared
  order; ``handleInvalid`` 'error' (default) raises on NaN, 'keep'
  passes NaN through (Spark's contract minus null rows, which the
  columnar layer has no representation for);
- StringIndexer: ``stringOrderType`` frequencyDesc (default — ties
  broken alphabetically, Spark's rule) / frequencyAsc / alphabetDesc /
  alphabetAsc; ``handleInvalid`` 'error' or 'keep' (unseen → numLabels);
- OneHotEncoder: index column(s) → one-hot arrays, ``dropLast`` True by
  default (Spark's reference-category convention); category sizes are
  learned at fit.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model, Transformer
from spark_rapids_ml_tpu.models.params import HasInputCol, HasOutputCol, Param
from spark_rapids_ml_tpu.utils import columnar


#: shared column extraction (moved to utils/columnar so the text stages
#: use the same dispatch)
_column_values = columnar.extract_column_values


class VectorAssembler(HasOutputCol, Transformer):
    inputCols = Param("inputCols", "columns to concatenate, in order", list)
    handleInvalid = Param(
        "handleInvalid", "'error' (default) or 'keep' for NaN values", str
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(outputCol="features", handleInvalid="error")

    def setInputCols(self, value) -> "VectorAssembler":
        return self._set(inputCols=list(value))

    def getInputCols(self) -> list:
        return self.getOrDefault("inputCols")

    def setHandleInvalid(self, value: str) -> "VectorAssembler":
        if value not in ("error", "keep"):
            raise ValueError(
                f"handleInvalid must be 'error' or 'keep', got {value!r}"
            )
        return self._set(handleInvalid=value)

    def transform(self, dataset: Any) -> Any:
        cols = self.getInputCols()
        pieces = []
        for c in cols:
            v = _column_values(dataset, c)
            v = np.asarray(v, dtype=np.float64)
            pieces.append(v[:, None] if v.ndim == 1 else v)
        out = np.concatenate(pieces, axis=1)
        # Spark errors on NaN (null) only — Infinity is a legal Double
        if self.getOrDefault("handleInvalid") == "error" and np.isnan(
            out
        ).any():
            bad = [c for c, p in zip(cols, pieces) if np.isnan(p).any()]
            raise ValueError(
                f"VectorAssembler found NaN in columns {bad}; set "
                "handleInvalid='keep' to pass them through"
            )
        return columnar.append_columns(dataset, [(self.getOutputCol(), out)])


class StringIndexer(HasInputCol, HasOutputCol, Estimator):
    stringOrderType = Param(
        "stringOrderType",
        "'frequencyDesc' (default; ties alphabetical — Spark's rule), "
        "'frequencyAsc', 'alphabetAsc', or 'alphabetDesc'",
        str,
    )
    handleInvalid = Param(
        "handleInvalid",
        "'error' (default) or 'keep' (unseen labels → index numLabels)",
        str,
    )

    _ORDERS = ("frequencyDesc", "frequencyAsc", "alphabetAsc", "alphabetDesc")

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            stringOrderType="frequencyDesc", handleInvalid="error"
        )

    def setStringOrderType(self, value: str) -> "StringIndexer":
        if value not in self._ORDERS:
            raise ValueError(
                f"stringOrderType must be one of {self._ORDERS}, got {value!r}"
            )
        return self._set(stringOrderType=value)

    def setHandleInvalid(self, value: str) -> "StringIndexer":
        if value not in ("error", "keep"):
            raise ValueError(
                f"handleInvalid must be 'error' or 'keep', got {value!r}"
            )
        return self._set(handleInvalid=value)

    def fit(self, dataset: Any) -> "StringIndexerModel":
        values = _column_values(dataset, self.getOrDefault("inputCol"))
        strings = np.asarray([str(v) for v in values])
        uniq, counts = np.unique(strings, return_counts=True)
        order = self.getOrDefault("stringOrderType")
        if order == "frequencyDesc":
            # np.lexsort: last key is primary — frequency desc, ties by
            # value ascending (Spark's tie rule)
            idx = np.lexsort((uniq, -counts))
        elif order == "frequencyAsc":
            idx = np.lexsort((uniq, counts))
        elif order == "alphabetAsc":
            idx = np.argsort(uniq)
        else:  # alphabetDesc
            idx = np.argsort(uniq)[::-1]
        model = StringIndexerModel(uid=self.uid, labels=list(uniq[idx]))
        return self._copyValues(model)


class StringIndexerModel(HasInputCol, HasOutputCol, Model):
    stringOrderType = StringIndexer.stringOrderType
    handleInvalid = StringIndexer.handleInvalid

    def __init__(self, uid: str | None = None, labels: list | None = None):
        super().__init__(uid)
        self.labels = list(labels or [])
        self._setDefault(
            stringOrderType="frequencyDesc", handleInvalid="error"
        )

    def setHandleInvalid(self, value: str) -> "StringIndexerModel":
        if value not in ("error", "keep"):
            raise ValueError(
                f"handleInvalid must be 'error' or 'keep', got {value!r}"
            )
        return self._set(handleInvalid=value)

    def transform(self, dataset: Any) -> Any:
        values = _column_values(dataset, self.getOrDefault("inputCol"))
        strings = np.asarray([str(v) for v in values])
        # vectorized lookup: searchsorted over the sorted label table (the
        # transform hot path stays free of per-row Python dict probing)
        labels = np.asarray(self.labels)
        sort_idx = np.argsort(labels)
        sorted_labels = labels[sort_idx]
        pos = np.searchsorted(sorted_labels, strings)
        pos_c = np.clip(pos, 0, len(labels) - 1)
        found = sorted_labels[pos_c] == strings
        if len(labels) == 0:
            found = np.zeros(len(strings), dtype=bool)
        if not found.all():
            if self.getOrDefault("handleInvalid") != "keep":
                bad = str(strings[~found][0])
                raise ValueError(
                    f"StringIndexer met unseen label {bad!r}; set "
                    "handleInvalid='keep' to index it as numLabels"
                )
        out = np.where(
            found,
            sort_idx[pos_c].astype(np.float64),
            float(len(labels)),
        )
        return columnar.append_columns(dataset, [(self.getOutputCol(), out)])

    def _saveData(self) -> dict[str, np.ndarray]:
        # explicit UTF-8: numpy's U->S cast is ASCII-only and would raise
        # mid-save (after the base layer already cleared an overwrite)
        return {
            "labels": np.asarray(
                [lab.encode("utf-8") for lab in self.labels], dtype=object
            ).astype("S")
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            labels=[v.decode("utf-8") for v in data["labels"].tolist()],
        )


class OneHotEncoder(HasInputCol, HasOutputCol, Estimator):
    dropLast = Param(
        "dropLast", "drop the last category (Spark's default)", bool
    )
    handleInvalid = Param(
        "handleInvalid",
        "'error' (default) or 'keep' (out-of-range → all-zero / extra slot)",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(dropLast=True, handleInvalid="error")

    def setDropLast(self, value: bool) -> "OneHotEncoder":
        return self._set(dropLast=bool(value))

    def setHandleInvalid(self, value: str) -> "OneHotEncoder":
        if value not in ("error", "keep"):
            raise ValueError(
                f"handleInvalid must be 'error' or 'keep', got {value!r}"
            )
        return self._set(handleInvalid=value)

    def fit(self, dataset: Any) -> "OneHotEncoderModel":
        v = np.asarray(
            _column_values(dataset, self.getOrDefault("inputCol")),
            dtype=np.float64,
        )
        if (v < 0).any() or not np.all(v == np.round(v)):
            raise ValueError(
                "OneHotEncoder requires non-negative integer indices"
            )
        model = OneHotEncoderModel(
            uid=self.uid, categorySize=int(v.max()) + 1
        )
        return self._copyValues(model)


class OneHotEncoderModel(HasInputCol, HasOutputCol, Model):
    dropLast = OneHotEncoder.dropLast
    handleInvalid = OneHotEncoder.handleInvalid

    def __init__(self, uid: str | None = None, categorySize: int = 0):
        super().__init__(uid)
        self.categorySize = int(categorySize)
        self._setDefault(dropLast=True, handleInvalid="error")

    def setDropLast(self, value: bool) -> "OneHotEncoderModel":
        return self._set(dropLast=bool(value))

    def setHandleInvalid(self, value: str) -> "OneHotEncoderModel":
        if value not in ("error", "keep"):
            raise ValueError(
                f"handleInvalid must be 'error' or 'keep', got {value!r}"
            )
        return self._set(handleInvalid=value)

    def transform(self, dataset: Any) -> Any:
        v = np.asarray(
            _column_values(dataset, self.getOrDefault("inputCol")),
            dtype=np.float64,
        ).astype(np.int64)
        keep = self.getOrDefault("handleInvalid") == "keep"
        size = self.categorySize + (1 if keep else 0)
        width = size - (1 if self.getOrDefault("dropLast") else 0)
        if not keep and ((v < 0) | (v >= self.categorySize)).any():
            raise ValueError(
                f"OneHotEncoder met index outside [0, {self.categorySize}); "
                "set handleInvalid='keep' to map it to the extra slot"
            )
        v = np.where((v < 0) | (v >= self.categorySize), self.categorySize, v)
        out = np.zeros((len(v), width), dtype=np.float64)
        in_range = v < width
        out[np.flatnonzero(in_range), v[in_range]] = 1.0
        return columnar.append_columns(dataset, [(self.getOutputCol(), out)])

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"categorySize": np.asarray([self.categorySize])}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, categorySize=int(data["categorySize"][0]))


class IndexToString(HasInputCol, HasOutputCol, Transformer):
    """The StringIndexer inverse (pyspark.ml.feature.IndexToString): map a
    numeric index column back to labels — typically a model's prediction
    column back to the original categories."""

    labels = Param("labels", "index → label table (required)", list)

    def setLabels(self, value) -> "IndexToString":
        value = [str(v) for v in value]
        if not value:
            raise ValueError("labels must be non-empty")
        return self._set(labels=value)

    def getLabels(self) -> list:
        return self.getOrDefault("labels")

    def transform(self, dataset: Any) -> Any:
        if "labels" not in self._paramMap:
            raise ValueError("setLabels([...]) before transform")
        labels = np.asarray(self.getLabels())
        idx = np.asarray(
            _column_values(dataset, self.getOrDefault("inputCol")),
            dtype=np.float64,
        ).astype(np.int64)
        if ((idx < 0) | (idx >= len(labels))).any():
            bad = int(idx[(idx < 0) | (idx >= len(labels))][0])
            raise ValueError(
                f"index {bad} outside the label table of size {len(labels)}"
            )
        return columnar.append_columns(
            dataset, [(self.getOutputCol(), labels[idx])]
        )
