"""Incremental (streaming) fits — partial_fit/finalize over the stats monoids.

The framework's fits are all "accumulate a commutative-monoid statistic,
then one small solve" (docs/ARCHITECTURE.md §2). That structure gives
streaming fits for free: ``partial_fit(batch)`` folds a batch into the
running statistic on device, ``finalize()`` runs the decomposition and
returns the same fitted model the one-shot estimator produces — bit-for-bit
when the batch concatenation equals the one-shot input, because the monoid
combine is exactly the cross-partition reduction the batch path uses.

This is a capability the reference lacks (its fit is a single two-phase
job, SURVEY.md §3.1) and the sklearn ``IncrementalPCA`` shape users expect
for data that arrives in chunks or exceeds host memory.

Accumulator memory is O(model²) regardless of stream length: [n, n] for
PCA, [n, n] for TruncatedSVD's Gram route (or [n, n] R for the svd route),
[n] for the scaler.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.pca import (
    PCA,
    PCAModel,
    _combine_r,
    _fit_from_stats_jit,
    _qr_r,
    _svd_from_r_jit,
)
from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel
from spark_rapids_ml_tpu.models.params import Param
from spark_rapids_ml_tpu.models.linear import (
    LinearRegression,
    LinearRegressionModel,
    _solve_from_stats,
)
from spark_rapids_ml_tpu.models.scaler import (
    StandardScaler,
    StandardScalerModel,
)
from spark_rapids_ml_tpu.models.truncated_svd import (
    TruncatedSVD,
    TruncatedSVDModel,
    _decompose_gram_jit,
    _svd_values_from_r_jit,
)
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.ops import scaler as S
from spark_rapids_ml_tpu.utils import columnar

from spark_rapids_ml_tpu.ops import linear as LIN

# partial_fit accumulation rides the streamed-fit donated fold steps
# (ops.linalg.gram_fold_step rationale): the carry updates in place on
# device — no per-batch [n, n] realloc — and the dispatch returns before
# the fold completes, so the caller's next batch extraction overlaps the
# device work for free.

# Durable state: every incremental estimator round-trips its carry through
# ``to_state() -> (arrays, scalars)`` / ``from_state(arrays, scalars)``,
# the (npz, json) shape utils.checkpoint.TrainingCheckpointer persists
# atomically. The carries are exact sufficient statistics, so a
# save/restore mid-stream resumes BITWISE-identically: the restored fold
# sequence produces the same finalize() as the uninterrupted one (the
# refresh daemon's restart-survival contract, asserted in tests).


def _check_state_kind(est, state: dict) -> None:
    kind = state.get("kind")
    if kind != type(est).__name__:
        raise ValueError(
            f"checkpoint state is for {kind!r}, not {type(est).__name__}"
        )


def _as_matrix(est, batch: Any) -> np.ndarray:
    """Extract the batch matrix AND pin/verify the stream's feature width."""
    input_col = est._paramMap.get("inputCol")
    mat = columnar.extract_matrix(batch, input_col)
    if est._n_cols is None:
        est._n_cols = mat.shape[1]
    elif mat.shape[1] != est._n_cols:
        raise ValueError(
            f"inconsistent feature dim: {mat.shape[1]} != {est._n_cols}"
        )
    return mat


def _pin_solver(est) -> str:
    """The accumulator layout depends on the solver route; switching solvers
    mid-stream would silently orphan the batches accumulated under the other
    route. Pin it at the first partial_fit."""
    solver = est.getOrDefault("solver")
    pinned = getattr(est, "_solver_used", None)
    if pinned is None:
        est._solver_used = solver
    elif solver != pinned:
        raise ValueError(
            f"solver changed mid-stream ({pinned!r} -> {solver!r}); "
            "reset() before switching solvers"
        )
    return solver


class IncrementalPCA(PCA):
    """PCA fitted by streaming batches.

    >>> inc = IncrementalPCA().setK(4)
    >>> for chunk in stream:
    ...     inc.partial_fit(chunk)
    >>> model = inc.finalize()

    ``fit`` still works (one-shot, inherited). The running statistic is the
    same ``GramStats`` triple the batch fit reduces, so
    ``partial_fit(a); partial_fit(b); finalize()`` ==
    ``fit(concat(a, b))`` for every solver.
    """

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._acc = None
        self._r_acc = None
        self._n_cols: int | None = None
        self._rows_seen = 0

    @property
    def n_rows_seen(self) -> int:
        if self._acc is not None:
            return int(np.asarray(self._acc.count))
        return self._rows_seen if self._r_acc is not None else 0

    def partial_fit(self, batch: Any) -> "IncrementalPCA":
        mat = _as_matrix(self, batch)
        solver = _pin_solver(self)
        padded, true_rows = columnar.pad_rows(mat)
        if solver == "svd":
            if self.getMeanCentering():
                raise ValueError(
                    "solver='svd' with meanCentering needs the global mean "
                    "before any QR; use the gram-route solvers for "
                    "incremental centered fits"
                )
            r = _qr_r(jnp.asarray(padded))
            self._r_acc = r if self._r_acc is None else _combine_r(self._r_acc, r)
            self._rows_seen = getattr(self, "_rows_seen", 0) + len(mat)
            return self
        prec = L.PRECISIONS[self.getOrDefault("precision")]
        xj = jnp.asarray(padded)
        wp = np.zeros(padded.shape[0], padded.dtype)
        wp[:true_rows] = 1.0  # pad mask doubles as the exact count
        if self._acc is None:
            self._acc = L.init_gram_carry(xj.shape[1], xj.dtype)
        self._acc = L.gram_fold_step(prec)(self._acc, xj, jnp.asarray(wp))
        return self

    def finalize(self) -> PCAModel:
        k = self.getK()
        if self._n_cols is not None and k > self._n_cols:
            raise ValueError(f"k={k} must be <= number of features {self._n_cols}")
        if self._acc is not None or self._r_acc is not None:
            _pin_solver(self)  # a solver switch after the last batch is
            # the same mistake as mid-stream — same clear error
        if self._r_acc is not None:
            pc, explained = _svd_from_r_jit(self._r_acc, k)
        elif self._acc is not None:
            pc, explained = _fit_from_stats_jit(
                self._acc, k, self.getMeanCentering(), self._solver_used
            )
        else:
            raise ValueError("finalize() before any partial_fit()")
        model = PCAModel(
            uid=self.uid,
            pc=np.asarray(pc),
            explainedVariance=np.asarray(explained),
        )
        return self._copyValues(model)

    def reset(self) -> "IncrementalPCA":
        self._acc = self._r_acc = self._n_cols = self._solver_used = None
        self._rows_seen = 0
        return self

    def to_state(self) -> tuple[dict[str, np.ndarray], dict]:
        arrays: dict[str, np.ndarray] = {}
        if self._acc is not None:
            arrays["gram_xtx"] = np.asarray(self._acc.xtx)
            arrays["gram_col_sum"] = np.asarray(self._acc.col_sum)
            arrays["gram_count"] = np.asarray(self._acc.count)
        if self._r_acc is not None:
            arrays["r_acc"] = np.asarray(self._r_acc)
        return arrays, {
            "kind": type(self).__name__,
            "n_cols": self._n_cols,
            "rows_seen": int(self._rows_seen),
            "solver_used": getattr(self, "_solver_used", None),
        }

    def from_state(
        self, arrays: dict[str, np.ndarray], state: dict
    ) -> "IncrementalPCA":
        _check_state_kind(self, state)
        self.reset()
        if "gram_xtx" in arrays:
            self._acc = L.GramStats(
                jnp.asarray(arrays["gram_xtx"]),
                jnp.asarray(arrays["gram_col_sum"]),
                jnp.asarray(arrays["gram_count"]),
            )
        if "r_acc" in arrays:
            self._r_acc = jnp.asarray(arrays["r_acc"])
        self._n_cols = state.get("n_cols")
        self._rows_seen = int(state.get("rows_seen", 0))
        if state.get("solver_used") is not None:
            self._solver_used = state["solver_used"]
        return self


class IncrementalTruncatedSVD(TruncatedSVD):
    """TruncatedSVD fitted by streaming batches (gram or svd route)."""

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._gram = None
        self._r_acc = None
        self._n_cols: int | None = None

    def partial_fit(self, batch: Any) -> "IncrementalTruncatedSVD":
        mat = _as_matrix(self, batch)
        padded, _ = columnar.pad_rows(mat)
        if _pin_solver(self) == "svd":
            r = _qr_r(jnp.asarray(padded))
            self._r_acc = r if self._r_acc is None else _combine_r(self._r_acc, r)
        else:
            prec = L.PRECISIONS[self.getOrDefault("precision")]
            xj = jnp.asarray(padded)
            if self._gram is None:
                self._gram = jnp.zeros((xj.shape[1], xj.shape[1]), xj.dtype)
            self._gram = L.gram_fold_xtx_step(prec)(self._gram, xj)
        return self

    def finalize(self) -> TruncatedSVDModel:
        k = self.getK()
        if self._n_cols is not None and k > self._n_cols:
            raise ValueError(f"k={k} must be <= number of features {self._n_cols}")
        if self._gram is not None or self._r_acc is not None:
            _pin_solver(self)
        if self._r_acc is not None:
            components, s = _svd_values_from_r_jit(self._r_acc, k)
        elif self._gram is not None:
            components, s = _decompose_gram_jit(self._gram, k, self._solver_used)
        else:
            raise ValueError("finalize() before any partial_fit()")
        model = TruncatedSVDModel(
            uid=self.uid,
            components=np.asarray(components),
            singularValues=np.asarray(s[:k]),
        )
        return self._copyValues(model)

    def reset(self) -> "IncrementalTruncatedSVD":
        self._gram = self._r_acc = self._n_cols = self._solver_used = None
        return self

    def to_state(self) -> tuple[dict[str, np.ndarray], dict]:
        arrays: dict[str, np.ndarray] = {}
        if self._gram is not None:
            arrays["gram"] = np.asarray(self._gram)
        if self._r_acc is not None:
            arrays["r_acc"] = np.asarray(self._r_acc)
        return arrays, {
            "kind": type(self).__name__,
            "n_cols": self._n_cols,
            "solver_used": getattr(self, "_solver_used", None),
        }

    def from_state(
        self, arrays: dict[str, np.ndarray], state: dict
    ) -> "IncrementalTruncatedSVD":
        _check_state_kind(self, state)
        self.reset()
        if "gram" in arrays:
            self._gram = jnp.asarray(arrays["gram"])
        if "r_acc" in arrays:
            self._r_acc = jnp.asarray(arrays["r_acc"])
        self._n_cols = state.get("n_cols")
        if state.get("solver_used") is not None:
            self._solver_used = state["solver_used"]
        return self


class IncrementalStandardScaler(StandardScaler):
    """StandardScaler fitted by streaming batches."""

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._acc = None
        self._n_cols: int | None = None

    def partial_fit(self, batch: Any) -> "IncrementalStandardScaler":
        mat = _as_matrix(self, batch)
        padded, true_rows = columnar.pad_rows(mat)
        xj = jnp.asarray(padded)
        wp = np.zeros(padded.shape[0], padded.dtype)
        wp[:true_rows] = 1.0
        if self._acc is None:
            self._acc = S.init_moment_carry(xj.shape[1], xj.dtype)
        self._acc = S.moment_fold_step()(self._acc, xj, jnp.asarray(wp))
        return self

    def finalize(self) -> StandardScalerModel:
        if self._acc is None:
            raise ValueError("finalize() before any partial_fit()")
        mean, std = S.finalize_moments(self._acc)
        model = StandardScalerModel(
            uid=self.uid, mean=np.asarray(mean), std=np.asarray(std)
        )
        return self._copyValues(model)

    def reset(self) -> "IncrementalStandardScaler":
        self._acc = self._n_cols = None
        return self

    def to_state(self) -> tuple[dict[str, np.ndarray], dict]:
        arrays: dict[str, np.ndarray] = {}
        if self._acc is not None:
            arrays["moment_count"] = np.asarray(self._acc.count)
            arrays["moment_total"] = np.asarray(self._acc.total)
            arrays["moment_total_sq"] = np.asarray(self._acc.total_sq)
        return arrays, {"kind": type(self).__name__, "n_cols": self._n_cols}

    def from_state(
        self, arrays: dict[str, np.ndarray], state: dict
    ) -> "IncrementalStandardScaler":
        _check_state_kind(self, state)
        self.reset()
        if "moment_count" in arrays:
            self._acc = S.MomentStats(
                jnp.asarray(arrays["moment_count"]),
                jnp.asarray(arrays["moment_total"]),
                jnp.asarray(arrays["moment_total_sq"]),
            )
        self._n_cols = state.get("n_cols")
        return self


class IncrementalLinearRegression(LinearRegression):
    """LinearRegression fitted by streaming labeled batches.

    The running statistic is the same ``LinearStats`` monoid the batch fit
    reduces (XᵀX, Xᵀy, Σx, Σy, Σy², m — O(n²) memory regardless of stream
    length), so ``partial_fit(a); partial_fit(b); finalize()`` ==
    ``fit(concat(a, b))`` — including the elastic-net solvers, which run on
    the reduced statistics only. Batches are anything the one-shot fit
    accepts: an ``(X, y)`` / ``(X, y, w)`` tuple or a DataFrame carrying
    ``featuresCol``/``labelCol`` (and ``weightCol``).
    """

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._acc = None
        self._n_cols: int | None = None
        self._rows_seen = 0

    @property
    def n_rows_seen(self) -> int:
        # tracked separately from the monoid: LinearStats.count is the
        # WEIGHT sum, which differs from the row count on weighted streams
        return self._rows_seen

    def partial_fit(self, batch: Any) -> "IncrementalLinearRegression":
        parts = self._labeled(batch, 1)
        for x, y, sw in parts:
            if self._n_cols is None:
                self._n_cols = x.shape[1]
            elif x.shape[1] != self._n_cols:
                raise ValueError(
                    f"inconsistent feature dim: {x.shape[1]} != {self._n_cols}"
                )
            xp, yp, w = columnar.pad_labeled(x, y, sw)
            xj = jnp.asarray(xp)
            if self._acc is None:
                self._acc = LIN.init_linear_carry(xj.shape[1], xj.dtype)
            self._acc = LIN.linear_fold_step()(
                self._acc, xj, jnp.asarray(yp), jnp.asarray(w)
            )
            self._rows_seen += x.shape[0]
        return self

    def finalize(self):
        if self._acc is None:
            raise ValueError("finalize() before any partial_fit()")
        coef, intercept = _solve_from_stats(self._acc, **self._solve_args())
        model = LinearRegressionModel(
            uid=self.uid,
            coefficients=np.asarray(coef),
            intercept=float(intercept),
        )
        return self._copyValues(model)

    def reset(self) -> "IncrementalLinearRegression":
        self._acc = self._n_cols = None
        self._rows_seen = 0
        return self

    def to_state(self) -> tuple[dict[str, np.ndarray], dict]:
        arrays: dict[str, np.ndarray] = {}
        if self._acc is not None:
            for fld, value in zip(self._acc._fields, self._acc):
                arrays[f"linear_{fld}"] = np.asarray(value)
        return arrays, {
            "kind": type(self).__name__,
            "n_cols": self._n_cols,
            "rows_seen": int(self._rows_seen),
        }

    def from_state(
        self, arrays: dict[str, np.ndarray], state: dict
    ) -> "IncrementalLinearRegression":
        _check_state_kind(self, state)
        self.reset()
        if "linear_xtx" in arrays:
            self._acc = LIN.LinearStats(
                *(
                    jnp.asarray(arrays[f"linear_{fld}"])
                    for fld in LIN.LinearStats._fields
                )
            )
        self._n_cols = state.get("n_cols")
        self._rows_seen = int(state.get("rows_seen", 0))
        return self


class IncrementalKMeans(KMeans):
    """Mini-batch KMeans fitted by streaming batches (Sculley, WWW'10 —
    the ``sklearn.cluster.MiniBatchKMeans`` shape).

    Unlike the monoid streamers above, Lloyd is iterative, so streaming
    CANNOT equal the one-shot fit; the honest contract is the mini-batch
    one: each ``partial_fit(batch)`` runs one weighted assignment pass
    (the same blocked-MXU ``kmeans_stats`` kernel every other path uses)
    and a per-center ONLINE-MEAN update — center c moves with step size
    1/n_c where n_c is its cumulative assigned weight, Sculley's
    per-center learning rate. Memory is O(k·n) regardless of stream
    length.

    Seeding: rows buffer host-side until ``max(k, seedRows)`` arrive,
    then the buffer seeds k centers and replays as the first mini-batch.
    ``initMode`` semantics on a stream: ``'random'`` draws k uniform
    positive-weight buffered rows; ``'k-means||'`` (the inherited
    default) and ``'k-means++'`` both run k-means++ on the buffer — the
    buffer plays the oversampled-candidate role the distributed rounds
    play in the batch fit. A stream that ends before the threshold still
    finalizes: ``finalize()`` seeds from whatever is buffered when it
    holds at least k positive-weight rows. ``finalize()`` returns a
    normal :class:`KMeansModel`; its ``trainingCost`` is the LAST batch's
    assignment cost (a streaming proxy — there is no full-dataset pass to
    measure true inertia on).

    Stream-order caveat (inherent to mini-batch k-means, not this
    implementation): a cluster-sorted stream seeds from whatever cluster
    arrives first, and the 1/n_c rate then migrates centers only slowly.
    Shuffle the stream, or raise ``seedRows`` past the sorted prefix.
    """

    seedRows = Param(
        "seedRows", "rows buffered before k-means++ seeding", int
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(seedRows=4096)
        self._centers = None       # jnp [k, n]
        self._cum_weights = None   # jnp [k]
        self._n_cols: int | None = None
        self._rows_seen = 0
        self._last_cost = float("nan")
        self._seed_rows: list[np.ndarray] = []
        self._seed_weights: list[np.ndarray] = []

    @property
    def n_rows_seen(self) -> int:
        return self._rows_seen

    def _batch_arrays(self, batch: Any, sample_weight):
        mat = _as_matrix(self, batch)
        w = None
        if sample_weight is not None:
            w = columnar.validate_weights(
                sample_weight, len(mat), allow_all_zero=True
            )
        else:
            weight_col = self._paramMap.get("weightCol")
            if weight_col:
                w = columnar.validate_weights(
                    columnar.extract_vector(batch, weight_col),
                    len(mat),
                    allow_all_zero=True,
                )
        return mat, (np.ones(len(mat)) if w is None else w)

    def partial_fit(
        self, batch: Any, sample_weight=None
    ) -> "IncrementalKMeans":
        from spark_rapids_ml_tpu.ops import kmeans as KM

        mat, w = self._batch_arrays(batch, sample_weight)
        self._rows_seen += len(mat)
        if self._centers is None:
            self._seed_rows.append(mat)
            self._seed_weights.append(w)
            buffered = sum(len(m) for m in self._seed_rows)
            if buffered < max(self.getK(), self.getOrDefault("seedRows")):
                return self  # keep buffering
            mat, w = self._seed_from_buffer()
            # fall through: the seed buffer replays as the first mini-batch
        xp, true_rows = columnar.pad_rows(mat)
        wp = np.zeros(xp.shape[0])
        wp[:true_rows] = w  # pad rows carry weight 0: excluded exactly
        stats = KM.kmeans_stats(
            jnp.asarray(xp), self._centers, jnp.asarray(wp)
        )
        self._centers, self._cum_weights = _minibatch_center_update(
            self._centers, self._cum_weights, stats.sums, stats.counts
        )
        self._last_cost = float(stats.cost)
        return self

    def _seed_from_buffer(self) -> tuple[np.ndarray, np.ndarray]:
        """Seed k centers from the buffered rows; returns (mat, w) so the
        caller replays the buffer as the first mini-batch. Raises WITHOUT
        consuming the buffer when it lacks k positive-weight rows, so the
        stream can keep feeding partial_fit after the error."""
        from spark_rapids_ml_tpu.ops import kmeans as KM

        mat = np.concatenate(self._seed_rows)
        w = np.concatenate(self._seed_weights)
        keep = w > 0
        if keep.sum() < self.getK():
            raise ValueError(
                f"k={self.getK()} but only {int(keep.sum())} buffered "
                "rows with positive weight to seed from"
            )
        key = jax.random.PRNGKey(self.getSeed())
        if self.getInitMode() == "random":
            rng = np.random.default_rng(self.getSeed())
            pool = mat[keep]
            self._centers = jnp.asarray(
                pool[rng.choice(len(pool), self.getK(), replace=False)]
            )
        else:  # 'k-means++' and 'k-means||' both: k-means++ on the buffer,
            # which plays the oversampled-candidate role the distributed
            # rounds play in the batch fit
            self._centers = KM.kmeans_plus_plus_init(
                key, jnp.asarray(mat[keep]), self.getK()
            )
        self._cum_weights = jnp.zeros((self.getK(),), self._centers.dtype)
        self._seed_rows, self._seed_weights = [], []
        return mat, w

    def finalize(self) -> KMeansModel:
        if self._centers is None and self._seed_rows:
            # short stream (< max(k, seedRows) rows): seed from whatever
            # arrived and run the buffer as the one-and-only mini-batch
            from spark_rapids_ml_tpu.ops import kmeans as KM

            mat, w = self._seed_from_buffer()
            xp, true_rows = columnar.pad_rows(mat)
            wp = np.zeros(xp.shape[0])
            wp[:true_rows] = w
            stats = KM.kmeans_stats(
                jnp.asarray(xp), self._centers, jnp.asarray(wp)
            )
            self._centers, self._cum_weights = _minibatch_center_update(
                self._centers, self._cum_weights, stats.sums, stats.counts
            )
            self._last_cost = float(stats.cost)
        if self._centers is None:
            raise ValueError(
                "finalize() before seeding completed — no rows were "
                "streamed through partial_fit()"
            )
        model = KMeansModel(
            uid=self.uid,
            clusterCenters=np.asarray(self._centers),
            trainingCost=self._last_cost,
        )
        return self._copyValues(model)

    def setSeedRows(self, value: int) -> "IncrementalKMeans":
        if value < 1:
            raise ValueError(f"seedRows must be >= 1, got {value}")
        return self._set(seedRows=value)

    def reset(self) -> "IncrementalKMeans":
        self._centers = self._cum_weights = self._n_cols = None
        self._rows_seen = 0
        self._last_cost = float("nan")
        self._seed_rows, self._seed_weights = [], []
        return self

    def to_state(self) -> tuple[dict[str, np.ndarray], dict]:
        arrays: dict[str, np.ndarray] = {}
        if self._centers is not None:
            arrays["centers"] = np.asarray(self._centers)
            arrays["cum_weights"] = np.asarray(self._cum_weights)
        if self._seed_rows:
            # pre-seeding buffers persist concatenated; only the
            # concatenation is ever consumed downstream
            arrays["seed_rows"] = np.concatenate(self._seed_rows)
            arrays["seed_weights"] = np.concatenate(self._seed_weights)
        return arrays, {
            "kind": type(self).__name__,
            "n_cols": self._n_cols,
            "rows_seen": int(self._rows_seen),
            "last_cost": self._last_cost,
        }

    def from_state(
        self, arrays: dict[str, np.ndarray], state: dict
    ) -> "IncrementalKMeans":
        _check_state_kind(self, state)
        self.reset()
        if "centers" in arrays:
            self._centers = jnp.asarray(arrays["centers"])
            self._cum_weights = jnp.asarray(arrays["cum_weights"])
        if "seed_rows" in arrays:
            self._seed_rows = [np.asarray(arrays["seed_rows"])]
            self._seed_weights = [np.asarray(arrays["seed_weights"])]
        self._n_cols = state.get("n_cols")
        self._rows_seen = int(state.get("rows_seen", 0))
        self._last_cost = float(state.get("last_cost", float("nan")))
        return self


@jax.jit
def _minibatch_center_update(centers, cum_weights, batch_sums, batch_counts):
    """Per-center online mean: c ← (W_c·c + Σ_batch) / (W_c + w_batch) —
    Sculley's 1/n_c learning-rate update in its weighted form. Centers
    that own nothing (cumulative weight still zero) stay put."""
    new_cum = cum_weights + batch_counts
    upd = (
        centers * cum_weights[:, None] + batch_sums
    ) / jnp.maximum(new_cum, 1e-300)[:, None]
    return jnp.where((new_cum > 0)[:, None], upd, centers), new_cum
