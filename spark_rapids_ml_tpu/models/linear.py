"""LinearRegression / LogisticRegression estimators — the GLM family.

Spark-ML-shaped supervised estimators (``featuresCol``/``labelCol``/
``predictionCol``, fluent setters, save/load) on the same two-phase
architecture as PCA (SURVEY.md §3.1): per-partition MXU statistics monoids,
tree-reduced across partitions (mesh/psum variants live in
``parallel.linear``), then a tiny replicated solve.

- ``LinearRegression``: one data pass (normal equations), closed-form L2.
- ``LogisticRegression``: IRLS/Newton — one monoid pass per iteration, with
  the same ``checkpoint_dir`` mid-training checkpoint/resume contract as
  KMeans (utils/checkpoint.py).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    Param,
)
from spark_rapids_ml_tpu.ops import linear as LIN
from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks
from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

import jax.numpy as jnp

_linear_stats = jax.jit(LIN.linear_stats)
_solve_normal = jax.jit(LIN.solve_normal, static_argnames=("fit_intercept",))
# elastic_net_param is static (it picks the closed-form vs FISTA branch);
# reg_param/max_iter/tol stay traced so a CV sweep over λ reuses ONE
# compiled program instead of recompiling per candidate value
_solve_from_stats = jax.jit(
    LIN.solve_from_stats,
    static_argnames=("elastic_net_param", "fit_intercept"),
)
_newton_stats = jax.jit(LIN.logistic_newton_stats)
_newton_update = jax.jit(
    LIN.newton_update, static_argnames=("elastic_net_param", "fit_intercept")
)
_predict_linear = jax.jit(LIN.predict_linear)
_predict_proba = jax.jit(LIN.predict_logistic_proba)
# Full-Newton multinomial cap: the Hessian is [C·d, C·d] and its block
# assembly unrolls C(C+1)/2 matmuls — fine for classical multiclass,
# pathological for ID-like labels.
_MAX_CLASSES = 64

_softmax_stats = jax.jit(LIN.softmax_newton_stats, static_argnames=("n_classes",))
_softmax_update = jax.jit(
    LIN.softmax_newton_update,
    static_argnames=("n_classes", "elastic_net_param", "fit_intercept"),
)
_predict_softmax = jax.jit(LIN.predict_softmax_proba)


class _SupervisedParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    regParam = Param("regParam", "L2 regularization strength λ", float)
    fitIntercept = Param("fitIntercept", "whether to fit an intercept term", bool)
    weightCol = Param(
        "weightCol",
        "optional instance-weight column (Spark ML weightCol contract); "
        "weights ride the same per-row vector that masks shape-bucketing "
        "padding, so weighted fits cost nothing extra",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            regParam=0.0,
            fitIntercept=True,
        )

    def setRegParam(self, value: float):
        return self._set(regParam=value)

    def setWeightCol(self, value: str):
        return self._set(weightCol=value)

    def setFitIntercept(self, value: bool):
        return self._set(fitIntercept=value)

    def getRegParam(self) -> float:
        return self.getOrDefault("regParam")

    def getFitIntercept(self) -> bool:
        return self.getOrDefault("fitIntercept")

    def _labeled(self, dataset: Any, num_partitions: int | None):
        return columnar.labeled_partitions(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("labelCol"),
            num_partitions,
            weight_col=self._paramMap.get("weightCol"),
        )


class _GLMModel(_SupervisedParams, Model):
    """Shared fitted-model surface: coefficients [n] + intercept."""

    def __init__(
        self,
        uid: str | None = None,
        coefficients: np.ndarray | None = None,
        intercept: float = 0.0,
    ):
        super().__init__(uid)
        self.coefficients = (
            None if coefficients is None else np.asarray(coefficients)
        )
        self.intercept = float(intercept)

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, dataset: Any) -> Any:
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "coefficients": self.coefficients,
            "intercept": np.asarray([self.intercept]),
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            coefficients=data["coefficients"],
            intercept=float(data["intercept"][0]),
        )


# ---------------------------------------------------------------------------
# Linear regression
# ---------------------------------------------------------------------------


class _ElasticNetParams:
    """elasticNetParam/maxIter/tol — shared by LinearRegression AND its
    model (so a fitted model carries + persists the solver params, the
    Spark ML estimator/model param-mirroring pattern)."""

    elasticNetParam = Param(
        "elasticNetParam",
        "elastic-net mixing α in [0, 1]: 0 = pure L2 (closed form), "
        "1 = lasso; the L1 solve is FISTA over the reduced statistics",
        float,
    )
    maxIter = Param("maxIter", "maximum FISTA iterations (α > 0 only)", int)
    tol = Param(
        "tol",
        "FISTA convergence tolerance on the relative coefficient change",
        float,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(elasticNetParam=0.0, maxIter=500, tol=1e-8)

    def setElasticNetParam(self, value: float):
        if not 0.0 <= value <= 1.0:
            raise ValueError(f"elasticNetParam must be in [0, 1], got {value}")
        return self._set(elasticNetParam=float(value))

    def getElasticNetParam(self) -> float:
        return self.getOrDefault("elasticNetParam")

    def setMaxIter(self, value: int):
        return self._set(maxIter=value)

    def getMaxIter(self) -> int:
        return self.getOrDefault("maxIter")

    def setTol(self, value: float):
        return self._set(tol=value)

    def getTol(self) -> float:
        return self.getOrDefault("tol")


class LinearRegression(_ElasticNetParams, _SupervisedParams, Estimator):
    """Least squares with optional L2 / L1 / elastic-net regularization.

    One MXU pass builds the (XᵀX, Xᵀy, …) monoid per partition; the [n, n]
    solve runs once on the reduced statistics. With ``elasticNetParam=0``
    (default) the solve is the closed-form normal equations and λ scales
    with the row count (matches ``sklearn.linear_model.Ridge(
    alpha=regParam·rows)``). With ``elasticNetParam=α>0`` the solve is
    FISTA on the same reduced statistics (``ops.linear.solve_elastic_net``)
    — still ONE distributed data pass, zero per-iteration communication —
    matching ``sklearn.linear_model.ElasticNet(alpha=regParam,
    l1_ratio=α)`` / Spark ML's (regParam, elasticNetParam) convention.
    """

    def _solve_args(self) -> dict:
        """Solver kwargs shared by every data path (core/Spark/mesh)."""
        return dict(
            reg_param=self.getRegParam(),
            elastic_net_param=self.getElasticNetParam(),
            fit_intercept=self.getFitIntercept(),
            max_iter=self.getMaxIter(),
            tol=self.getTol(),
        )

    def fit(
        self, dataset: Any, num_partitions: int | None = None
    ) -> "LinearRegressionModel":
        parts = self._labeled(dataset, num_partitions)

        def task(part):
            x, y, sw = part
            xp, yp, w = columnar.pad_labeled(x, y, sw)
            return _linear_stats(jnp.asarray(xp), jnp.asarray(yp), jnp.asarray(w))

        with trace_range("linreg stats"):
            rows = sum(len(p[0]) for p in parts)
            n = parts[0][0].shape[1] if parts else 0
            from spark_rapids_ml_tpu.spark.ingest import (
                stream_fold,
                use_streamed_fit,
                wire_dtype,
            )

            if parts and use_streamed_fit(rows, n):
                # out-of-core: labeled partitions drain through the donated
                # LinearStats fold at O(chunk + n²) device memory; instance
                # weights and the pad mask share the same w vector
                res = stream_fold(
                    iter(parts),
                    LIN.linear_fold_step(),
                    n=n,
                    label_col="y",
                    init=LIN.init_linear_carry(n, wire_dtype()),
                )
                stats = res.carry
            else:
                partials = run_partition_tasks(task, parts)
                stats = tree_reduce(partials, LIN.combine_linear_stats)
        with trace_range("linreg solve"):
            coef, intercept = _solve_from_stats(stats, **self._solve_args())
        model = LinearRegressionModel(
            uid=self.uid,
            coefficients=np.asarray(coef),
            intercept=float(intercept),
        )
        return self._copyValues(model)


class LinearRegressionModel(_ElasticNetParams, _GLMModel):
    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        padded, true_rows = columnar.pad_rows(mat)
        xd = jnp.asarray(padded)
        out = _predict_linear(
            xd,
            jnp.asarray(self.coefficients, dtype=xd.dtype),
            jnp.asarray(self.intercept, dtype=xd.dtype),
        )
        return np.asarray(out)[:true_rows]

    def predict(self, row) -> float:
        return float(np.dot(self.coefficients, np.asarray(row)) + self.intercept)


# ---------------------------------------------------------------------------
# Logistic regression
# ---------------------------------------------------------------------------


def _pad_parts(parts, fit_intercept: bool, label_dtype=None):
    """Bucket-pad labeled partitions and append the intercept column —
    the shared Newton-loop preamble (binary and multinomial)."""
    padded = []
    for x, y, sw in parts:
        xp, yp, w = columnar.pad_labeled(x, y, sw)
        if fit_intercept:
            xp = np.concatenate([xp, np.ones((xp.shape[0], 1), xp.dtype)], axis=1)
        if label_dtype is not None:
            yp = yp.astype(label_dtype)
        padded.append((jnp.asarray(xp), jnp.asarray(yp), jnp.asarray(w)))
    return padded


def _resume_newton_checkpoint(checkpoint_dir: str | None, n_params: int):
    """(initial w, start iteration, checkpointer-or-None) for a Newton loop,
    resuming from the newest durable checkpoint when one exists."""
    w = np.zeros(n_params)
    if checkpoint_dir is None:
        return w, 0, None
    from spark_rapids_ml_tpu.utils.checkpoint import TrainingCheckpointer

    ckpt = TrainingCheckpointer(checkpoint_dir)
    resumed = ckpt.latest()
    if resumed is None:
        return w, 0, ckpt
    step, arrays, _ = resumed
    if arrays["w"].shape[0] != n_params:
        raise ValueError(
            f"checkpoint at {checkpoint_dir} holds {arrays['w'].shape[0]} "
            f"parameters but this fit has {n_params}; is checkpoint_dir stale?"
        )
    return arrays["w"], step + 1, ckpt


def _binary_newton_fit(
    est,
    padded,
    stats_jit,
    *,
    elastic_net_param: float,
    trace_label: str,
    checkpoint_dir: str | None,
    checkpoint_every: int,
) -> tuple[np.ndarray, float]:
    """THE driver-merge binary Newton loop — one copy shared by the
    logistic and squared-hinge (LinearSVC) fits, which differ only in the
    per-shard statistics function. Returns (coefficients, intercept) split
    per the estimator's fitIntercept."""
    fit_intercept = est.getFitIntercept()
    d = padded[0][0].shape[1]
    w_full, start_iter, ckpt = _resume_newton_checkpoint(checkpoint_dir, d)

    with trace_range(trace_label):
        for it in range(start_iter, est.getMaxIter()):
            wj = jnp.asarray(w_full)

            def task(part, wj=wj):
                x, y, w = part
                return stats_jit(x, y, wj, w)

            partials = run_partition_tasks(task, padded)
            stats = tree_reduce(partials, LIN.combine_newton_stats)
            new_w, step_norm = _newton_update(
                wj,
                stats,
                reg_param=est.getRegParam(),
                elastic_net_param=elastic_net_param,
                fit_intercept=fit_intercept,
            )
            w_full = np.asarray(new_w)
            if _newton_step_bookkeeping(
                w_full, step_norm, tol=est.getTol(), ckpt=ckpt, it=it,
                checkpoint_every=checkpoint_every, loss=float(stats.loss),
            ):
                break

    if fit_intercept:
        return w_full[:-1], float(w_full[-1])
    return w_full, 0.0


def _newton_step_bookkeeping(
    w, step_norm, *, tol, ckpt, it, checkpoint_every, loss
) -> bool:
    """Shared post-update tail of the driver-merge Newton loops: the stop
    test, the NaN-input raise BEFORE any save (run_chunked_newton's order —
    a junk step checkpoint must never outlive the raise), then the cadenced
    checkpoint save. Returns True when the loop should stop."""
    stop = not float(step_norm) > tol
    if stop:
        # raises on non-finite DATA; accepts separable-divergence's last
        # finite iterate (see ops.linear.check_newton_outcome)
        LIN.check_newton_outcome(step_norm, w)
    if ckpt is not None and (it + 1) % checkpoint_every == 0:
        ckpt.save(it, {"w": w}, {"loss": loss})
    return stop


class _HasProbabilityCol:
    """probabilityCol — shared by LogisticRegression and its model so the
    fitted model carries it (pyspark.ml's probability-vector output column).
    Default '' = don't emit (this framework's transforms append only the
    columns asked for); setProbabilityCol('probability') restores the stock
    pyspark.ml surface."""

    probabilityCol = Param(
        "probabilityCol",
        "optional output column for the per-class probability vector "
        "([1-p, p] for binary, the softmax row for multinomial); '' = "
        "don't emit",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(probabilityCol="")

    def setProbabilityCol(self, value: str):
        return self._set(probabilityCol=value)

    def getProbabilityCol(self) -> str:
        return self.getOrDefault("probabilityCol")


class LogisticRegression(_HasProbabilityCol, _SupervisedParams, Estimator):
    """Binary logistic regression via IRLS/Newton, optionally elastic-net.

    Each iteration is one distributed monoid pass (XᵀWX, Xᵀ(y−p)) plus a
    replicated [d, d] solve; convergence on the Newton step norm. With
    ``elasticNetParam=α>0`` the replicated solve becomes a proximal-Newton
    step (FISTA on the quadratic model — ``ops.linear.newton_update``);
    the per-iteration distributed cost is identical — for BOTH the binary
    sigmoid and the multinomial softmax paths. Supports the same
    ``checkpoint_dir``/``checkpoint_every`` mid-training checkpoint/resume
    contract as KMeans.
    """

    maxIter = Param("maxIter", "maximum Newton iterations", int)
    tol = Param("tol", "convergence tolerance on the Newton step norm", float)
    elasticNetParam = Param(
        "elasticNetParam",
        "elastic-net mixing α in [0, 1]: 0 = pure L2 IRLS (closed-form "
        "step), >0 = proximal-Newton with L1 soft-thresholding",
        float,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(maxIter=25, tol=1e-6, elasticNetParam=0.0)

    def setMaxIter(self, value: int):
        return self._set(maxIter=value)

    def setTol(self, value: float):
        return self._set(tol=value)

    def getMaxIter(self) -> int:
        return self.getOrDefault("maxIter")

    def getTol(self) -> float:
        return self.getOrDefault("tol")

    def setElasticNetParam(self, value: float):
        if not 0.0 <= value <= 1.0:
            raise ValueError(f"elasticNetParam must be in [0, 1], got {value}")
        return self._set(elasticNetParam=float(value))

    def getElasticNetParam(self) -> float:
        return self.getOrDefault("elasticNetParam")

    def fit(
        self,
        dataset: Any,
        num_partitions: int | None = None,
        *,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 5,
    ) -> "LogisticRegressionModel":
        if checkpoint_every < 1:
            raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
        parts = self._labeled(dataset, num_partitions)
        fit_intercept = self.getFitIntercept()

        all_labels = np.unique(np.concatenate([np.unique(y) for _, y, _ in parts]))
        if not np.all(all_labels == np.round(all_labels)) or all_labels.min() < 0:
            raise ValueError(
                "logistic regression requires integer class labels "
                f"0..C-1, got {all_labels[:8]}"
            )
        n_classes = int(all_labels.max()) + 1
        if n_classes > _MAX_CLASSES:
            raise ValueError(
                f"labels imply {n_classes} classes (max label "
                f"{int(all_labels.max())}), over the supported cap of "
                f"{_MAX_CLASSES} — the full-Newton Hessian is [C·d, C·d]. "
                "Check for mislabeled/ID-like rows, or re-encode labels "
                "densely as 0..C-1"
            )
        if n_classes > 2:
            return self._fit_multinomial(
                parts,
                n_classes,
                fit_intercept,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
            )
        padded = _pad_parts(parts, fit_intercept)
        coef, intercept = _binary_newton_fit(
            self,
            padded,
            _newton_stats,
            elastic_net_param=self.getElasticNetParam(),
            trace_label="logreg newton",
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
        )
        model = LogisticRegressionModel(
            uid=self.uid, coefficients=coef, intercept=intercept
        )
        return self._copyValues(model)

    def _fit_multinomial(
        self,
        parts,
        n_classes: int,
        fit_intercept: bool,
        *,
        checkpoint_dir: str | None,
        checkpoint_every: int,
    ) -> "LogisticRegressionModel":
        """Softmax IRLS: full-Newton on the flattened [C·d] parameter.

        Same distributed schedule as the binary path — one stats-monoid pass
        per iteration (SoftmaxStats: the full Fisher Hessian as C(C+1)/2 MXU
        block matmuls), replicated [C·d, C·d] solve between passes. Spark ML
        fits the same family with L-BFGS; full Newton converges in a handful
        of data passes, which on TPU (where each pass is cheap and the solve
        is tiny) is the better trade.
        """
        padded = _pad_parts(parts, fit_intercept, label_dtype=np.int32)
        d = padded[0][0].shape[1]
        w_flat, start_iter, ckpt = _resume_newton_checkpoint(
            checkpoint_dir, n_classes * d
        )

        with trace_range("softmax newton"):
            for it in range(start_iter, self.getMaxIter()):
                wj = jnp.asarray(w_flat)

                def task(part, wj=wj):
                    x, y, w = part
                    return _softmax_stats(x, y, wj, n_classes, w)

                partials = run_partition_tasks(task, padded)
                stats = tree_reduce(partials, LIN.combine_softmax_stats)
                new_w, step_norm = _softmax_update(
                    wj,
                    stats,
                    n_classes,
                    reg_param=self.getRegParam(),
                    elastic_net_param=self.getElasticNetParam(),
                    fit_intercept=fit_intercept,
                )
                w_flat = np.asarray(new_w)
                if _newton_step_bookkeeping(
                    w_flat, step_norm, tol=self.getTol(), ckpt=ckpt, it=it,
                    checkpoint_every=checkpoint_every,
                    loss=float(stats.loss),
                ):
                    break

        w_mat = w_flat.reshape(n_classes, d)
        if fit_intercept:
            coef_matrix, intercepts = w_mat[:, :-1], w_mat[:, -1]
        else:
            coef_matrix, intercepts = w_mat, np.zeros(n_classes)
        model = LogisticRegressionModel(
            uid=self.uid,
            coefficientMatrix=coef_matrix,
            interceptVector=intercepts,
        )
        return self._copyValues(model)


class LogisticRegressionModel(_HasProbabilityCol, _GLMModel):
    """Binary or multinomial fitted model.

    Binary: ``coefficients`` [n] + ``intercept`` (``predict_proba_matrix``
    returns [rows] P(y=1), preserving the binary contract). Multinomial:
    ``coefficientMatrix`` [C, n] + ``interceptVector`` [C]
    (``predict_proba_matrix`` returns [rows, C]); transform emits the argmax
    class — the Spark LogisticRegressionModel shape.
    """

    def __init__(
        self,
        uid: str | None = None,
        coefficients: np.ndarray | None = None,
        intercept: float = 0.0,
        coefficientMatrix: np.ndarray | None = None,
        interceptVector: np.ndarray | None = None,
    ):
        super().__init__(uid, coefficients=coefficients, intercept=intercept)
        self.coefficientMatrix = (
            None if coefficientMatrix is None else np.asarray(coefficientMatrix)
        )
        self.interceptVector = (
            None if interceptVector is None else np.asarray(interceptVector)
        )

    @property
    def numClasses(self) -> int:
        if self.coefficientMatrix is not None:
            return self.coefficientMatrix.shape[0]
        return 2

    def transform(self, dataset: Any) -> Any:
        proba_col = self.getProbabilityCol()
        if proba_col and columnar.has_named_columns(dataset):
            # emit BOTH Spark-ML-style output columns from ONE forward pass
            # on column-bearing containers (arrow/pandas); matrix/partition
            # inputs have no named columns and keep the prediction-only
            # contract
            mat = columnar.extract_matrix(
                dataset, self.getOrDefault("featuresCol")
            )
            vecs, preds = self.proba_and_predictions(mat)
            return columnar.append_columns(
                dataset,
                [
                    (proba_col, vecs),
                    (self.getOrDefault("predictionCol"), preds),
                ],
            )
        return super().transform(dataset)

    def proba_and_predictions(
        self, mat: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """One forward pass → ([rows, C] probability vectors, [rows]
        predictions). THE decision rule for both the local and Spark
        transform paths: binary stacks [1−p, p] and thresholds at 0.5
        inclusive; multinomial takes the argmax of the softmax row."""
        proba = self.predict_proba_matrix(mat)
        if proba.ndim == 1:
            preds = (proba >= 0.5).astype(np.float64)
            return np.stack([1.0 - proba, proba], axis=1), preds
        return proba, np.argmax(proba, axis=1).astype(np.float64)

    def predict_proba_matrix(self, mat: np.ndarray) -> np.ndarray:
        padded, true_rows = columnar.pad_rows(mat)
        xd = jnp.asarray(padded)
        if self.coefficientMatrix is not None:
            out = _predict_softmax(
                xd,
                jnp.asarray(self.coefficientMatrix, dtype=xd.dtype),
                jnp.asarray(self.interceptVector, dtype=xd.dtype),
            )
            return np.asarray(out)[:true_rows]
        out = _predict_proba(
            xd,
            jnp.asarray(self.coefficients, dtype=xd.dtype),
            jnp.asarray(self.intercept, dtype=xd.dtype),
        )
        return np.asarray(out)[:true_rows]

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        proba = self.predict_proba_matrix(mat)
        if proba.ndim == 2:
            return np.argmax(proba, axis=1).astype(np.float64)
        return (proba >= 0.5).astype(np.float64)

    def predict(self, row) -> float:
        if self.coefficientMatrix is not None:
            z = self.coefficientMatrix @ np.asarray(row) + self.interceptVector
            return float(np.argmax(z))
        z = float(np.dot(self.coefficients, np.asarray(row)) + self.intercept)
        return 1.0 if z >= 0.0 else 0.0

    def _saveData(self) -> dict[str, np.ndarray]:
        if self.coefficientMatrix is not None:
            return {
                "coefficientMatrix": self.coefficientMatrix,
                "interceptVector": self.interceptVector,
            }
        return super()._saveData()

    @classmethod
    def _fromSaved(cls, uid, data):
        if "coefficientMatrix" in data:
            return cls(
                uid=uid,
                coefficientMatrix=data["coefficientMatrix"],
                interceptVector=data["interceptVector"],
            )
        return super()._fromSaved(uid, data)


# ---------------------------------------------------------------------------
# Linear SVC (squared-hinge L2 SVM)
# ---------------------------------------------------------------------------

_svc_stats = jax.jit(LIN.svc_newton_stats)


class LinearSVC(_SupervisedParams, Estimator):
    """Linear support-vector classifier on the squared-hinge loss.

    The spark-rapids-ml family exposes cuML's LinearSVC; pyspark.ml's
    LinearSVC minimizes the plain (non-smooth) hinge with OWLQN and is
    L2-only. This implementation takes the cuML/sklearn default — the
    SQUARED hinge — because it is smooth: the same Newton machinery as
    LogisticRegression applies (one NewtonStats monoid pass + a replicated
    [d, d] solve per iteration, ops.linear.svc_newton_stats), converging
    in a handful of data passes where OWLQN takes hundreds. L2-only, like
    Spark's.
    """

    maxIter = Param("maxIter", "maximum Newton iterations", int)
    tol = Param("tol", "convergence tolerance on the Newton step norm", float)
    threshold = Param(
        "threshold",
        "decision threshold on the rawPrediction margin (Spark LinearSVC "
        "contract: predict 1.0 when wᵀx + b > threshold)",
        float,
    )
    rawPredictionCol = Param(
        "rawPredictionCol", "margin output column ([−m, m], Spark shape)", str
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            maxIter=100, tol=1e-6, threshold=0.0,
            rawPredictionCol="rawPrediction", regParam=0.0,
        )

    def setMaxIter(self, value: int):
        return self._set(maxIter=value)

    def setTol(self, value: float):
        return self._set(tol=value)

    def setThreshold(self, value: float):
        return self._set(threshold=float(value))

    def setRawPredictionCol(self, value: str):
        return self._set(rawPredictionCol=value)

    def getMaxIter(self) -> int:
        return self.getOrDefault("maxIter")

    def getTol(self) -> float:
        return self.getOrDefault("tol")

    def getThreshold(self) -> float:
        return self.getOrDefault("threshold")

    def fit(
        self,
        dataset: Any,
        num_partitions: int | None = None,
        *,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 5,
    ) -> "LinearSVCModel":
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}"
            )
        parts = self._labeled(dataset, num_partitions)
        fit_intercept = self.getFitIntercept()
        labels = np.unique(np.concatenate([np.unique(y) for _, y, _ in parts]))
        if not np.all(np.isin(labels, (0.0, 1.0))):
            raise ValueError(
                f"LinearSVC requires binary 0/1 labels, got {labels[:8]}"
            )
        padded = _pad_parts(parts, fit_intercept)
        coef, intercept = _binary_newton_fit(
            self,
            padded,
            _svc_stats,
            elastic_net_param=0.0,  # Spark LinearSVC: L2 only
            trace_label="svc newton",
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
        )
        model = LinearSVCModel(
            uid=self.uid, coefficients=coef, intercept=intercept
        )
        return self._copyValues(model)


class LinearSVCModel(_GLMModel):
    """Fitted linear SVC: margin m = wᵀx + b; rawPrediction [−m, m];
    prediction 1.0 when m > threshold (Spark LinearSVCModel shape)."""

    threshold = LinearSVC.threshold
    rawPredictionCol = LinearSVC.rawPredictionCol

    def __init__(self, uid=None, coefficients=None, intercept: float = 0.0):
        super().__init__(uid, coefficients=coefficients, intercept=intercept)
        self._setDefault(threshold=0.0, rawPredictionCol="rawPrediction")

    def getThreshold(self) -> float:
        return self.getOrDefault("threshold")

    def setThreshold(self, value: float):
        return self._set(threshold=float(value))

    def margins(self, mat: np.ndarray) -> np.ndarray:
        # row-bucketed padding so varying batch sizes reuse one compiled
        # program (the sibling predict paths' contract)
        padded, true_rows = columnar.pad_rows(mat)
        return np.asarray(
            _predict_linear(
                jnp.asarray(padded),
                jnp.asarray(self.coefficients, dtype=padded.dtype),
                jnp.asarray(self.intercept, dtype=padded.dtype),
            )
        )[:true_rows]

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        return (self.margins(mat) > self.getThreshold()).astype(np.float64)

    def transform(self, dataset: Any) -> Any:
        raw_col = self.getOrDefault("rawPredictionCol")
        if raw_col and columnar.has_named_columns(dataset):
            mat = columnar.extract_matrix(
                dataset, self.getOrDefault("featuresCol")
            )
            m = self.margins(mat)
            raw = np.stack([-m, m], axis=1)
            preds = (m > self.getThreshold()).astype(np.float64)
            return columnar.append_columns(
                dataset,
                [
                    (raw_col, raw),
                    (self.getOrDefault("predictionCol"), preds),
                ],
            )
        return super().transform(dataset)
