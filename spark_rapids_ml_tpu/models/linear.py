"""LinearRegression / LogisticRegression estimators — the GLM family.

Spark-ML-shaped supervised estimators (``featuresCol``/``labelCol``/
``predictionCol``, fluent setters, save/load) on the same two-phase
architecture as PCA (SURVEY.md §3.1): per-partition MXU statistics monoids,
tree-reduced across partitions (mesh/psum variants live in
``parallel.linear``), then a tiny replicated solve.

- ``LinearRegression``: one data pass (normal equations), closed-form L2.
- ``LogisticRegression``: IRLS/Newton — one monoid pass per iteration, with
  the same ``checkpoint_dir`` mid-training checkpoint/resume contract as
  KMeans (utils/checkpoint.py).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    Param,
)
from spark_rapids_ml_tpu.ops import linear as LIN
from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks
from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.utils.tracing import trace_range

import jax.numpy as jnp

_linear_stats = jax.jit(LIN.linear_stats)
_solve_normal = jax.jit(LIN.solve_normal, static_argnames=("fit_intercept",))
_newton_stats = jax.jit(LIN.logistic_newton_stats)
_newton_update = jax.jit(LIN.newton_update, static_argnames=("fit_intercept",))
_predict_linear = jax.jit(LIN.predict_linear)
_predict_proba = jax.jit(LIN.predict_logistic_proba)


class _SupervisedParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    regParam = Param("regParam", "L2 regularization strength λ", float)
    fitIntercept = Param("fitIntercept", "whether to fit an intercept term", bool)
    weightCol = Param(
        "weightCol",
        "optional instance-weight column (Spark ML weightCol contract); "
        "weights ride the same per-row vector that masks shape-bucketing "
        "padding, so weighted fits cost nothing extra",
        str,
    )

    def __init__(self, uid: str | None = None):
        super().__init__(uid)
        self._setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            regParam=0.0,
            fitIntercept=True,
        )

    def setRegParam(self, value: float):
        return self._set(regParam=value)

    def setWeightCol(self, value: str):
        return self._set(weightCol=value)

    def setFitIntercept(self, value: bool):
        return self._set(fitIntercept=value)

    def getRegParam(self) -> float:
        return self.getOrDefault("regParam")

    def getFitIntercept(self) -> bool:
        return self.getOrDefault("fitIntercept")

    def _labeled(self, dataset: Any, num_partitions: int | None):
        return columnar.labeled_partitions(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("labelCol"),
            num_partitions,
            weight_col=self._paramMap.get("weightCol"),
        )


class _GLMModel(_SupervisedParams, Model):
    """Shared fitted-model surface: coefficients [n] + intercept."""

    def __init__(
        self,
        uid: str | None = None,
        coefficients: np.ndarray | None = None,
        intercept: float = 0.0,
    ):
        super().__init__(uid)
        self.coefficients = (
            None if coefficients is None else np.asarray(coefficients)
        )
        self.intercept = float(intercept)

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, dataset: Any) -> Any:
        return columnar.apply_column_transform(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("predictionCol"),
            self._predict_matrix,
        )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {
            "coefficients": self.coefficients,
            "intercept": np.asarray([self.intercept]),
        }

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            coefficients=data["coefficients"],
            intercept=float(data["intercept"][0]),
        )


# ---------------------------------------------------------------------------
# Linear regression
# ---------------------------------------------------------------------------


class LinearRegression(_SupervisedParams, Estimator):
    """Closed-form (normal equations) least squares with optional L2.

    One MXU pass builds the (XᵀX, Xᵀy, …) monoid per partition; the [n, n]
    solve runs once on the reduced statistics. λ scales with the row count,
    so results match ``sklearn.linear_model.Ridge(alpha=regParam·rows)``.
    """

    def fit(
        self, dataset: Any, num_partitions: int | None = None
    ) -> "LinearRegressionModel":
        parts = self._labeled(dataset, num_partitions)

        def task(part):
            x, y, sw = part
            xp, yp, w = columnar.pad_labeled(x, y, sw)
            return _linear_stats(jnp.asarray(xp), jnp.asarray(yp), jnp.asarray(w))

        with trace_range("linreg stats"):
            partials = run_partition_tasks(task, parts)
            stats = tree_reduce(partials, LIN.combine_linear_stats)
        with trace_range("linreg solve"):
            coef, intercept = _solve_normal(
                stats,
                reg_param=self.getRegParam(),
                fit_intercept=self.getFitIntercept(),
            )
        model = LinearRegressionModel(
            uid=self.uid,
            coefficients=np.asarray(coef),
            intercept=float(intercept),
        )
        return self._copyValues(model)


class LinearRegressionModel(_GLMModel):
    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        padded, true_rows = columnar.pad_rows(mat)
        xd = jnp.asarray(padded)
        out = _predict_linear(
            xd,
            jnp.asarray(self.coefficients, dtype=xd.dtype),
            jnp.asarray(self.intercept, dtype=xd.dtype),
        )
        return np.asarray(out)[:true_rows]

    def predict(self, row) -> float:
        return float(np.dot(self.coefficients, np.asarray(row)) + self.intercept)


# ---------------------------------------------------------------------------
# Logistic regression
# ---------------------------------------------------------------------------


class LogisticRegression(_SupervisedParams, Estimator):
    """Binary logistic regression via IRLS/Newton.

    Each iteration is one distributed monoid pass (XᵀWX, Xᵀ(y−p)) plus a
    replicated [d, d] solve; convergence on the Newton step norm. Supports
    the same ``checkpoint_dir``/``checkpoint_every`` mid-training
    checkpoint/resume contract as KMeans.
    """

    maxIter = Param("maxIter", "maximum Newton iterations", int)
    tol = Param("tol", "convergence tolerance on the Newton step norm", float)

    def __init__(self, uid: str | None = None):
        super().__init__(uid)
        self._setDefault(maxIter=25, tol=1e-6)

    def setMaxIter(self, value: int):
        return self._set(maxIter=value)

    def setTol(self, value: float):
        return self._set(tol=value)

    def getMaxIter(self) -> int:
        return self.getOrDefault("maxIter")

    def getTol(self) -> float:
        return self.getOrDefault("tol")

    def fit(
        self,
        dataset: Any,
        num_partitions: int | None = None,
        *,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 5,
    ) -> "LogisticRegressionModel":
        if checkpoint_every < 1:
            raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
        parts = self._labeled(dataset, num_partitions)
        fit_intercept = self.getFitIntercept()

        padded = []
        for x, y, sw in parts:
            labels = np.unique(y)
            if not np.all(np.isin(labels, (0.0, 1.0))):
                raise ValueError(
                    f"binary logistic regression requires 0/1 labels, got {labels}"
                )
            xp, yp, w = columnar.pad_labeled(x, y, sw)
            if fit_intercept:
                xp = np.concatenate([xp, np.ones((xp.shape[0], 1), xp.dtype)], axis=1)
            padded.append((jnp.asarray(xp), jnp.asarray(yp), jnp.asarray(w)))

        d = padded[0][0].shape[1]
        w_full = np.zeros(d)
        start_iter = 0
        ckpt = None
        if checkpoint_dir is not None:
            from spark_rapids_ml_tpu.utils.checkpoint import TrainingCheckpointer

            ckpt = TrainingCheckpointer(checkpoint_dir)
            resumed = ckpt.latest()
            if resumed is not None:
                step, arrays, _ = resumed
                if arrays["w"].shape[0] != d:
                    raise ValueError(
                        f"checkpoint at {checkpoint_dir} holds {arrays['w'].shape[0]} "
                        f"parameters but this fit has {d}; is checkpoint_dir stale?"
                    )
                w_full, start_iter = arrays["w"], step + 1

        with trace_range("logreg newton"):
            for it in range(start_iter, self.getMaxIter()):
                wj = jnp.asarray(w_full)

                def task(part, wj=wj):
                    x, y, w = part
                    return _newton_stats(x, y, wj, w)

                partials = run_partition_tasks(task, padded)
                stats = tree_reduce(partials, LIN.combine_newton_stats)
                new_w, step_norm = _newton_update(
                    wj,
                    stats,
                    reg_param=self.getRegParam(),
                    fit_intercept=fit_intercept,
                )
                w_full = np.asarray(new_w)
                if ckpt is not None and (it + 1) % checkpoint_every == 0:
                    ckpt.save(it, {"w": w_full}, {"loss": float(stats.loss)})
                if float(step_norm) <= self.getTol():
                    break

        if fit_intercept:
            coef, intercept = w_full[:-1], float(w_full[-1])
        else:
            coef, intercept = w_full, 0.0
        model = LogisticRegressionModel(
            uid=self.uid, coefficients=coef, intercept=intercept
        )
        return self._copyValues(model)


class LogisticRegressionModel(_GLMModel):
    def predict_proba_matrix(self, mat: np.ndarray) -> np.ndarray:
        padded, true_rows = columnar.pad_rows(mat)
        xd = jnp.asarray(padded)
        out = _predict_proba(
            xd,
            jnp.asarray(self.coefficients, dtype=xd.dtype),
            jnp.asarray(self.intercept, dtype=xd.dtype),
        )
        return np.asarray(out)[:true_rows]

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        return (self.predict_proba_matrix(mat) >= 0.5).astype(np.float64)

    def predict(self, row) -> float:
        z = float(np.dot(self.coefficients, np.asarray(row)) + self.intercept)
        return 1.0 if z >= 0.0 else 0.0
