"""DBSCAN estimator/model — the spark-rapids-ml density-clustering family.

API mirrors spark-rapids-ml's cuML-backed DBSCAN: ``eps`` /
``minSamples`` / ``metric`` params, ``fit`` is parameter capture (density
clustering has no training phase separate from inference), and
``DBSCANModel.transform(dataset)`` runs the clustering on the dataset it is
given, appending an integer cluster column (−1 = noise) — spark-rapids-ml
documents the same "call transform on the dataframe you fit" contract.
Kernels: ops/dbscan.py (blocked MXU eps-neighborhood + min-label
propagation); parallel/dbscan.py runs the identical recursion mesh-sharded.

Determinism note: cluster ids are assigned by smallest member core-row
index and border rows join their smallest core neighbor's cluster, so
output is invariant to partitioning/order — stricter than sklearn, whose
border assignment is scan-order dependent.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model
from spark_rapids_ml_tpu.models.params import HasInputCol, Param
from spark_rapids_ml_tpu.ops import dbscan as DB
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

_METRICS = ("euclidean", "sqeuclidean")


class _DBSCANParams(HasInputCol):
    eps = Param("eps", "neighborhood radius", float)
    minSamples = Param(
        "minSamples",
        "weighted neighbor mass (self included) required for a core point",
        float,
    )
    metric = Param("metric", "'euclidean' (default) or 'sqeuclidean'", str)
    predictionCol = Param("predictionCol", "output cluster-id column", str)
    weightCol = Param(
        "weightCol",
        "optional sample-weight column: a point is core when the WEIGHT SUM "
        "of its eps-neighborhood reaches minSamples; weights gate core "
        "status only, so zero-weight points still receive border labels "
        "(sklearn sample_weight semantics)",
        str,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            eps=0.5, minSamples=5.0, metric="euclidean",
            predictionCol="prediction",
        )

    def getEps(self) -> float:
        return self.getOrDefault("eps")

    def getMinSamples(self) -> float:
        return self.getOrDefault("minSamples")

    def getMetric(self) -> str:
        return self.getOrDefault("metric")

    def getPredictionCol(self) -> str:
        return self.getOrDefault("predictionCol")


class DBSCAN(_DBSCANParams, Estimator):
    def setEps(self, value: float) -> "DBSCAN":
        if value <= 0:
            raise ValueError(f"eps must be > 0, got {value}")
        return self._set(eps=float(value))

    def setMinSamples(self, value: float) -> "DBSCAN":
        if value < 1:
            raise ValueError(f"minSamples must be >= 1, got {value}")
        return self._set(minSamples=float(value))

    def setMetric(self, value: str) -> "DBSCAN":
        if value not in _METRICS:
            raise ValueError(f"metric must be one of {_METRICS}, got {value!r}")
        return self._set(metric=value)

    def setPredictionCol(self, value: str) -> "DBSCAN":
        return self._set(predictionCol=value)

    def setWeightCol(self, value: str) -> "DBSCAN":
        return self._set(weightCol=value)

    def fit(self, dataset: Any = None) -> "DBSCANModel":
        """Parameter capture (the spark-rapids-ml shape: the clustering
        itself runs in ``DBSCANModel.transform``); ``dataset`` is accepted
        for Estimator-contract compatibility and ignored."""
        return self._copyValues(DBSCANModel(uid=self.uid))


class DBSCANModel(_DBSCANParams, Model):
    def _cluster_matrix(
        self, mat: np.ndarray, weights: np.ndarray | None
    ) -> np.ndarray:
        """THE clustering body: dtype/eps resolution, padded kernel run,
        consecutive relabel. ``_compute_labels`` is the kernel+padding hook
        the Spark wrapper overrides with the mesh-sharded program — the eps
        semantics live only here."""
        fdt = columnar.float_dtype_for(mat.dtype)
        x = mat.astype(fdt, copy=False)
        eps = self.getEps()
        eps_sq = eps * eps if self.getMetric() == "euclidean" else eps
        labels = self._compute_labels(
            x,
            weights,
            np.asarray(eps_sq, fdt),
            np.asarray(self.getMinSamples(), fdt),
        )
        return _relabel_consecutive(labels)

    @staticmethod
    def _pad_inputs(x, weights, pad_to: int):
        """(padded x, weight vector, valid mask) with pad rows at weight 0 /
        valid False — shared by the single-device and mesh paddings."""
        fdt = x.dtype
        rows = x.shape[0]
        xp = np.zeros((pad_to, x.shape[1]), fdt)
        xp[:rows] = x
        w = np.zeros(pad_to, fdt)
        w[:rows] = 1.0 if weights is None else weights
        valid = np.zeros(pad_to, bool)
        valid[:rows] = True
        return xp, w, valid

    def _compute_labels(self, x, weights, eps_sq, min_samples) -> np.ndarray:
        """Single-device kernel run on shape-bucketed padding."""
        padded, true_rows = columnar.pad_rows(x)
        xp, w, valid = self._pad_inputs(x, weights, padded.shape[0])
        return np.asarray(
            DB.dbscan_labels(
                jnp.asarray(xp),
                jnp.asarray(w),
                jnp.asarray(valid),
                jnp.asarray(eps_sq),
                jnp.asarray(min_samples),
            )
        )[:true_rows]

    def clusterLabels(self, dataset: Any) -> np.ndarray:
        """[rows] int32 cluster ids (−1 = noise) for ``dataset`` — the
        ndarray spelling of ``transform``."""
        mat = columnar.extract_matrix(dataset, self._paramMap.get("inputCol"))
        weight_col = self._paramMap.get("weightCol")
        weights = None
        if weight_col is not None:
            weights = columnar.validate_weights(
                columnar.extract_vector(dataset, weight_col), mat.shape[0]
            )
        with trace_range("dbscan cluster"):
            return self._cluster_matrix(mat, weights)

    def transform(self, dataset: Any) -> Any:
        labels = self.clusterLabels(dataset)
        return columnar.append_columns(
            dataset, [(self.getPredictionCol(), labels)]
        )


def _relabel_consecutive(labels: np.ndarray) -> np.ndarray:
    """Map cluster ids (smallest-core-index values) onto 0..C−1, ascending —
    deterministic regardless of data scale; −1 noise passes through."""
    ids = np.unique(labels[labels >= 0])
    remap = np.full(int(ids.max()) + 1 if len(ids) else 0, -1, dtype=np.int32)
    remap[ids] = np.arange(len(ids), dtype=np.int32)
    out = labels.copy()
    out[labels >= 0] = remap[labels[labels >= 0]]
    return out
