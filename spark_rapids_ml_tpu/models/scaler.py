"""StandardScaler and Normalizer — the preprocessing stages of BASELINE
config 4 ("StandardScaler / Normalizer fused into the PCA input pipeline").

API shape follows Spark MLlib (the reference's host framework): StandardScaler
is an Estimator with ``withMean`` (default False) / ``withStd`` (default
True); Normalizer is a stateless Transformer with a ``p`` norm param
(default 2.0). Fit statistics use the same partition-monoid + tree-reduce
design as PCA's GramStats, so the distributed story is identical.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model, Transformer
from spark_rapids_ml_tpu.models.params import HasInputCol, HasOutputCol, Param
from spark_rapids_ml_tpu.ops import scaler as S
from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.utils.tracing import trace_range

_moment_stats = jax.jit(S.moment_stats)
_finalize = jax.jit(S.finalize_moments)


class _ScalerParams(HasInputCol, HasOutputCol):
    withMean = Param("withMean", "center features before scaling", bool)
    withStd = Param("withStd", "scale features to unit sample std", bool)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(withMean=False, withStd=True, outputCol="scaled_features")

    def getWithMean(self) -> bool:
        return self.getOrDefault("withMean")

    def getWithStd(self) -> bool:
        return self.getOrDefault("withStd")


class StandardScaler(_ScalerParams, Estimator):
    def setWithMean(self, value: bool) -> "StandardScaler":
        return self._set(withMean=value)

    def setWithStd(self, value: bool) -> "StandardScaler":
        return self._set(withStd=value)

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "StandardScalerModel":
        input_col = self._paramMap.get("inputCol")
        ds = columnar.PartitionedDataset.from_any(dataset, input_col, num_partitions)
        with trace_range("scaler moments"):

            def partition_task(mat):
                padded, true_rows = columnar.pad_rows(mat)
                st = _moment_stats(jnp.asarray(padded))
                return S.MomentStats(
                    jnp.asarray(true_rows, st.count.dtype), st.total, st.total_sq
                )

            from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks

            partials = run_partition_tasks(partition_task, list(ds.matrices()))
            stats = tree_reduce(partials, S.combine_moment_stats)
            mean, std = _finalize(stats)
        model = StandardScalerModel(
            uid=self.uid, mean=np.asarray(mean), std=np.asarray(std)
        )
        return self._copyValues(model)


class StandardScalerModel(_ScalerParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        mean: np.ndarray | None = None,
        std: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.mean = None if mean is None else np.asarray(mean)
        self.std = None if std is None else np.asarray(std)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        out = jax.jit(
            S.standardize, static_argnames=("with_mean", "with_std")
        )(
            jnp.asarray(mat),
            jnp.asarray(self.mean, dtype=mat.dtype),
            jnp.asarray(self.std, dtype=mat.dtype),
            with_mean=self.getWithMean(),
            with_std=self.getWithStd(),
        )
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("scaler transform"):
            return columnar.apply_column_transform(
                dataset, self._paramMap.get("inputCol"), self.getOutputCol(), self._scale
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"mean": self.mean, "std": self.std}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, mean=data["mean"], std=data["std"])

    # -- stock pyspark.ml interop (layout="spark"): Spark persists
    # Row(std: Vector, mean: Vector) in that order --------------------------
    _SPARK_ML_CLASS = "org.apache.spark.ml.feature.StandardScalerModel"
    _SPARK_ML_PARAMS = ("withMean", "withStd", "inputCol", "outputCol")

    def _saveSparkML(self, path: str) -> None:
        from spark_rapids_ml_tpu.models.base import spark_set_params
        from spark_rapids_ml_tpu.utils import persistence as P

        params = {
            k: v
            for k, v in spark_set_params(self).items()
            if k in self._SPARK_ML_PARAMS
        }
        vec_field = lambda name: {  # noqa: E731 - tiny schema helper
            "name": name,
            "type": P._vector_udt_json(),
            "nullable": True,
            "metadata": {},
        }
        P.save_spark_ml_metadata(
            path, class_name=self._SPARK_ML_CLASS, uid=self.uid, param_map=params
        )
        P.save_spark_ml_data(
            path,
            {
                "std": P._dense_vector_struct(self.std),
                "mean": P._dense_vector_struct(self.mean),
            },
            {"type": "struct", "fields": [vec_field("std"), vec_field("mean")]},
        )

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "StandardScalerModel":
        from spark_rapids_ml_tpu.utils import persistence as P

        return cls(
            uid=meta["uid"],
            mean=P.struct_to_vector(table.column("mean")[0].as_py()),
            std=P.struct_to_vector(table.column("std")[0].as_py()),
        )


class Normalizer(HasInputCol, HasOutputCol, Transformer):
    """Stateless row p-normalization (Spark ``Normalizer`` semantics)."""

    p = Param("p", "norm order (p >= 1; inf supported)", float)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(p=2.0, outputCol="normalized_features")

    def setP(self, value: float) -> "Normalizer":
        return self._set(p=value)

    def getP(self) -> float:
        return self.getOrDefault("p")

    def _normalize_matrix(self, mat: np.ndarray) -> np.ndarray:
        """[rows, n] → row-p-normalized [rows, n]; the one matrix fn both the
        local and the Spark (mapInArrow) transform paths run."""
        return np.asarray(
            jax.jit(S.normalize, static_argnums=(1,))(
                jnp.asarray(mat), self.getP()
            )
        )

    def transform(self, dataset: Any) -> Any:
        with trace_range("normalize"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._normalize_matrix,
            )
