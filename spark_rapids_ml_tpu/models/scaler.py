"""StandardScaler and Normalizer — the preprocessing stages of BASELINE
config 4 ("StandardScaler / Normalizer fused into the PCA input pipeline").

API shape follows Spark MLlib (the reference's host framework): StandardScaler
is an Estimator with ``withMean`` (default False) / ``withStd`` (default
True); Normalizer is a stateless Transformer with a ``p`` norm param
(default 2.0). Fit statistics use the same partition-monoid + tree-reduce
design as PCA's GramStats, so the distributed story is identical.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model, Transformer
from spark_rapids_ml_tpu.models.params import HasInputCol, HasOutputCol, Param
from spark_rapids_ml_tpu.ops import scaler as S
from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

_moment_stats = jax.jit(S.moment_stats)
_finalize = jax.jit(S.finalize_moments)
# transform kernels hoisted once per process (the repo's jit-caching
# convention): a per-call jax.jit wrapper would retrace on every Arrow
# batch in the Spark mapInArrow transform path
_standardize = jax.jit(S.standardize, static_argnames=("with_mean", "with_std"))
_minmax_scale = jax.jit(S.minmax_scale, static_argnames=("lo", "hi"))
_maxabs_scale = jax.jit(S.maxabs_scale)
_robust_scale = jax.jit(
    S.robust_scale, static_argnames=("with_centering", "with_scaling")
)
_binarize = jax.jit(S.binarize, static_argnames=("threshold",))
_normalize = jax.jit(S.normalize, static_argnums=(1,))


def _save_spark_ml_vectors(model, path: str, vectors: dict) -> None:
    """One stock-layout writer for the scaler family: filtered params +
    ordered dense-vector data row (see persistence.save_spark_ml_vector_model)."""
    from spark_rapids_ml_tpu.models.base import spark_set_params
    from spark_rapids_ml_tpu.utils import persistence as P

    P.save_spark_ml_vector_model(
        path,
        class_name=model._SPARK_ML_CLASS,
        uid=model.uid,
        params={
            k: v
            for k, v in spark_set_params(model).items()
            if k in model._SPARK_ML_PARAMS
        },
        vectors=vectors,
    )


class _ScalerParams(HasInputCol, HasOutputCol):
    withMean = Param("withMean", "center features before scaling", bool)
    withStd = Param("withStd", "scale features to unit sample std", bool)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(withMean=False, withStd=True, outputCol="scaled_features")

    def getWithMean(self) -> bool:
        return self.getOrDefault("withMean")

    def getWithStd(self) -> bool:
        return self.getOrDefault("withStd")


class StandardScaler(_ScalerParams, Estimator):
    def setWithMean(self, value: bool) -> "StandardScaler":
        return self._set(withMean=value)

    def setWithStd(self, value: bool) -> "StandardScaler":
        return self._set(withStd=value)

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "StandardScalerModel":
        input_col = self._paramMap.get("inputCol")
        ds = columnar.PartitionedDataset.from_any(dataset, input_col, num_partitions)
        with trace_range("scaler moments"):
            if columnar.use_streamed_fit(ds):
                # out-of-core: partitions drain through the donated moments
                # fold (ops.scaler.moment_fold_step) at O(chunk + n) device
                # memory; count = Σw (1.0 true rows / 0.0 pads) is exact
                from spark_rapids_ml_tpu.spark import ingest

                it = ds.matrices()
                first = next(it)
                n = first.shape[1]

                def chunks():
                    yield first
                    yield from it

                res = ingest.stream_fold(
                    chunks(),
                    S.moment_fold_step(),
                    n=n,
                    init=S.init_moment_carry(n, ingest.wire_dtype()),
                )
                stats = res.carry
            else:

                def partition_task(mat):
                    padded, true_rows = columnar.pad_rows(mat)
                    st = _moment_stats(jnp.asarray(padded))
                    return S.MomentStats(
                        jnp.asarray(true_rows, st.count.dtype), st.total, st.total_sq
                    )

                from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks

                partials = run_partition_tasks(partition_task, list(ds.matrices()))
                stats = tree_reduce(partials, S.combine_moment_stats)
            mean, std = _finalize(stats)
        model = StandardScalerModel(
            uid=self.uid, mean=np.asarray(mean), std=np.asarray(std)
        )
        return self._copyValues(model)


class StandardScalerModel(_ScalerParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        mean: np.ndarray | None = None,
        std: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.mean = None if mean is None else np.asarray(mean)
        self.std = None if std is None else np.asarray(std)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        out = _standardize(
            jnp.asarray(mat),
            jnp.asarray(self.mean, dtype=mat.dtype),
            jnp.asarray(self.std, dtype=mat.dtype),
            with_mean=self.getWithMean(),
            with_std=self.getWithStd(),
        )
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("scaler transform"):
            return columnar.apply_column_transform(
                dataset, self._paramMap.get("inputCol"), self.getOutputCol(), self._scale
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"mean": self.mean, "std": self.std}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, mean=data["mean"], std=data["std"])

    # -- stock pyspark.ml interop (layout="spark"): Spark persists
    # Row(std: Vector, mean: Vector) in that order --------------------------
    _SPARK_ML_CLASS = "org.apache.spark.ml.feature.StandardScalerModel"
    _SPARK_ML_PARAMS = ("withMean", "withStd", "inputCol", "outputCol")

    def _saveSparkML(self, path: str) -> None:
        _save_spark_ml_vectors(self, path, {"std": self.std, "mean": self.mean})

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "StandardScalerModel":
        from spark_rapids_ml_tpu.utils import persistence as P

        return cls(
            uid=meta["uid"],
            mean=P.struct_to_vector(table.column("mean")[0].as_py()),
            std=P.struct_to_vector(table.column("std")[0].as_py()),
        )


_range_stats = jax.jit(S.range_stats)


def _fit_range_stats(self, dataset: Any, num_partitions: int | None):
    """Shared distributed fit for the range-summary scalers: one masked
    reduction per partition, elementwise-min/max tree reduce — the same
    monoid schedule as StandardScaler's moments."""
    input_col = self._paramMap.get("inputCol")
    ds = columnar.PartitionedDataset.from_any(dataset, input_col, num_partitions)
    with trace_range("scaler range stats"):

        def partition_task(mat):
            padded, true_rows = columnar.pad_rows(mat)
            return _range_stats(
                jnp.asarray(padded), jnp.asarray(true_rows)
            )

        from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks

        partials = run_partition_tasks(partition_task, list(ds.matrices()))
        return tree_reduce(partials, S.combine_range_stats)


class _MinMaxParams(HasInputCol, HasOutputCol):
    min = Param("min", "lower bound of the output range", float)
    max = Param("max", "upper bound of the output range", float)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(min=0.0, max=1.0, outputCol="scaled_features")

    def getMin(self) -> float:
        return self.getOrDefault("min")

    def getMax(self) -> float:
        return self.getOrDefault("max")

    def _check_range(self) -> None:
        if not self.getMin() < self.getMax():
            raise ValueError(
                f"min={self.getMin()} must be < max={self.getMax()}"
            )


class MinMaxScaler(_MinMaxParams, Estimator):
    """Rescale each feature to [min, max] (Spark ``MinMaxScaler``): fit
    learns per-feature observed E_min/E_max; constant features map to the
    output midpoint."""

    def setMin(self, value: float) -> "MinMaxScaler":
        return self._set(min=float(value))

    def setMax(self, value: float) -> "MinMaxScaler":
        return self._set(max=float(value))

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "MinMaxScalerModel":
        self._check_range()
        stats = _fit_range_stats(self, dataset, num_partitions)
        model = MinMaxScalerModel(
            uid=self.uid,
            originalMin=np.asarray(stats.min),
            originalMax=np.asarray(stats.max),
        )
        return self._copyValues(model)


class MinMaxScalerModel(_MinMaxParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        originalMin: np.ndarray | None = None,
        originalMax: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.originalMin = None if originalMin is None else np.asarray(originalMin)
        self.originalMax = None if originalMax is None else np.asarray(originalMax)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        out = _minmax_scale(
            jnp.asarray(mat),
            jnp.asarray(self.originalMin, dtype=mat.dtype),
            jnp.asarray(self.originalMax, dtype=mat.dtype),
            lo=self.getMin(),
            hi=self.getMax(),
        )
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("minmax transform"):
            return columnar.apply_column_transform(
                dataset, self._paramMap.get("inputCol"), self.getOutputCol(), self._scale
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"originalMin": self.originalMin, "originalMax": self.originalMax}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            originalMin=data["originalMin"],
            originalMax=data["originalMax"],
        )

    # -- stock pyspark.ml interop: Row(originalMin, originalMax) ------------
    _SPARK_ML_CLASS = "org.apache.spark.ml.feature.MinMaxScalerModel"
    _SPARK_ML_PARAMS = ("min", "max", "inputCol", "outputCol")

    def _saveSparkML(self, path: str) -> None:
        _save_spark_ml_vectors(
            self,
            path,
            {"originalMin": self.originalMin, "originalMax": self.originalMax},
        )

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "MinMaxScalerModel":
        from spark_rapids_ml_tpu.utils import persistence as P

        return cls(
            uid=meta["uid"],
            originalMin=P.struct_to_vector(table.column("originalMin")[0].as_py()),
            originalMax=P.struct_to_vector(table.column("originalMax")[0].as_py()),
        )


class _MaxAbsParams(HasInputCol, HasOutputCol):
    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(outputCol="scaled_features")


class MaxAbsScaler(_MaxAbsParams, Estimator):
    """Scale each feature to [-1, 1] by its max |x| (Spark ``MaxAbsScaler``)
    — sparsity-preserving: no centering, zeros stay zero."""

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "MaxAbsScalerModel":
        stats = _fit_range_stats(self, dataset, num_partitions)
        model = MaxAbsScalerModel(uid=self.uid, maxAbs=np.asarray(stats.max_abs))
        return self._copyValues(model)


class MaxAbsScalerModel(_MaxAbsParams, Model):
    def __init__(self, uid: str | None = None, maxAbs: np.ndarray | None = None):
        super().__init__(uid)
        self.maxAbs = None if maxAbs is None else np.asarray(maxAbs)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        out = _maxabs_scale(
            jnp.asarray(mat), jnp.asarray(self.maxAbs, dtype=mat.dtype)
        )
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("maxabs transform"):
            return columnar.apply_column_transform(
                dataset, self._paramMap.get("inputCol"), self.getOutputCol(), self._scale
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"maxAbs": self.maxAbs}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, maxAbs=data["maxAbs"])

    # -- stock pyspark.ml interop: Row(maxAbs) ------------------------------
    _SPARK_ML_CLASS = "org.apache.spark.ml.feature.MaxAbsScalerModel"
    _SPARK_ML_PARAMS = ("inputCol", "outputCol")

    def _saveSparkML(self, path: str) -> None:
        _save_spark_ml_vectors(self, path, {"maxAbs": self.maxAbs})

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "MaxAbsScalerModel":
        from spark_rapids_ml_tpu.utils import persistence as P

        return cls(
            uid=meta["uid"],
            maxAbs=P.struct_to_vector(table.column("maxAbs")[0].as_py()),
        )


class Normalizer(HasInputCol, HasOutputCol, Transformer):
    """Stateless row p-normalization (Spark ``Normalizer`` semantics)."""

    p = Param("p", "norm order (p >= 1; inf supported)", float)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(p=2.0, outputCol="normalized_features")

    def setP(self, value: float) -> "Normalizer":
        return self._set(p=value)

    def getP(self) -> float:
        return self.getOrDefault("p")

    def _normalize_matrix(self, mat: np.ndarray) -> np.ndarray:
        """[rows, n] → row-p-normalized [rows, n]; the one matrix fn both the
        local and the Spark (mapInArrow) transform paths run."""
        return np.asarray(_normalize(jnp.asarray(mat), self.getP()))

    def transform(self, dataset: Any) -> Any:
        with trace_range("normalize"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._normalize_matrix,
            )


class Binarizer(HasInputCol, HasOutputCol, Transformer):
    """Stateless thresholding (Spark ``Binarizer`` semantics): 1.0 where
    x > threshold, else 0.0 — strict inequality, like Spark's."""

    threshold = Param("threshold", "binarization threshold (strict >)", float)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(threshold=0.0, outputCol="binarized_features")

    def setThreshold(self, value: float) -> "Binarizer":
        return self._set(threshold=float(value))

    def getThreshold(self) -> float:
        return self.getOrDefault("threshold")

    def _binarize(self, mat: np.ndarray) -> np.ndarray:
        out = _binarize(jnp.asarray(mat), threshold=self.getThreshold())
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("binarize"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._binarize,
            )


_histogram_stats = jax.jit(S.histogram_stats, static_argnames=("bins",))
_quantile = jax.jit(S.quantile_from_histogram, static_argnames=())


def _quantiles_multi_fn(hist, mins, maxs, qs):
    # one program for a whole quantile grid (vmap shares the cumsum work
    # via XLA CSE instead of one dispatch per q)
    return jax.vmap(
        lambda q: S.quantile_from_histogram(hist, mins, maxs, q)
    )(qs)


_quantiles_multi = jax.jit(_quantiles_multi_fn)


def _fit_histogram(self, dataset, num_partitions, mins, maxs, bins: int):
    """Shared partitioned histogram pass (RobustScaler, QuantileDiscretizer):
    pad, jitted sketch, tree-reduced additive fold."""
    input_col = self._paramMap.get("inputCol")
    ds = columnar.PartitionedDataset.from_any(dataset, input_col, num_partitions)

    def task(mat):
        padded, true_rows = columnar.pad_rows(mat)
        return _histogram_stats(
            jnp.asarray(padded), jnp.asarray(true_rows), mins, maxs, bins=bins
        )

    from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks

    partials = run_partition_tasks(task, list(ds.matrices()))
    return tree_reduce(partials, lambda a, b: a + b)


class _RobustParams(HasInputCol, HasOutputCol):
    lower = Param("lower", "lower quantile of the scaling range", float)
    upper = Param("upper", "upper quantile of the scaling range", float)
    withCentering = Param("withCentering", "subtract the median", bool)
    withScaling = Param("withScaling", "divide by the quantile range", bool)
    numBins = Param(
        "numBins",
        "histogram resolution of the distributed quantile sketch "
        "(value-resolution error = feature range / numBins)",
        int,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            lower=0.25,
            upper=0.75,
            withCentering=False,
            withScaling=True,
            numBins=4096,
            outputCol="scaled_features",
        )

    def getLower(self) -> float:
        return self.getOrDefault("lower")

    def getUpper(self) -> float:
        return self.getOrDefault("upper")

    def getWithCentering(self) -> bool:
        return self.getOrDefault("withCentering")

    def getWithScaling(self) -> bool:
        return self.getOrDefault("withScaling")

    def getNumBins(self) -> int:
        return self.getOrDefault("numBins")

    def _check_quantile_bounds(self) -> None:
        if not 0.0 <= self.getLower() < self.getUpper() <= 1.0:
            raise ValueError(
                f"need 0 <= lower < upper <= 1, got "
                f"[{self.getLower()}, {self.getUpper()}]"
            )


class RobustScaler(_RobustParams, Estimator):
    """Quantile-based scaling (Spark ``RobustScaler`` surface: lower/upper
    default [0.25, 0.75], withCentering=False, withScaling=True).

    Distributed fit is TWO monoid passes, both mesh-reducible: the
    min/max range pass, then a per-feature fixed-bin histogram
    (``ops.scaler.histogram_stats`` — one scatter-add per column, additive
    across partitions) from which median and quantile range interpolate.
    Spark bounds quantile RANK error (approxQuantile's relativeError);
    this sketch bounds quantile VALUE error at range/numBins — a
    TPU-shaped trade (static shapes, no sorting) documented on the param.
    """

    def setLower(self, value: float) -> "RobustScaler":
        return self._set(lower=float(value))

    def setUpper(self, value: float) -> "RobustScaler":
        return self._set(upper=float(value))

    def setWithCentering(self, value: bool) -> "RobustScaler":
        return self._set(withCentering=bool(value))

    def setWithScaling(self, value: bool) -> "RobustScaler":
        return self._set(withScaling=bool(value))

    def setNumBins(self, value: int) -> "RobustScaler":
        if value < 2:
            raise ValueError(f"numBins must be >= 2, got {value}")
        return self._set(numBins=int(value))

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "RobustScalerModel":
        self._check_quantile_bounds()
        rstats = _fit_range_stats(self, dataset, num_partitions)
        mins = jnp.asarray(rstats.min)
        maxs = jnp.asarray(rstats.max)
        with trace_range("robust scaler histogram"):
            hist = _fit_histogram(
                self, dataset, num_partitions, mins, maxs, self.getNumBins()
            )
        median = np.asarray(_quantile(hist, mins, maxs, 0.5))
        lo = np.asarray(_quantile(hist, mins, maxs, self.getLower()))
        hi = np.asarray(_quantile(hist, mins, maxs, self.getUpper()))
        model = RobustScalerModel(
            uid=self.uid, median=median, range=hi - lo
        )
        return self._copyValues(model)


class RobustScalerModel(_RobustParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        median: np.ndarray | None = None,
        range: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.median = None if median is None else np.asarray(median)
        self.range = None if range is None else np.asarray(range)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        out = _robust_scale(
            jnp.asarray(mat),
            jnp.asarray(self.median, dtype=mat.dtype),
            jnp.asarray(self.range, dtype=mat.dtype),
            with_centering=self.getWithCentering(),
            with_scaling=self.getWithScaling(),
        )
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("robust transform"):
            return columnar.apply_column_transform(
                dataset, self._paramMap.get("inputCol"), self.getOutputCol(), self._scale
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"median": self.median, "range": self.range}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, median=data["median"], range=data["range"])

    # -- stock pyspark.ml interop: Row(range, median) -----------------------
    _SPARK_ML_CLASS = "org.apache.spark.ml.feature.RobustScalerModel"
    _SPARK_ML_PARAMS = (
        "lower", "upper", "withCentering", "withScaling", "inputCol", "outputCol",
    )

    def _saveSparkML(self, path: str) -> None:
        _save_spark_ml_vectors(
            self, path, {"range": self.range, "median": self.median}
        )

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "RobustScalerModel":
        from spark_rapids_ml_tpu.utils import persistence as P

        return cls(
            uid=meta["uid"],
            median=P.struct_to_vector(table.column("median")[0].as_py()),
            range=P.struct_to_vector(table.column("range")[0].as_py()),
        )


_nan_moment_stats = jax.jit(S.nan_moment_stats, static_argnames=("missing",))
_nan_range_stats = jax.jit(S.nan_range_stats, static_argnames=("missing",))
_impute = jax.jit(S.impute, static_argnames=("missing",))


def _histogram_with_missing_fn(x, true_rows, mins, maxs, *, bins, missing):
    return S.histogram_stats(
        x, true_rows, mins, maxs, bins=bins,
        valid=S.valid_mask(x, true_rows, missing),
    )


_histogram_with_missing = jax.jit(
    _histogram_with_missing_fn, static_argnames=("bins", "missing")
)


def _apply_empty_surrogate(count: np.ndarray, surrogate: np.ndarray) -> np.ndarray:
    """All-missing features cannot be imputed from data: surrogate 0.0
    (Spark ML's empty-stat convention) with a warning naming them — ONE
    definition shared by the local and Spark fit paths."""
    empty = count == 0
    if empty.any():
        import warnings

        warnings.warn(
            f"imputer: feature(s) {np.flatnonzero(empty).tolist()} "
            "have no valid entries; their surrogate is 0.0",
            UserWarning,
            stacklevel=3,
        )
        return np.where(empty, 0.0, surrogate)
    return surrogate


class _ImputerParams(HasInputCol, HasOutputCol):
    strategy = Param("strategy", "imputation strategy: mean | median", str)
    missingValue = Param(
        "missingValue",
        "the placeholder for missing entries (default NaN)",
        float,
    )
    numBins = Param(
        "numBins",
        "histogram resolution of the median sketch (see RobustScaler)",
        int,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            strategy="mean",
            missingValue=float("nan"),
            numBins=4096,
            outputCol="imputed_features",
        )

    def getStrategy(self) -> str:
        return self.getOrDefault("strategy")

    def getMissingValue(self) -> float:
        return self.getOrDefault("missingValue")

    def getNumBins(self) -> int:
        return self.getOrDefault("numBins")


class Imputer(_ImputerParams, Estimator):
    """Per-feature missing-value imputation over the features vector
    column (Spark ``Imputer`` strategies ``mean``/``median``, default
    missingValue NaN — surface adapted to this framework's vector-column
    convention; Spark's operates on separate numeric columns).

    Distributed fit: ``mean`` is one NaN-aware moments pass; ``median``
    reuses RobustScaler's histogram sketch with missing entries routed to
    the dropped overflow bin. Features with NO valid entries surrogate to
    0.0 (imputing from nothing is undefined; 0 is Spark ML's empty-stat
    convention) — a warning names them.
    """

    def setStrategy(self, value: str) -> "Imputer":
        if value not in ("mean", "median"):
            raise ValueError(
                f"strategy must be 'mean' or 'median', got {value!r} "
                "('mode' needs exact value counts, which the histogram "
                "sketch deliberately does not keep)"
            )
        return self._set(strategy=value)

    def setMissingValue(self, value: float) -> "Imputer":
        return self._set(missingValue=float(value))

    def setNumBins(self, value: int) -> "Imputer":
        if value < 2:
            raise ValueError(f"numBins must be >= 2, got {value}")
        return self._set(numBins=int(value))

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "ImputerModel":
        input_col = self._paramMap.get("inputCol")
        missing = self.getMissingValue()
        ds = columnar.PartitionedDataset.from_any(
            dataset, input_col, num_partitions
        )
        mats = list(ds.matrices())
        from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks

        with trace_range("imputer fit"):
            if self.getStrategy() == "mean":

                def task(mat):
                    padded, true_rows = columnar.pad_rows(mat)
                    return _nan_moment_stats(
                        jnp.asarray(padded),
                        jnp.asarray(true_rows),
                        missing=missing,
                    )

                stats = tree_reduce(
                    run_partition_tasks(task, mats), S.combine_nan_moment_stats
                )
                count = np.asarray(stats.count)
                surrogate = np.asarray(stats.total) / np.maximum(count, 1.0)
            else:  # median

                def rtask(mat):
                    padded, true_rows = columnar.pad_rows(mat)
                    return _nan_range_stats(
                        jnp.asarray(padded),
                        jnp.asarray(true_rows),
                        missing=missing,
                    )

                rstats = tree_reduce(
                    run_partition_tasks(rtask, mats), S.combine_nan_range_stats
                )
                count = np.asarray(rstats.count)
                # all-missing features carry +/-inf bounds; neutralize any
                # non-finite bound so the histogram pass stays finite (the
                # resulting quantile is overwritten by the empty-surrogate
                # epilogue below)
                mins = jnp.asarray(
                    np.where(np.isfinite(rstats.min), rstats.min, 0.0)
                )
                maxs = jnp.asarray(
                    np.where(np.isfinite(rstats.max), rstats.max, 0.0)
                )
                bins = self.getNumBins()

                def htask(mat):
                    padded, true_rows = columnar.pad_rows(mat)
                    return _histogram_with_missing(
                        jnp.asarray(padded), jnp.asarray(true_rows),
                        mins, maxs, bins=bins, missing=missing,
                    )

                hist = tree_reduce(
                    run_partition_tasks(htask, mats), lambda a, b: a + b
                )
                surrogate = np.asarray(
                    _quantile(hist, mins, maxs, 0.5)
                )
            surrogate = _apply_empty_surrogate(count, surrogate)
        model = ImputerModel(uid=self.uid, surrogate=surrogate)
        return self._copyValues(model)


class ImputerModel(_ImputerParams, Model):
    def __init__(self, uid: str | None = None, surrogate: np.ndarray | None = None):
        super().__init__(uid)
        self.surrogate = None if surrogate is None else np.asarray(surrogate)

    def _fill(self, mat: np.ndarray) -> np.ndarray:
        out = _impute(
            jnp.asarray(mat),
            jnp.asarray(self.surrogate, dtype=mat.dtype),
            missing=self.getMissingValue(),
        )
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("impute"):
            return columnar.apply_column_transform(
                dataset, self._paramMap.get("inputCol"), self.getOutputCol(), self._fill
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"surrogate": self.surrogate}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, surrogate=data["surrogate"])

    def _saveSparkML(self, path: str) -> None:
        raise NotImplementedError(
            "stock Spark ML's Imputer operates on separate numeric input "
            "columns (surrogateDF layout), which cannot represent this "
            "vector-column model; use the native layout"
        )


class ElementwiseProduct(HasInputCol, HasOutputCol, Transformer):
    """Stateless per-feature rescaling by a fixed weight vector (Spark
    ``ElementwiseProduct``: output = x ∘ scalingVec)."""

    scalingVec = Param("scalingVec", "the componentwise multiplier", None)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(outputCol="scaled_features")

    def setScalingVec(self, value) -> "ElementwiseProduct":
        return self._set(scalingVec=np.asarray(value, dtype=np.float64))

    def getScalingVec(self) -> np.ndarray:
        return np.asarray(self.getOrDefault("scalingVec"))

    def _apply(self, mat: np.ndarray) -> np.ndarray:
        w = self.getScalingVec()
        if mat.shape[1] != len(w):
            raise ValueError(
                f"scalingVec has {len(w)} entries, features have "
                f"{mat.shape[1]}"
            )
        # multiply in float64 like Spark: downcasting w to an integer
        # input dtype would truncate fractional weights to zero
        return mat * w[None, :]

    def transform(self, dataset: Any) -> Any:
        if not self.isSet("scalingVec"):
            raise ValueError("scalingVec must be set before transform")
        with trace_range("elementwise product"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._apply,
            )


class VectorSlicer(HasInputCol, HasOutputCol, Transformer):
    """Stateless feature subsetting by indices (Spark ``VectorSlicer``'s
    ``indices`` surface; name-based slicing needs column metadata this
    framework's ArrayType convention does not carry)."""

    indices = Param("indices", "feature indices to keep, in output order", None)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(outputCol="sliced_features")

    def setIndices(self, value) -> "VectorSlicer":
        idx = np.asarray(value, dtype=np.int32)
        if idx.ndim != 1 or len(idx) == 0:
            raise ValueError("indices must be a non-empty 1-D sequence")
        if len(np.unique(idx)) != len(idx):
            raise ValueError(f"indices must be unique, got {idx.tolist()}")
        if (idx < 0).any():
            raise ValueError(f"indices must be non-negative, got {idx.tolist()}")
        return self._set(indices=idx)

    def getIndices(self) -> np.ndarray:
        return np.asarray(self.getOrDefault("indices"))

    def _slice(self, mat: np.ndarray) -> np.ndarray:
        idx = self.getIndices()
        if idx.max() >= mat.shape[1]:
            raise ValueError(
                f"index {int(idx.max())} out of bounds for "
                f"{mat.shape[1]} features"
            )
        return np.ascontiguousarray(mat[:, idx])

    def transform(self, dataset: Any) -> Any:
        if not self.isSet("indices"):
            raise ValueError("indices must be set before transform")
        with trace_range("vector slicer"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._slice,
            )


_dct2 = jax.jit(S.dct2, static_argnames=("inverse",))


class DCT(HasInputCol, HasOutputCol, Transformer):
    """Row-wise unitary Discrete Cosine Transform (Spark ``DCT``: DCT-II
    scaled so the representing matrix is orthonormal; ``inverse=True``
    applies DCT-III, the exact inverse). One [n, n] cosine-basis matmul
    per batch — MXU-shaped, basis cached per feature count."""

    inverse = Param("inverse", "apply the inverse transform (DCT-III)", bool)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(inverse=False, outputCol="dct_features")

    def setInverse(self, value: bool) -> "DCT":
        return self._set(inverse=bool(value))

    def getInverse(self) -> bool:
        return self.getOrDefault("inverse")

    def _apply_dct(self, mat: np.ndarray) -> np.ndarray:
        # promote to float BEFORE casting the basis to the input dtype:
        # unitary-DCT coefficients are all |b| < 1, so an integer input
        # dtype would truncate the whole basis to zero (the same trap
        # ElementwiseProduct guards)
        if not np.issubdtype(mat.dtype, np.floating):
            mat = mat.astype(np.float64)
        xm = jnp.asarray(mat)  # one H2D transfer per batch
        basis = _dct_basis(mat.shape[1])
        out = _dct2(xm, basis.astype(xm.dtype), inverse=self.getInverse())
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("dct"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._apply_dct,
            )


@functools.lru_cache(maxsize=32)
def _dct_basis(n: int):
    return S.dct2_matrix(n)


@functools.lru_cache(maxsize=64)
def _poly_plan(n: int, degree: int):
    """Monomial plan for PolynomialExpansion in SPARK's exact output order.

    Spark MLlib expands recursively —
    ``E(k, d) = E(k-1, d) ++ x_k · ([1] ++ E(k, d-1))`` — giving
    ``(x, x·x, y, x·y, y·y)`` for (x, y) at degree 2 (the documented
    example). Built ITERATIVELY (recursion depth would scale with n):
    level d's list is the concatenation over k of "new parts"
    ``[x_k] ++ x_k·E(k, d-1)``, with E(k, d-1) maintained incrementally.
    Each term records (parent, created-with feature), so evaluation is one
    multiply per monomial, vectorizable by degree wave. Returns
    (parents [m] int32, features [m] int32, term_degrees [m] int32) over
    the FINAL level's order.
    """
    # new_parts[d][k-1] = list of (key, feat); key = frozenset((feat, exp))
    new_parts = [None] * (degree + 1)
    for d in range(1, degree + 1):
        parts_d = []
        running_prev = []  # E(k, d-1), extended as k advances
        for k in range(1, n + 1):
            feat = k - 1
            if d > 1:
                running_prev.extend(new_parts[d - 1][k - 1])
            part = [(frozenset([(feat, 1)]), feat)]
            for key, _ in running_prev:
                dd = dict(key)
                dd[feat] = dd.get(feat, 0) + 1
                part.append((frozenset(dd.items()), feat))
            parts_d.append(part)
        new_parts[d] = parts_d

    order = [t for part in new_parts[degree] for t in part]
    index = {key: i for i, (key, _) in enumerate(order)}
    m = len(order)
    parents = np.empty(m, dtype=np.int32)
    features = np.empty(m, dtype=np.int32)
    degrees = np.empty(m, dtype=np.int32)
    for i, (key, feat) in enumerate(order):
        dd = dict(key)
        degrees[i] = sum(dd.values())
        dd[feat] -= 1
        if dd[feat] == 0:
            del dd[feat]
        parents[i] = index[frozenset(dd.items())] if dd else -1
        features[i] = feat
    return parents, features, degrees


class PolynomialExpansion(HasInputCol, HasOutputCol, Transformer):
    """Polynomial feature expansion in Spark MLlib's exact output order
    (all monomials of total degree 1..degree, NO bias term): degree 2 on
    (x, y) yields (x, x·x, y, x·y, y·y). Output width grows as
    C(n+d, d) − 1 — guarded at 100k terms."""

    degree = Param("degree", "maximum monomial degree (>= 1)", int)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(degree=2, outputCol="poly_features")

    def setDegree(self, value: int) -> "PolynomialExpansion":
        if value < 1:
            raise ValueError(f"degree must be >= 1, got {value}")
        return self._set(degree=int(value))

    def getDegree(self) -> int:
        return self.getOrDefault("degree")

    def _expand(self, mat: np.ndarray) -> np.ndarray:
        import math

        n = mat.shape[1]
        d = self.getDegree()
        m = math.comb(n + d, d) - 1
        if m > 100_000:
            raise ValueError(
                f"degree={d} on {n} features expands to {m} terms; "
                "cap is 100000 — lower the degree or select features first"
            )
        parents, features, degrees = _poly_plan(n, d)
        if not np.issubdtype(mat.dtype, np.floating):
            mat = mat.astype(np.float64)
        out = np.empty((mat.shape[0], len(parents)), dtype=mat.dtype)
        # every degree-t term's parent has degree t-1, so evaluation is d
        # fancy-indexed waves, not an O(m) Python loop
        for t in range(1, d + 1):
            idx = np.flatnonzero(degrees == t)
            if t == 1:
                out[:, idx] = mat[:, features[idx]]
            else:
                out[:, idx] = out[:, parents[idx]] * mat[:, features[idx]]
        return out

    def transform(self, dataset: Any) -> Any:
        with trace_range("polynomial expansion"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._expand,
            )
