"""StandardScaler and Normalizer — the preprocessing stages of BASELINE
config 4 ("StandardScaler / Normalizer fused into the PCA input pipeline").

API shape follows Spark MLlib (the reference's host framework): StandardScaler
is an Estimator with ``withMean`` (default False) / ``withStd`` (default
True); Normalizer is a stateless Transformer with a ``p`` norm param
(default 2.0). Fit statistics use the same partition-monoid + tree-reduce
design as PCA's GramStats, so the distributed story is identical.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model, Transformer
from spark_rapids_ml_tpu.models.params import HasInputCol, HasOutputCol, Param
from spark_rapids_ml_tpu.ops import scaler as S
from spark_rapids_ml_tpu.parallel.tree_aggregate import tree_reduce
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.utils.tracing import trace_range

_moment_stats = jax.jit(S.moment_stats)
_finalize = jax.jit(S.finalize_moments)


def _save_spark_ml_vectors(model, path: str, vectors: dict) -> None:
    """One stock-layout writer for the scaler family: filtered params +
    ordered dense-vector data row (see persistence.save_spark_ml_vector_model)."""
    from spark_rapids_ml_tpu.models.base import spark_set_params
    from spark_rapids_ml_tpu.utils import persistence as P

    P.save_spark_ml_vector_model(
        path,
        class_name=model._SPARK_ML_CLASS,
        uid=model.uid,
        params={
            k: v
            for k, v in spark_set_params(model).items()
            if k in model._SPARK_ML_PARAMS
        },
        vectors=vectors,
    )


class _ScalerParams(HasInputCol, HasOutputCol):
    withMean = Param("withMean", "center features before scaling", bool)
    withStd = Param("withStd", "scale features to unit sample std", bool)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(withMean=False, withStd=True, outputCol="scaled_features")

    def getWithMean(self) -> bool:
        return self.getOrDefault("withMean")

    def getWithStd(self) -> bool:
        return self.getOrDefault("withStd")


class StandardScaler(_ScalerParams, Estimator):
    def setWithMean(self, value: bool) -> "StandardScaler":
        return self._set(withMean=value)

    def setWithStd(self, value: bool) -> "StandardScaler":
        return self._set(withStd=value)

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "StandardScalerModel":
        input_col = self._paramMap.get("inputCol")
        ds = columnar.PartitionedDataset.from_any(dataset, input_col, num_partitions)
        with trace_range("scaler moments"):

            def partition_task(mat):
                padded, true_rows = columnar.pad_rows(mat)
                st = _moment_stats(jnp.asarray(padded))
                return S.MomentStats(
                    jnp.asarray(true_rows, st.count.dtype), st.total, st.total_sq
                )

            from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks

            partials = run_partition_tasks(partition_task, list(ds.matrices()))
            stats = tree_reduce(partials, S.combine_moment_stats)
            mean, std = _finalize(stats)
        model = StandardScalerModel(
            uid=self.uid, mean=np.asarray(mean), std=np.asarray(std)
        )
        return self._copyValues(model)


class StandardScalerModel(_ScalerParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        mean: np.ndarray | None = None,
        std: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.mean = None if mean is None else np.asarray(mean)
        self.std = None if std is None else np.asarray(std)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        out = jax.jit(
            S.standardize, static_argnames=("with_mean", "with_std")
        )(
            jnp.asarray(mat),
            jnp.asarray(self.mean, dtype=mat.dtype),
            jnp.asarray(self.std, dtype=mat.dtype),
            with_mean=self.getWithMean(),
            with_std=self.getWithStd(),
        )
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("scaler transform"):
            return columnar.apply_column_transform(
                dataset, self._paramMap.get("inputCol"), self.getOutputCol(), self._scale
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"mean": self.mean, "std": self.std}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, mean=data["mean"], std=data["std"])

    # -- stock pyspark.ml interop (layout="spark"): Spark persists
    # Row(std: Vector, mean: Vector) in that order --------------------------
    _SPARK_ML_CLASS = "org.apache.spark.ml.feature.StandardScalerModel"
    _SPARK_ML_PARAMS = ("withMean", "withStd", "inputCol", "outputCol")

    def _saveSparkML(self, path: str) -> None:
        _save_spark_ml_vectors(self, path, {"std": self.std, "mean": self.mean})

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "StandardScalerModel":
        from spark_rapids_ml_tpu.utils import persistence as P

        return cls(
            uid=meta["uid"],
            mean=P.struct_to_vector(table.column("mean")[0].as_py()),
            std=P.struct_to_vector(table.column("std")[0].as_py()),
        )


_range_stats = jax.jit(S.range_stats)


def _fit_range_stats(self, dataset: Any, num_partitions: int | None):
    """Shared distributed fit for the range-summary scalers: one masked
    reduction per partition, elementwise-min/max tree reduce — the same
    monoid schedule as StandardScaler's moments."""
    input_col = self._paramMap.get("inputCol")
    ds = columnar.PartitionedDataset.from_any(dataset, input_col, num_partitions)
    with trace_range("scaler range stats"):

        def partition_task(mat):
            padded, true_rows = columnar.pad_rows(mat)
            return _range_stats(
                jnp.asarray(padded), jnp.asarray(true_rows)
            )

        from spark_rapids_ml_tpu.parallel.executor import run_partition_tasks

        partials = run_partition_tasks(partition_task, list(ds.matrices()))
        return tree_reduce(partials, S.combine_range_stats)


class _MinMaxParams(HasInputCol, HasOutputCol):
    min = Param("min", "lower bound of the output range", float)
    max = Param("max", "upper bound of the output range", float)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(min=0.0, max=1.0, outputCol="scaled_features")

    def getMin(self) -> float:
        return self.getOrDefault("min")

    def getMax(self) -> float:
        return self.getOrDefault("max")


class MinMaxScaler(_MinMaxParams, Estimator):
    """Rescale each feature to [min, max] (Spark ``MinMaxScaler``): fit
    learns per-feature observed E_min/E_max; constant features map to the
    output midpoint."""

    def setMin(self, value: float) -> "MinMaxScaler":
        return self._set(min=float(value))

    def setMax(self, value: float) -> "MinMaxScaler":
        return self._set(max=float(value))

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "MinMaxScalerModel":
        if not self.getMin() < self.getMax():
            raise ValueError(
                f"min={self.getMin()} must be < max={self.getMax()}"
            )
        stats = _fit_range_stats(self, dataset, num_partitions)
        model = MinMaxScalerModel(
            uid=self.uid,
            originalMin=np.asarray(stats.min),
            originalMax=np.asarray(stats.max),
        )
        return self._copyValues(model)


class MinMaxScalerModel(_MinMaxParams, Model):
    def __init__(
        self,
        uid: str | None = None,
        originalMin: np.ndarray | None = None,
        originalMax: np.ndarray | None = None,
    ):
        super().__init__(uid)
        self.originalMin = None if originalMin is None else np.asarray(originalMin)
        self.originalMax = None if originalMax is None else np.asarray(originalMax)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        out = jax.jit(S.minmax_scale, static_argnames=("lo", "hi"))(
            jnp.asarray(mat),
            jnp.asarray(self.originalMin, dtype=mat.dtype),
            jnp.asarray(self.originalMax, dtype=mat.dtype),
            lo=self.getMin(),
            hi=self.getMax(),
        )
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("minmax transform"):
            return columnar.apply_column_transform(
                dataset, self._paramMap.get("inputCol"), self.getOutputCol(), self._scale
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"originalMin": self.originalMin, "originalMax": self.originalMax}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(
            uid=uid,
            originalMin=data["originalMin"],
            originalMax=data["originalMax"],
        )

    # -- stock pyspark.ml interop: Row(originalMin, originalMax) ------------
    _SPARK_ML_CLASS = "org.apache.spark.ml.feature.MinMaxScalerModel"
    _SPARK_ML_PARAMS = ("min", "max", "inputCol", "outputCol")

    def _saveSparkML(self, path: str) -> None:
        _save_spark_ml_vectors(
            self,
            path,
            {"originalMin": self.originalMin, "originalMax": self.originalMax},
        )

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "MinMaxScalerModel":
        from spark_rapids_ml_tpu.utils import persistence as P

        return cls(
            uid=meta["uid"],
            originalMin=P.struct_to_vector(table.column("originalMin")[0].as_py()),
            originalMax=P.struct_to_vector(table.column("originalMax")[0].as_py()),
        )


class _MaxAbsParams(HasInputCol, HasOutputCol):
    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(outputCol="scaled_features")


class MaxAbsScaler(_MaxAbsParams, Estimator):
    """Scale each feature to [-1, 1] by its max |x| (Spark ``MaxAbsScaler``)
    — sparsity-preserving: no centering, zeros stay zero."""

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "MaxAbsScalerModel":
        stats = _fit_range_stats(self, dataset, num_partitions)
        model = MaxAbsScalerModel(uid=self.uid, maxAbs=np.asarray(stats.max_abs))
        return self._copyValues(model)


class MaxAbsScalerModel(_MaxAbsParams, Model):
    def __init__(self, uid: str | None = None, maxAbs: np.ndarray | None = None):
        super().__init__(uid)
        self.maxAbs = None if maxAbs is None else np.asarray(maxAbs)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        out = jax.jit(S.maxabs_scale)(
            jnp.asarray(mat), jnp.asarray(self.maxAbs, dtype=mat.dtype)
        )
        return np.asarray(out)

    def transform(self, dataset: Any) -> Any:
        with trace_range("maxabs transform"):
            return columnar.apply_column_transform(
                dataset, self._paramMap.get("inputCol"), self.getOutputCol(), self._scale
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"maxAbs": self.maxAbs}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, maxAbs=data["maxAbs"])

    # -- stock pyspark.ml interop: Row(maxAbs) ------------------------------
    _SPARK_ML_CLASS = "org.apache.spark.ml.feature.MaxAbsScalerModel"
    _SPARK_ML_PARAMS = ("inputCol", "outputCol")

    def _saveSparkML(self, path: str) -> None:
        _save_spark_ml_vectors(self, path, {"maxAbs": self.maxAbs})

    @classmethod
    def _fromSparkML(cls, meta: dict, table) -> "MaxAbsScalerModel":
        from spark_rapids_ml_tpu.utils import persistence as P

        return cls(
            uid=meta["uid"],
            maxAbs=P.struct_to_vector(table.column("maxAbs")[0].as_py()),
        )


class Normalizer(HasInputCol, HasOutputCol, Transformer):
    """Stateless row p-normalization (Spark ``Normalizer`` semantics)."""

    p = Param("p", "norm order (p >= 1; inf supported)", float)

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(p=2.0, outputCol="normalized_features")

    def setP(self, value: float) -> "Normalizer":
        return self._set(p=value)

    def getP(self) -> float:
        return self.getOrDefault("p")

    def _normalize_matrix(self, mat: np.ndarray) -> np.ndarray:
        """[rows, n] → row-p-normalized [rows, n]; the one matrix fn both the
        local and the Spark (mapInArrow) transform paths run."""
        return np.asarray(
            jax.jit(S.normalize, static_argnums=(1,))(
                jnp.asarray(mat), self.getP()
            )
        )

    def transform(self, dataset: Any) -> Any:
        with trace_range("normalize"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._normalize_matrix,
            )
