"""OneVsRest — pyspark.ml's multiclass meta-estimator, natively.

Wraps any binary classifier whose model emits a margin/score (LinearSVC,
GBTClassifier, binary LogisticRegression): fit trains C one-vs-rest
copies (label == c → 1.0), predict takes the class whose model scores its
positive side highest — pyspark.ml.classification.OneVsRest semantics.

The per-class fits are independent, so the meta-layer adds no new
distributed machinery: each sub-fit uses whatever distribution its
estimator implements.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model, Saveable
from spark_rapids_ml_tpu.models.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
)
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range


def _positive_score(model, mat: np.ndarray) -> np.ndarray:
    """[rows] 'how positive' score from a fitted binary model — the
    decision surface OneVsRest ranks classes on. Preference order matches
    what each model family exposes: probability of class 1, else the raw
    margin."""
    if hasattr(model, "proba_and_predictions"):
        proba, _ = model.proba_and_predictions(mat)
        proba = np.asarray(proba)
        return proba[:, 1] if proba.ndim == 2 else proba
    if hasattr(model, "predict_proba_matrix"):
        p = np.asarray(model.predict_proba_matrix(mat))
        return p[:, 1] if p.ndim == 2 else p
    if hasattr(model, "margins"):
        return np.asarray(model.margins(mat))
    raise TypeError(
        f"{type(model).__name__} exposes no probability or margin surface "
        "for OneVsRest scoring"
    )


class OneVsRest(HasFeaturesCol, HasLabelCol, HasPredictionCol, Estimator):
    def __init__(self, uid: str | None = None, classifier=None, **kwargs):
        super().__init__(uid, **kwargs)
        self.classifier = classifier
        self._setDefault(
            featuresCol="features", labelCol="label",
            predictionCol="prediction",
        )

    def setClassifier(self, value) -> "OneVsRest":
        self.classifier = value
        return self

    def getClassifier(self):
        return self.classifier

    def fit(self, dataset: Any, num_partitions: int | None = None):
        if self.classifier is None:
            raise ValueError("setClassifier(...) before fit")
        parts = columnar.labeled_partitions(
            dataset,
            self.getOrDefault("featuresCol"),
            self.getOrDefault("labelCol"),
            None,  # sub-fits re-partition themselves below
            weight_col=None,
        )
        x = np.concatenate([p[0] for p in parts])
        y = np.concatenate([p[1] for p in parts])
        return self._fit_xy(x, y, num_partitions)

    def _fit_xy(
        self, x: np.ndarray, y: np.ndarray, num_partitions: int | None = None
    ):
        """The per-class training loop from pre-extracted arrays — shared
        with the Spark wrapper, whose collection path already produced
        (x, y) (re-running fit's ingestion would copy the matrix twice)."""
        if self.classifier is None:
            raise ValueError("setClassifier(...) before fit")
        classes = np.unique(y)
        if not np.all(classes == np.round(classes)) or classes.min() < 0:
            raise ValueError(
                f"OneVsRest requires integer class labels 0..C-1, got "
                f"{classes[:8]}"
            )
        n_classes = int(classes.max()) + 1
        if n_classes < 2:
            raise ValueError("OneVsRest needs at least 2 classes")
        models = []
        with trace_range("one-vs-rest fit"):
            for c in range(n_classes):
                est = self.classifier.copy()
                models.append(
                    est.fit(
                        (x, (y == c).astype(np.float64)), num_partitions
                    )
                )
        model = OneVsRestModel(uid=self.uid, models=models)
        return self._copyValues(model)

    # persistence: the classifier template lives in a subdirectory (the
    # pyspark OneVsRest writer's shape); base save handles params/layout
    def save(
        self, path: str, overwrite: bool = False, layout: str = "native"
    ) -> None:
        if self.classifier is None:
            raise ValueError(
                "OneVsRest has no classifier set; nothing meaningful to save"
            )
        super().save(path, overwrite=overwrite, layout=layout)
        from spark_rapids_ml_tpu.utils import persistence

        self.classifier.save(persistence._FS(path).join("classifier"))

    @classmethod
    def load(cls, path: str) -> "OneVsRest":
        from spark_rapids_ml_tpu.utils import persistence

        meta = persistence.load_metadata(path)
        classifier = Saveable.load(persistence._FS(path).join("classifier"))
        instance = cls(uid=meta["uid"], classifier=classifier)
        instance._restoreParamState(meta)
        return instance


class OneVsRestModel(
    HasFeaturesCol, HasLabelCol, HasPredictionCol, Model
):
    def __init__(self, uid: str | None = None, models: list | None = None):
        super().__init__(uid)
        self.models = list(models or [])
        self._setDefault(
            featuresCol="features", labelCol="label",
            predictionCol="prediction",
        )

    @property
    def numClasses(self) -> int:
        return len(self.models)

    def _predict_matrix(self, mat: np.ndarray) -> np.ndarray:
        scores = np.stack(
            [_positive_score(m, mat) for m in self.models], axis=1
        )
        return np.argmax(scores, axis=1).astype(np.float64)

    def transform(self, dataset: Any) -> Any:
        with trace_range("one-vs-rest transform"):
            return columnar.apply_column_transform(
                dataset,
                self.getOrDefault("featuresCol"),
                self.getOrDefault("predictionCol"),
                self._predict_matrix,
            )

    # persistence: one subdirectory per class model; the base save handles
    # params/overwrite/layout validation, ``_saveData`` records the count,
    # and the custom ``load`` (reachable from generic Saveable.load via
    # the composite-model delegation in models/base.py) reads the subdirs
    def _saveData(self) -> dict[str, np.ndarray]:
        return {"numClasses": np.asarray([len(self.models)])}

    def save(
        self, path: str, overwrite: bool = False, layout: str = "native"
    ) -> None:
        super().save(path, overwrite=overwrite, layout=layout)
        from spark_rapids_ml_tpu.utils import persistence

        fs = persistence._FS(path)
        for c, m in enumerate(self.models):
            m.save(fs.join(f"class-{c}"))

    @classmethod
    def load(cls, path: str) -> "OneVsRestModel":
        from spark_rapids_ml_tpu.utils import persistence

        meta = persistence.load_metadata(path)
        n = int(persistence.load_arrays(path)["numClasses"][0])
        fs = persistence._FS(path)
        models = [Saveable.load(fs.join(f"class-{c}")) for c in range(n)]
        instance = cls(uid=meta["uid"], models=models)
        instance._restoreParamState(meta)
        return instance
