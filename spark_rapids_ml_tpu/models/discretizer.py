"""QuantileDiscretizer / Bucketizer — binning on the histogram sketch.

Spark's pair operates on a single Double column (``QuantileDiscretizer.fit``
returns a ``Bucketizer`` with one splits array). This framework's data unit
is the features VECTOR column, so the adaptation mirrors ``Imputer``'s:
``Bucketizer`` applies ONE splits array elementwise across the vector, and
``QuantileDiscretizer`` learns PER-FEATURE splits (a [n, buckets+1] matrix —
each feature gets its own quantile grid, which a single-splits Bucketizer
cannot represent, hence the dedicated model class). Quantiles come from the
same distributed fixed-bin histogram sketch RobustScaler uses
(ops/scaler.py ``histogram_stats``), so the fit is two mesh-reducible
passes at any scale. Skewed data can collapse adjacent quantiles into
duplicate split points; those become EMPTY buckets (ids stay valid and
dense in [0, numBuckets)), where Spark instead reduces the bucket count
with a warning — both are lossless, this one keeps the output arity static
(XLA-friendly).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.models.base import Estimator, Model, Transformer
from spark_rapids_ml_tpu.models.params import (
    HasInputCol,
    HasOutputCol,
    Param,
)
from spark_rapids_ml_tpu.ops import scaler as S
from spark_rapids_ml_tpu.utils import columnar
from spark_rapids_ml_tpu.telemetry import trace_range

_bucketize = jax.jit(S.bucketize)


def check_finite_range(mins: np.ndarray, maxs: np.ndarray) -> None:
    """Reject NaN/Inf-poisoned feature ranges — ONE message shared by the
    local and Spark fit paths."""
    mins, maxs = np.asarray(mins), np.asarray(maxs)
    if np.isfinite(mins).all() and np.isfinite(maxs).all():
        return
    bad = np.flatnonzero(~np.isfinite(mins) | ~np.isfinite(maxs))
    raise ValueError(
        f"feature(s) {bad.tolist()} contain NaN/Inf values; "
        "QuantileDiscretizer needs finite data — impute first "
        "(spark_rapids_ml_tpu.Imputer)"
    )


def splits_from_histogram(hist, mins, maxs, num_buckets: int) -> np.ndarray:
    """[n, num_buckets+1] per-feature quantile grid with ±inf outer edges,
    interior splits from one vmapped quantile program — the split assembly
    both fit paths share."""
    from spark_rapids_ml_tpu.models.scaler import _quantiles_multi

    b = num_buckets
    n = hist.shape[0]
    splits = np.empty((n, b + 1))
    splits[:, 0] = -np.inf
    splits[:, b] = np.inf
    qs = jnp.asarray(np.arange(1, b) / b)
    splits[:, 1:b] = np.asarray(
        _quantiles_multi(
            jnp.asarray(hist), jnp.asarray(mins), jnp.asarray(maxs), qs
        )
    ).T
    return splits


class Bucketizer(HasInputCol, HasOutputCol, Transformer):
    """Stateless binning of every feature against ONE sorted splits array
    (see module docstring for the vector adaptation). ``handleInvalid``:
    ``'error'`` (default) raises on values outside [splits[0], splits[-1]];
    ``'keep'`` routes them to an extra bucket with id ``len(splits) - 1``.
    Use ±inf endpoints to make every value in-range, like Spark.
    """

    splits = Param("splits", "sorted bucket boundaries (len >= 3)", None)
    handleInvalid = Param(
        "handleInvalid", "out-of-range policy: error | keep", str
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(handleInvalid="error", outputCol="bucketed_features")

    def setSplits(self, value) -> "Bucketizer":
        sp = np.asarray(value, dtype=np.float64)
        if sp.ndim != 1 or len(sp) < 3:
            raise ValueError(
                "splits must be a 1-D sequence of at least 3 boundaries"
            )
        if not np.all(np.diff(sp) > 0):
            raise ValueError(f"splits must be strictly increasing, got {sp}")
        return self._set(splits=sp)

    def getSplits(self) -> np.ndarray:
        return np.asarray(self.getOrDefault("splits"))

    def setHandleInvalid(self, value: str) -> "Bucketizer":
        if value not in ("error", "keep"):
            raise ValueError(
                "handleInvalid must be 'error' or 'keep' ('skip' would "
                "drop rows, which a columnar map cannot do)"
            )
        return self._set(handleInvalid=value)

    def _bucket(self, mat: np.ndarray) -> np.ndarray:
        sp = self.getSplits()
        lo, hi = sp[0], sp[-1]
        # NaN is invalid too (comparisons are NaN-blind): Spark raises on
        # it in 'error' mode and routes it to the invalid bucket in 'keep'
        invalid = np.isnan(mat) | (mat < lo) | (mat > hi)
        if invalid.any():
            if self.getOrDefault("handleInvalid") == "error":
                bad = np.argwhere(invalid)[0]
                raise ValueError(
                    f"value {mat[tuple(bad)]} at row {bad[0]} feature "
                    f"{bad[1]} is outside [{lo}, {hi}] (or NaN); widen "
                    "splits (±inf endpoints) or setHandleInvalid('keep')"
                )
        splits = np.broadcast_to(sp, (mat.shape[1], len(sp)))
        ids = np.asarray(_bucketize(jnp.asarray(mat), jnp.asarray(splits)))
        if invalid.any():  # handleInvalid == "keep"
            ids = np.where(invalid, float(len(sp) - 1), ids)
        return ids

    def transform(self, dataset: Any) -> Any:
        if not self.isSet("splits"):
            raise ValueError("splits must be set before transform")
        with trace_range("bucketize"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._bucket,
            )


class _DiscretizerParams(HasInputCol, HasOutputCol):
    numBuckets = Param("numBuckets", "number of quantile buckets (>= 2)", int)
    numBins = Param(
        "numBins",
        "histogram resolution of the quantile sketch (see RobustScaler)",
        int,
    )

    def __init__(self, uid: str | None = None, **kwargs):
        super().__init__(uid, **kwargs)
        self._setDefault(
            numBuckets=2, numBins=4096, outputCol="bucketed_features"
        )

    def getNumBuckets(self) -> int:
        return self.getOrDefault("numBuckets")

    def getNumBins(self) -> int:
        return self.getOrDefault("numBins")


class QuantileDiscretizer(_DiscretizerParams, Estimator):
    """Learn per-feature quantile splits (numBuckets equal-frequency bins)
    from the distributed histogram sketch, then bin like Bucketizer with
    ±inf outer edges (every value lands in a bucket, matching Spark's
    fitted behavior)."""

    def setNumBuckets(self, value: int) -> "QuantileDiscretizer":
        if value < 2:
            raise ValueError(f"numBuckets must be >= 2, got {value}")
        return self._set(numBuckets=int(value))

    def setNumBins(self, value: int) -> "QuantileDiscretizer":
        if value < 2:
            raise ValueError(f"numBins must be >= 2, got {value}")
        return self._set(numBins=int(value))

    def fit(
        self, dataset: Any, num_partitions: int | None = None
    ) -> "QuantileDiscretizerModel":
        from spark_rapids_ml_tpu.models.scaler import (
            _fit_histogram,
            _fit_range_stats,
        )

        rstats = _fit_range_stats(self, dataset, num_partitions)
        check_finite_range(rstats.min, rstats.max)
        mins = jnp.asarray(rstats.min)
        maxs = jnp.asarray(rstats.max)
        with trace_range("quantile discretizer histogram"):
            hist = _fit_histogram(
                self, dataset, num_partitions, mins, maxs, self.getNumBins()
            )
        splits = splits_from_histogram(hist, mins, maxs, self.getNumBuckets())
        model = QuantileDiscretizerModel(uid=self.uid, splits=splits)
        return self._copyValues(model)


class QuantileDiscretizerModel(_DiscretizerParams, Model):
    """Per-feature splits matrix [n, numBuckets+1] with ±inf outer edges.
    Duplicate interior splits (collapsed quantiles) leave empty buckets —
    see the module docstring for the trade vs Spark's bucket-count
    reduction."""

    def __init__(self, uid: str | None = None, splits: np.ndarray | None = None):
        super().__init__(uid)
        self.splits = None if splits is None else np.asarray(splits)

    def _bucket(self, mat: np.ndarray) -> np.ndarray:
        if mat.shape[1] != self.splits.shape[0]:
            raise ValueError(
                f"model learned {self.splits.shape[0]} features, input has "
                f"{mat.shape[1]}"
            )
        if np.isnan(mat).any():
            # searchsorted would silently sort NaN past +inf into the top
            # bucket; Spark's fitted discretizer raises on NaN by default
            bad = np.argwhere(np.isnan(mat))[0]
            raise ValueError(
                f"NaN at row {bad[0]} feature {bad[1]}; "
                "QuantileDiscretizer bins finite data — impute first "
                "(spark_rapids_ml_tpu.Imputer)"
            )
        return np.asarray(
            _bucketize(jnp.asarray(mat), jnp.asarray(self.splits))
        )

    def transform(self, dataset: Any) -> Any:
        with trace_range("quantile bucketize"):
            return columnar.apply_column_transform(
                dataset,
                self._paramMap.get("inputCol"),
                self.getOutputCol(),
                self._bucket,
            )

    def _saveData(self) -> dict[str, np.ndarray]:
        return {"splits": self.splits}

    @classmethod
    def _fromSaved(cls, uid, data):
        return cls(uid=uid, splits=data["splits"])

    def _saveSparkML(self, path: str) -> None:
        raise NotImplementedError(
            "stock Spark ML's QuantileDiscretizer fits a single-column "
            "Bucketizer; the per-feature splits matrix has no stock "
            "layout — use the native layout"
        )
