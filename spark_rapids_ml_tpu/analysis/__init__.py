"""tpulint — project-native static analysis for the framework's JAX/TPU
invariants.

The reference design keeps its invariants honest with Scala's compiler over
a 1.8k-LoC surface; a ~27k-LoC Python/JAX reproduction keeps them honest
with this package instead. The engine (:mod:`.engine`) is a small AST
visitor framework — per-rule IDs, ``# tpulint: disable=RULE`` suppressions,
a checked-in baseline for grandfathered findings, JSON and human output —
and the rules (:mod:`.rules`) encode the conventions the first five PRs
established: donated fold carries, no host syncs inside traced code, no
recompile hazards, one retry policy, registered telemetry names, a central
knob inventory, locked telemetry globals, no silently swallowed broad
exceptions.

Run it as ``python -m tools.tpulint`` (CI runs ``--strict``); this package
stays import-pure (no jax) so linting works anywhere the repo checks out.
"""

from spark_rapids_ml_tpu.analysis.engine import (  # noqa: F401
    Baseline,
    Finding,
    LintedModule,
    Rule,
    lint_paths,
    lint_source,
)
from spark_rapids_ml_tpu.analysis.rules import ALL_RULES  # noqa: F401
