"""The tpulint rules — the framework's JAX/TPU invariants, as code.

Each rule subclasses :class:`~.engine.Rule` and documents what it enforces
and why (CONTRIBUTING.md renders these docstrings). Rules are heuristic on
purpose: they resolve only module-local facts (imports, same-file function
defs) and skip what they cannot resolve — a linter that guesses produces
noise, and noise gets disabled. Anything a rule flags wrongly can be
silenced with ``# tpulint: disable=RULE`` at the site or blessed with a
justification in the baseline.
"""

from __future__ import annotations

import ast
from typing import Iterator

from spark_rapids_ml_tpu.analysis.engine import (
    Finding,
    LintedModule,
    Rule,
    dotted_name,
)

# Parameter names the framework uses for streamed-fold / chunked-fit
# carries. A jitted callable taking one of these re-ingests the
# accumulator every call; without donation XLA must keep input and output
# alive simultaneously — 2x accumulator HBM and a copy per chunk.
CARRY_PARAM_NAMES = frozenset(
    {"carry", "carry0", "acc", "accum", "state", "state0", "w0", "centers0"}
)

CACHE_DECORATORS = frozenset(
    {"lru_cache", "cache", "functools.lru_cache", "functools.cache"}
)

_SHAPE_ATTRS = frozenset({"shape", "ndim", "size", "dtype"})


def _is_jit_call(mod: LintedModule, node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and mod.call_is(node, "jax.jit")


def _jit_kwargs(call: ast.Call) -> dict[str, ast.expr]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _decorator_jit_kwargs(
    mod: LintedModule, fn: ast.FunctionDef
) -> dict[str, ast.expr] | None:
    """jit kwargs if ``fn`` is jit-decorated (@jax.jit or
    @partial(jax.jit, ...)); None when it is not."""
    for dec in fn.decorator_list:
        if mod.resolves_to(dec, "jax.jit"):
            return {}
        if isinstance(dec, ast.Call):
            if mod.call_is(dec, "jax.jit"):
                return _jit_kwargs(dec)
            if (
                mod.call_is(dec, "functools.partial")
                and dec.args
                and mod.resolves_to(dec.args[0], "jax.jit")
            ):
                return _jit_kwargs(dec)
    return None


def _const_int_set(node: ast.expr | None) -> set[int] | None:
    """{ints} from a Constant/Tuple-of-Constants node; None if unresolvable."""
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: set[int] = set()
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                return None
            out.add(elt.value)
        return out
    return None


def _const_str_set(node: ast.expr | None) -> set[str] | None:
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: set[str] = set()
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
                return None
            out.add(elt.value)
        return out
    return None


def _param_names(fn: ast.FunctionDef | ast.Lambda) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _module_functions(mod: LintedModule) -> dict[str, ast.FunctionDef]:
    """Every (possibly nested) def in the file by name; later defs win."""
    return {
        n.name: n
        for n in ast.walk(mod.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _jit_target(
    mod: LintedModule, call: ast.Call
) -> tuple[ast.FunctionDef | ast.Lambda | None, str]:
    """The callable a ``jax.jit(...)`` call wraps, resolved module-locally.

    Sees through ``partial(f, ...)``; returns (def-node-or-None, label).
    When several defs share the name (factory modules reuse ``run``), the
    one enclosed by the same function as the jit call wins — that is the
    def the name actually binds to at the call site."""
    if not call.args:
        return None, ""
    target = call.args[0]
    if isinstance(target, ast.Call) and mod.call_is(target, "functools.partial"):
        if not target.args:
            return None, ""
        target = target.args[0]
    if isinstance(target, ast.Lambda):
        return target, "<lambda>"
    name = dotted_name(target)
    candidates = [
        n for n in ast.walk(mod.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name == name
    ]
    if not candidates:
        return None, name
    here = mod.enclosing_function(call)
    for fn in candidates:
        if mod.enclosing_function(fn) is here:
            return fn, name
    return candidates[-1], name


def _traced_functions(mod: LintedModule) -> dict[ast.AST, str]:
    """Function/lambda nodes whose bodies run under jax tracing:
    jit-decorated defs plus same-file callables passed to jax.jit."""
    out: dict[ast.AST, str] = {}
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.FunctionDef):
            if _decorator_jit_kwargs(mod, n) is not None:
                out[n] = n.name
        if _is_jit_call(mod, n):
            fn, label = _jit_target(mod, n)
            if fn is not None:
                out[fn] = label or "<lambda>"
    return out


class DonatedCarryRule(Rule):
    id = "TPL001"
    name = "donated-carry"
    doc = (
        "Every jax.jit of a fold/step/chunk callable that re-ingests an "
        "accumulator (a parameter named carry/acc/state/w0/centers0/...) "
        "must donate that argument (donate_argnums/donate_argnames). "
        "Without donation the streamed fold holds two copies of the carry "
        "in HBM and pays a device copy per chunk — the exact regression "
        "PR 1's donated-carry design exists to prevent."
    )

    def check(self, mod: LintedModule) -> Iterator[Finding]:
        # inline jax.jit(f, ...) calls
        for n in ast.walk(mod.tree):
            if _is_jit_call(mod, n):
                fn, label = _jit_target(mod, n)
                if fn is None:
                    continue
                yield from self._check_callable(mod, n, fn, label, _jit_kwargs(n))
            elif isinstance(n, ast.FunctionDef):
                kwargs = _decorator_jit_kwargs(mod, n)
                if kwargs is not None:
                    yield from self._check_callable(mod, n, n, n.name, kwargs)

    def _check_callable(self, mod, site, fn, label, kwargs):
        params = _param_names(fn)
        carry_idx = [i for i, p in enumerate(params) if p in CARRY_PARAM_NAMES]
        if not carry_idx:
            return
        donated_nums = _const_int_set(kwargs.get("donate_argnums"))
        donated_names = _const_str_set(kwargs.get("donate_argnames"))
        if donated_nums is None or donated_names is None:
            return  # dynamically built donation spec — trust it
        for i in carry_idx:
            if i not in donated_nums and params[i] not in donated_names:
                yield self.finding(
                    mod, site,
                    f"jit of {label or 'callable'}: carry parameter "
                    f"{params[i]!r} (arg {i}) is not donated — pass "
                    f"donate_argnums={i} so the fold reuses the "
                    "accumulator's buffer",
                )


class HostSyncRule(Rule):
    id = "TPL002"
    name = "host-sync-in-hot-path"
    doc = (
        "No float()/int()/bool()/np.asarray()/.item()/.tolist()/"
        ".block_until_ready() on traced values inside jit-traced functions "
        "— under tracing these either fail (ConcretizationTypeError) or, "
        "worse, silently force a device->host sync per call. ops/ kernel "
        "modules must additionally stay sync-free everywhere: they are the "
        "pure jittable compute layer and dispatch decides when to wait. "
        "serving/ holds the same whole-module bar — its kernels feed the "
        "AOT registry and a stray sync is per-request latency on the warm "
        "path. Shape/dtype reads (static under tracing) are exempt; "
        "telemetry/ is exempt (measurement is allowed to sync)."
    )

    SYNC_BUILTINS = frozenset({"float", "int", "bool"})
    SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
    NP_FUNCS = ("numpy.asarray", "numpy.array")
    # scopes held to the whole-module sync-method bar, not just traced fns
    SYNC_SCOPES = {
        "/ops/": "ops/ kernel module",
        "/serving/": "serving/ warm-path module",
    }

    def check(self, mod: LintedModule) -> Iterator[Finding]:
        if "/telemetry/" in mod.relpath:
            return
        traced = _traced_functions(mod)
        for fn, label in traced.items():
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for n in ast.walk(stmt):
                    # nested defs inside a traced fn still trace (closures)
                    yield from self._check_node(mod, n, f"traced {label}")
        scope_ctx = next(
            (c for s, c in self.SYNC_SCOPES.items() if s in mod.relpath),
            None,
        )
        if scope_ctx is not None:
            traced_nodes = {
                id(x) for fn in traced for x in ast.walk(fn)
            }
            for n in ast.walk(mod.tree):
                if id(n) in traced_nodes:
                    continue  # already reported with traced context
                yield from self._check_node(
                    mod, n, scope_ctx, methods_only=True
                )

    def _check_node(self, mod, n, ctx, methods_only=False):
        if not isinstance(n, ast.Call):
            return
        func = n.func
        if isinstance(func, ast.Attribute) and func.attr in self.SYNC_METHODS:
            yield self.finding(
                mod, n,
                f".{func.attr}() forces a device->host sync ({ctx})",
            )
            return
        if methods_only:
            return
        if (
            isinstance(func, ast.Name)
            and func.id in self.SYNC_BUILTINS
            and len(n.args) == 1
            and not self._static_arg(n.args[0])
        ):
            yield self.finding(
                mod, n,
                f"{func.id}() concretizes a traced value ({ctx})",
            )
            return
        if any(mod.resolves_to(func, f) for f in self.NP_FUNCS):
            yield self.finding(
                mod, n,
                f"{dotted_name(func)}() materializes a traced value on "
                f"host ({ctx}) — use jnp instead",
            )

    @staticmethod
    def _static_arg(arg: ast.expr) -> bool:
        """Constants and shape/dtype/len() reads are static under tracing."""
        if isinstance(arg, ast.Constant):
            return True
        for n in ast.walk(arg):
            if isinstance(n, ast.Attribute) and n.attr in _SHAPE_ATTRS:
                return True
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id == "len"
            ):
                return True
        return False


class RecompileHazardRule(Rule):
    id = "TPL003"
    name = "recompile-hazard"
    doc = (
        "A jax.jit(...) program object must be built once and reused: "
        "constructing one inside a loop, or inside an uncached function "
        "that runs per fit/chunk, discards XLA's in-process executable "
        "cache and retraces every call — the recompile storm the "
        "trace-report anomaly check flags at runtime. Build programs at "
        "module scope or in an @functools.lru_cache'd factory (the "
        "parallel/ convention). In serving/ the same discipline covers "
        "AOT lowering: a .lower(avals) call is a full trace+lower even "
        "when the executable would be cache-hit, so it must live in a "
        "cached factory (serving.registry._compiled_for), never per "
        "request or per loop iteration. Shape hazards are the runtime "
        "half of this rule: Python scalars that vary per call belong in "
        "static_argnums only if they are genuinely low-cardinality; "
        "varying data shapes belong in buckets (TPU_ML_MIN_BUCKET)."
    )

    def check(self, mod: LintedModule) -> Iterator[Finding]:
        for n in ast.walk(mod.tree):
            if not (
                _is_jit_call(mod, n) or self._is_aot_lower(mod, n)
            ):
                continue
            what = (
                "AOT .lower() trace" if self._is_aot_lower(mod, n)
                else "jax.jit program"
            )
            in_loop = any(
                isinstance(a, (ast.For, ast.While, ast.AsyncFor))
                for a in mod.ancestors(n)
            )
            if in_loop:
                yield self.finding(
                    mod, n,
                    f"{what} constructed inside a loop — every "
                    "iteration retraces; hoist it out of the loop",
                )
                continue
            encl = mod.enclosing_function(n)
            if encl is None:
                continue  # module scope: built once at import
            chain = [encl, *(
                a for a in mod.ancestors(encl)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
            )]
            if any(self._has_cache_decorator(mod, f) for f in chain):
                continue
            if any(f in _traced_functions(mod) for f in chain):
                continue  # jit-of-jit inside traced code is inlined, fine
            yield self.finding(
                mod, n,
                f"{what} built per call of {encl.name}() — cache "
                "the factory with @functools.lru_cache or hoist to module "
                "scope so repeat calls reuse the executable",
            )

    @staticmethod
    def _is_aot_lower(mod: LintedModule, n: ast.AST) -> bool:
        """A ``<jit-program>.lower(avals)`` AOT trace in serving/ — the
        argumentless form is str.lower() and stays exempt everywhere."""
        return (
            "/serving/" in mod.relpath
            and isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "lower"
            and bool(n.args or n.keywords)
        )

    @staticmethod
    def _has_cache_decorator(mod: LintedModule, fn: ast.FunctionDef) -> bool:
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = dotted_name(target)
            if name in CACHE_DECORATORS or any(
                mod.resolves_to(target, c) for c in CACHE_DECORATORS
            ):
                return True
        return False


class RetryDisciplineRule(Rule):
    id = "TPL004"
    name = "retry-discipline"
    doc = (
        "No hand-rolled time.sleep retry loops outside resilience/retry.py "
        "— the shared call_with_retry is the one backoff loop: it "
        "classifies errors, respects the attempt/deadline knobs, counts "
        "retry.attempts in telemetry, and never sleeps after the final "
        "attempt (the exact executor bug PR 3 fixed). A sleep inside an "
        "except handler, inside a loop that catches exceptions, or fed "
        "from a backoff variable is hand-rolled retry machinery."
    )

    BACKOFF_NAMES = ("backoff", "retry", "delay")

    def check(self, mod: LintedModule) -> Iterator[Finding]:
        if mod.relpath.endswith("resilience/retry.py"):
            return
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Call) and mod.call_is(n, "time.sleep")):
                continue
            ancestors = list(mod.ancestors(n))
            in_except = any(isinstance(a, ast.ExceptHandler) for a in ancestors)
            loop = next(
                (a for a in ancestors if isinstance(a, (ast.For, ast.While))),
                None,
            )
            loop_catches = loop is not None and any(
                isinstance(x, ast.Try) for x in ast.walk(loop)
            )
            backoff_arg = bool(n.args) and any(
                isinstance(x, ast.Name)
                and any(b in x.id.lower() for b in self.BACKOFF_NAMES)
                for x in ast.walk(n.args[0])
            )
            if in_except or loop_catches or backoff_arg:
                yield self.finding(
                    mod, n,
                    "hand-rolled sleep-based retry — route this through "
                    "resilience.retry.call_with_retry (shared policy, "
                    "telemetry counters, no sleep-after-final-attempt)",
                )


class NameRegistryRule(Rule):
    id = "TPL005"
    name = "name-registry"
    doc = (
        "Metric, span, timeline-instant and fault-site string literals at "
        "call sites must resolve against the canonical registries "
        "(telemetry/names.py, resilience/sites.py). A typo'd name does "
        "not error — it mints a silent new metric family no dashboard or "
        "anomaly check reads, or a fault gate no chaos plan can hit. "
        "Adding a series means declaring it in the registry first."
    )

    METRIC_FNS = frozenset({"counter_inc", "gauge_set", "histogram_record"})

    def __init__(self, metrics=None, prefixes=None, spans=None,
                 instants=None, sites=None):
        if metrics is None:
            from spark_rapids_ml_tpu.resilience.sites import FAULT_SITES
            from spark_rapids_ml_tpu.telemetry.names import (
                INSTANTS, METRIC_PREFIXES, METRICS, SPAN_PHASES,
            )
            metrics, prefixes = METRICS, METRIC_PREFIXES
            spans, instants, sites = SPAN_PHASES, INSTANTS, FAULT_SITES
        self.metrics = metrics
        self.prefixes = tuple(prefixes or ())
        self.spans = spans or frozenset()
        self.instants = instants or frozenset()
        self.sites = sites or frozenset()

    def check(self, mod: LintedModule) -> Iterator[Finding]:
        if mod.relpath.endswith(("telemetry/names.py", "resilience/sites.py")):
            return
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Call) and n.args):
                continue
            func = n.func
            attr = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else ""
            )
            lit = self._literal(n.args[0])
            if attr in self.METRIC_FNS:
                kind, registry = "metric", self.metrics
            elif attr == "trace_range" or attr == "record_span":
                kind, registry = "span phase", self.spans
            elif attr == "record_instant":
                kind, registry = "timeline instant", self.instants
            elif attr == "inject" and self._is_fault_inject(mod, func):
                kind, registry = "fault site", self.sites
            else:
                continue
            if lit is None:
                # f-string with a literal head: prefix-check metrics
                if kind == "metric":
                    head = self._fstring_head(n.args[0])
                    if head is not None and not any(
                        head.startswith(p) for p in self.prefixes
                    ):
                        yield self.finding(
                            mod, n,
                            f"dynamic metric name with unregistered prefix "
                            f"{head!r} — declare the prefix in "
                            "telemetry.names.METRIC_PREFIXES",
                        )
                continue
            ok = lit in registry or (
                kind == "metric"
                and any(lit.startswith(p) for p in self.prefixes)
            )
            if not ok:
                where = (
                    "telemetry.names" if kind != "fault site"
                    else "resilience.sites"
                )
                yield self.finding(
                    mod, n,
                    f"{kind} {lit!r} is not declared in the {where} "
                    "registry — a typo here silently mints a new family; "
                    "declare it (or fix the name)",
                )

    @staticmethod
    def _literal(node: ast.expr) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None

    @staticmethod
    def _fstring_head(node: ast.expr) -> str | None:
        if isinstance(node, ast.JoinedStr) and node.values:
            first = node.values[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                return first.value
        return None

    @staticmethod
    def _is_fault_inject(mod: LintedModule, func: ast.expr) -> bool:
        name = dotted_name(func)
        if name.endswith("faults.inject"):
            return True
        origin = mod.imports.get(name, "")
        return name == "inject" and origin.endswith("faults.inject")


class KnobInventoryRule(Rule):
    id = "TPL006"
    name = "knob-inventory"
    doc = (
        "Every TPU_ML_* environment knob must be declared in "
        "utils/knobs.py (name, type, default, doc, consumer) — the "
        "declaration is what --list-knobs renders and what keeps the "
        "README knob table honest (CI drift-checks them against each "
        "other). Any TPU_ML_* string literal outside the declaration "
        "module is either an undeclared knob or a typo'd read of a "
        "declared one; both ship silent misconfiguration."
    )

    def __init__(self, declared=None):
        if declared is None:
            from spark_rapids_ml_tpu.utils.knobs import KNOBS
            declared = frozenset(KNOBS)
        self.declared = declared

    def check(self, mod: LintedModule) -> Iterator[Finding]:
        if mod.relpath.endswith("utils/knobs.py"):
            return
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Constant) and isinstance(n.value, str)):
                continue
            v = n.value
            if not (v.startswith("TPU_ML_") and len(v) > len("TPU_ML_")
                    and v.replace("_", "").isalnum() and v == v.upper()):
                continue
            parent = mod.parents.get(n)
            if isinstance(parent, ast.Expr):
                continue  # docstring / bare string statement
            if v not in self.declared:
                yield self.finding(
                    mod, n,
                    f"env knob {v!r} is not declared in utils.knobs.KNOBS "
                    "— declare it there (and prefer referencing "
                    "knobs.<NAME>.name over a fresh literal)",
                )


class TelemetryRaceRule(Rule):
    id = "TPL007"
    name = "telemetry-race"
    doc = (
        "Module-level mutable state in telemetry/ and resilience/ must "
        "only be mutated under a lock: these modules are written to from "
        "the partition executor's thread pool and from worker callbacks, "
        "and unlocked dict/list mutation corrupts counts exactly the way "
        "the PR 2 registry lock exists to prevent. A mutation (or a "
        "`global` rebind) with no enclosing `with <lock>:` is a finding."
    )

    SCOPES = ("/telemetry/", "/resilience/")
    MUTATORS = frozenset({
        "append", "add", "update", "clear", "pop", "popitem",
        "setdefault", "extend", "remove", "discard", "insert",
    })
    MUTABLE_CTORS = frozenset({
        "dict", "list", "set", "defaultdict", "deque", "OrderedDict",
        "Counter",
    })

    def check(self, mod: LintedModule) -> Iterator[Finding]:
        if not any(s in mod.relpath for s in self.SCOPES):
            return
        mutable = self._module_mutables(mod)
        if not mutable:
            return
        for n in ast.walk(mod.tree):
            name = self._mutation_target(n, mutable, mod)
            if name and not self._under_lock(mod, n):
                yield self.finding(
                    mod, n,
                    f"module-level mutable {name!r} mutated outside a "
                    "lock — wrap in `with <lock>:` (or prove the path "
                    "single-threaded and bless with a note)",
                )

    def _module_mutables(self, mod: LintedModule) -> set[str]:
        out: set[str] = set()
        for stmt in mod.tree.body:
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            if value is None:
                continue
            is_mutable = isinstance(
                value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp)
            ) or (
                isinstance(value, ast.Call)
                and dotted_name(value.func).split(".")[-1] in self.MUTABLE_CTORS
            )
            if is_mutable:
                out.update(
                    t.id for t in targets if isinstance(t, ast.Name)
                )
        return out

    def _mutation_target(self, n: ast.AST, mutable: set[str], mod) -> str | None:
        # x[k] = v / del x[k] / x[k] += v
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (
                n.targets if isinstance(n, ast.Assign)
                else [n.target] if isinstance(n, ast.AugAssign)
                else n.targets
            )
            for t in targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in mutable
                ):
                    return t.value.id
            # global rebind: `global x` + assignment inside a function
            if isinstance(n, ast.Assign):
                fn = mod.enclosing_function(n)
                if fn is not None:
                    declared_global = {
                        g for s in ast.walk(fn)
                        if isinstance(s, ast.Global) for g in s.names
                    }
                    for t in targets:
                        if isinstance(t, ast.Name) and t.id in mutable \
                                and t.id in declared_global:
                            return t.id
        # x.append(...) etc.
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in self.MUTATORS
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id in mutable
        ):
            return n.func.value.id
        return None

    @staticmethod
    def _under_lock(mod: LintedModule, n: ast.AST) -> bool:
        for a in mod.ancestors(n):
            if isinstance(a, ast.With):
                for item in a.items:
                    if "lock" in ast.unparse(item.context_expr).lower():
                        return True
        return False


class SwallowedExceptionRule(Rule):
    id = "TPL008"
    name = "swallowed-exception"
    doc = (
        "`except Exception: pass` (or a bare except: pass) with no "
        "explanation swallows every failure mode including the "
        "XlaRuntimeError families the retry classifier must see — PR 3 "
        "exists because exactly this pattern hid a retry bug. A broad "
        "swallow is allowed only with a same-line comment saying why "
        "(narrow handlers, or handlers that do something, are fine)."
    )

    BROAD = frozenset({"Exception", "BaseException"})

    def check(self, mod: LintedModule) -> Iterator[Finding]:
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.ExceptHandler):
                continue
            if not (len(n.body) == 1 and isinstance(n.body[0], ast.Pass)):
                continue
            if not self._is_broad(n.type):
                continue
            # intent may be documented on the except line or the pass line
            last = min(n.body[0].lineno, len(mod.lines))
            if any("#" in mod.lines[i - 1] for i in range(n.lineno, last + 1)):
                continue
            what = "bare except" if n.type is None else dotted_name(n.type)
            yield self.finding(
                mod, n,
                f"{what}: pass silently swallows every failure — narrow "
                "the type, handle it, or add a same-line comment saying "
                "why ignoring is correct",
            )

    def _is_broad(self, t: ast.expr | None) -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Tuple):
            return any(self._is_broad(e) for e in t.elts)
        return dotted_name(t).split(".")[-1] in self.BROAD


def all_rules() -> list[Rule]:
    """Fresh instances of every rule, registry-backed defaults."""
    return [
        DonatedCarryRule(),
        HostSyncRule(),
        RecompileHazardRule(),
        RetryDisciplineRule(),
        NameRegistryRule(),
        KnobInventoryRule(),
        TelemetryRaceRule(),
        SwallowedExceptionRule(),
    ]


ALL_RULES = all_rules()
