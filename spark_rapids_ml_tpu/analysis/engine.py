"""The tpulint engine: module loading, suppressions, baseline, reporting.

Deliberately dependency-free (stdlib ``ast`` only — no jax, no third-party
lint frameworks) so the linter runs in any checkout, including CI images
and jax-free worker containers. Rules live in :mod:`.rules`; this module
gives them a parsed, cross-referenced view of one file
(:class:`LintedModule`) and owns everything around a finding's lifecycle:

- **Suppressions** — ``# tpulint: disable=TPL001[,TPL002]`` on the
  offending line (or on a comment-only line directly above it) silences
  those rules there; ``disable=all`` silences every rule.
- **Baseline** — grandfathered findings live in a checked-in JSON file
  keyed by a line-number-free fingerprint (rule | path | scope | message),
  so pure line drift never resurrects a blessed finding. Each entry
  carries a ``note`` saying *why* it is blessed — the perf-ledger
  ``--bless`` convention from PR 5.
- **Output** — human one-line-per-finding text or a JSON document
  (``tools/tpulint.py`` chooses).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator

SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:#|$)"
)

SKIP_DIR_NAMES = {
    "__pycache__", ".git", "build", "dist", ".eggs", "node_modules",
}


@dataclass
class Finding:
    """One rule violation at one location."""

    rule: str
    path: str       # repo-relative posix path
    line: int
    col: int
    message: str
    scope: str = ""         # dotted enclosing class/def chain
    suppressed: bool = False
    baselined: bool = False
    note: str = ""          # baseline justification when baselined

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity: stable across pure line drift."""
        raw = f"{self.rule}|{self.path}|{self.scope}|{self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        tag = ""
        if self.suppressed:
            tag = " [suppressed]"
        elif self.baselined:
            tag = " [baselined]"
        where = f"{self.path}:{self.line}:{self.col}"
        scope = f" in {self.scope}" if self.scope else ""
        return f"{where}: {self.rule} {self.message}{scope}{tag}"

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "scope": self.scope,
            "fingerprint": self.fingerprint,
        }
        if self.suppressed:
            d["suppressed"] = True
        if self.baselined:
            d["baselined"] = True
            if self.note:
                d["note"] = self.note
        return d


class Rule:
    """Base class of one lint rule.

    Subclasses set ``id`` (``TPL00x``), ``name`` (short kebab slug) and
    ``doc`` (one paragraph: what it enforces and why), and implement
    :meth:`check` yielding findings. ``self.finding`` stamps location and
    scope so rules only supply the message.
    """

    id: str = ""
    name: str = ""
    doc: str = ""

    def check(self, mod: "LintedModule") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: "LintedModule", node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=mod.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
            scope=mod.scope_of(node),
        )


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class LintedModule:
    """One parsed file plus the cross-references every rule needs."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.suppressions = self._parse_suppressions()
        # names imported in this module: local alias -> dotted origin
        self.imports: dict[str, str] = {}
        for n in ast.walk(self.tree):
            if isinstance(n, ast.Import):
                for a in n.names:
                    self.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(n, ast.ImportFrom) and n.module:
                for a in n.names:
                    self.imports[a.asname or a.name] = f"{n.module}.{a.name}"

    # -- location helpers ---------------------------------------------------

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def scope_of(self, node: ast.AST) -> str:
        names = [
            a.name
            for a in self.ancestors(node)
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
        ]
        return ".".join(reversed(names))

    def enclosing_function(self, node: ast.AST) -> ast.FunctionDef | None:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    # -- name resolution ----------------------------------------------------

    def resolves_to(self, node: ast.AST, dotted: str) -> bool:
        """Does ``node`` (Name/Attribute) denote ``dotted`` (e.g.
        ``jax.jit``), accounting for ``import jax``, ``from jax import
        jit`` and aliases?"""
        got = dotted_name(node)
        if not got:
            return False
        if got == dotted:
            return True
        head, _, rest = got.partition(".")
        origin = self.imports.get(head)
        if origin:
            resolved = origin + ("." + rest if rest else "")
            if resolved == dotted:
                return True
        return False

    def call_is(self, call: ast.Call, dotted: str) -> bool:
        return self.resolves_to(call.func, dotted)

    # -- suppressions -------------------------------------------------------

    def _parse_suppressions(self) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(1).split(",") if r.strip()}
            target = i
            if line.lstrip().startswith("#"):
                # comment-only line: applies to the next source line
                target = i + 1
            out.setdefault(target, set()).update(rules)
        return out

    def is_suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line, ())
        return bool(rules) and ("ALL" in rules or finding.rule in rules)


@dataclass
class Baseline:
    """The checked-in set of blessed findings."""

    path: str = ""
    entries: dict[str, dict] = field(default_factory=dict)  # fingerprint -> entry

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls(path=path)
        with open(path) as f:
            doc = json.load(f)
        entries = {e["fingerprint"]: e for e in doc.get("entries", [])}
        return cls(path=path, entries=entries)

    def apply(self, findings: list[Finding]) -> None:
        """Mark baselined findings in place."""
        for f in findings:
            e = self.entries.get(f.fingerprint)
            if e is not None:
                f.baselined = True
                f.note = e.get("note", "")

    def stale(self, findings: list[Finding]) -> list[dict]:
        """Entries whose finding no longer fires (fixed or vanished)."""
        live = {f.fingerprint for f in findings}
        return [e for fp, e in sorted(self.entries.items()) if fp not in live]

    @staticmethod
    def write(path: str, findings: list[Finding], notes: dict[str, str] | None = None) -> int:
        """Bless the given findings: write them as the new baseline.

        ``notes`` maps fingerprints to justifications; findings keep an
        existing note when re-blessed. Returns the entry count."""
        notes = notes or {}
        entries = []
        for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line)):
            entries.append({
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "path": f.path,
                "scope": f.scope,
                "message": f.message,
                "note": notes.get(f.fingerprint) or f.note
                or "blessed without note — justify or fix",
            })
        doc = {
            "comment": (
                "tpulint baseline: grandfathered findings, keyed by a "
                "line-free fingerprint. Every entry's note says why it is "
                "blessed instead of fixed. Regenerate with "
                "`python -m tools.tpulint --bless` after editing notes."
            ),
            "entries": entries,
        }
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=2)
            fh.write("\n")
        return len(entries)


# -- running ----------------------------------------------------------------


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if d not in SKIP_DIR_NAMES and not d.endswith(".egg-info")
            )
            for fn in sorted(files):
                if fn.endswith(".py"):
                    yield os.path.join(root, fn)


def lint_source(
    source: str, relpath: str, rules: Iterable[Rule]
) -> list[Finding]:
    """Lint one in-memory module (the test-fixture entry point)."""
    mod = LintedModule(relpath, source)
    findings: list[Finding] = []
    for rule in rules:
        for f in rule.check(mod):
            f.suppressed = mod.is_suppressed(f)
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_paths(
    paths: Iterable[str],
    rules: Iterable[Rule],
    *,
    root: str = ".",
) -> tuple[list[Finding], list[str]]:
    """Lint files/trees. Returns (findings, unparseable-file errors)."""
    rules = list(rules)
    findings: list[Finding] = []
    errors: list[str] = []
    for path in iter_py_files(paths):
        relpath = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            findings.extend(lint_source(source, relpath, rules))
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{relpath}: {type(e).__name__}: {e}")
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors
