"""Drop-in k-NN namespace mirroring ``spark_rapids_ml.knn``.

The modern spark-rapids-ml package exposes its exact brute-force
NearestNeighbors under ``spark_rapids_ml.knn``; this shim gives users of
that API the same import path here.
"""

from spark_rapids_ml_tpu.models.neighbors import (  # noqa: F401
    ApproximateNearestNeighbors,
    ApproximateNearestNeighborsModel,
    NearestNeighbors,
    NearestNeighborsModel,
)

__all__ = [
    "ApproximateNearestNeighbors",
    "ApproximateNearestNeighborsModel",
    "NearestNeighbors",
    "NearestNeighborsModel",
]
