"""Spark DataFrame-facing estimators — the drop-in layer over pyspark.

The reference's user story (README.md:24-37): change one import and your
Spark ML PCA pipeline runs accelerated, with ``setInputCol`` taking an
ArrayType column. ``SparkPCA`` here is that layer for TPU: it drives a real
``pyspark.sql.DataFrame`` through the Arrow plan functions in
``spark_rapids_ml_tpu.spark.arrow_fns``:

- ``fit``:    ``df.mapInArrow(fit_partition_fn) → collect → merge → eigh``
              — the §3.1 call stack with mapInArrow standing in for
              ColumnarRdd and an Arrow shuffle standing in for the breeze
              ``reduce``.
- ``transform``: ``df.mapInArrow(transform_partition_fn)`` — the columnar
              UDF analog (RapidsPCA.scala:128-161); batches are projected on
              the executor-local accelerator.

pyspark is an OPTIONAL dependency: this module imports lazily and raises an
actionable error if Spark isn't installed. Everything executor-side lives in
``arrow_fns`` and is tested without Spark.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from spark_rapids_ml_tpu.models.pca import PCA, PCAModel
from spark_rapids_ml_tpu.ops import linalg as L
from spark_rapids_ml_tpu.spark import arrow_fns
from spark_rapids_ml_tpu.utils.tracing import trace_range


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        from pyspark.sql import DataFrame  # noqa: F401
    except ImportError as e:  # pragma: no cover - exercised via message test
        raise ImportError(
            "spark_rapids_ml_tpu.spark.estimators requires pyspark "
            "(pip install pyspark>=3.4); the core estimators in "
            "spark_rapids_ml_tpu work without it on pandas/Arrow/ndarray input"
        ) from e


def _spark_stats_type():
    """Spark schema for the serialized GramStats row (mapInArrow needs it).
    ArrayType maps to the Arrow variable list the workers emit
    (``arrow_fns.stats_schema``)."""
    from pyspark.sql import types as T

    return T.StructType(
        [
            T.StructField("xtx", T.ArrayType(T.DoubleType())),
            T.StructField("col_sum", T.ArrayType(T.DoubleType())),
            T.StructField("count", T.DoubleType()),
        ]
    )


class SparkPCA(PCA):
    """PCA whose ``fit``/``transform`` accept ``pyspark.sql.DataFrame``.

    Inherits every param (k, inputCol, outputCol, meanCentering, precision,
    solver) and the persistence format from the core :class:`PCA`; only the
    data path differs. Non-Spark inputs fall through to the core paths, so
    one estimator serves both worlds.
    """

    def fit(self, dataset: Any, num_partitions: int | None = None) -> "SparkPCAModel":
        if not _is_spark_df(dataset):
            core = super().fit(dataset, num_partitions)
            return self._copyValues(
                SparkPCAModel(uid=core.uid, pc=core.pc,
                              explainedVariance=core.explainedVariance)
            )
        _require_pyspark()
        input_col = self.getInputCol()
        with trace_range("compute cov"):  # NvtxRange analog, RapidsRowMatrix.scala:62
            selected = dataset.select(input_col)
            # infer n from one row, like RapidsPCA.scala:73-74
            first = selected.first()
            if first is None:
                raise ValueError("empty dataset")
            if first[0] is None:
                raise ValueError(
                    f"input column {input_col!r} contains null feature "
                    "vectors; drop or impute nulls before fit"
                )
            n = len(first[0])
            k = self.getK()
            # validate before launching the cluster-wide Gram pass
            if k > n:
                raise ValueError(f"k={k} must be <= number of features {n}")
            fit_fn = arrow_fns.make_fit_partition_fn(
                input_col, precision=self.getOrDefault("precision")
            )
            stats_df = selected.mapInArrow(fit_fn, schema=_spark_stats_type())
            if hasattr(stats_df, "toArrow"):  # PySpark >= 4.0: stays columnar
                stats = arrow_fns.stats_from_batches(stats_df.toArrow().to_batches())
            else:  # PySpark 3.4/3.5: tiny payload (one [n,n] row per partition)
                stats = arrow_fns.stats_from_rows(stats_df.collect())
        with trace_range("eigh"):
            import jax.numpy as jnp

            cov = L.covariance_from_stats(
                L.GramStats(
                    jnp.asarray(stats.xtx),
                    jnp.asarray(stats.col_sum),
                    jnp.asarray(stats.count),
                ),
                mean_centering=self.getMeanCentering(),
            )
            pc, ev = L.pca_fit_from_cov(
                cov, k, solver=self.getOrDefault("solver")
            )
        model = SparkPCAModel(
            uid=self.uid, pc=np.asarray(pc), explainedVariance=np.asarray(ev)
        )
        return self._copyValues(model)


class SparkPCAModel(PCAModel):
    """Fitted model whose ``transform`` streams Spark DataFrames through the
    executor-local accelerator via mapInArrow."""

    def transform(self, dataset: Any) -> Any:
        if not _is_spark_df(dataset):
            return super().transform(dataset)
        _require_pyspark()
        from pyspark.sql import types as T

        input_col = self.getInputCol()
        output_col = self.getOutputCol()
        fn = arrow_fns.make_transform_partition_fn(input_col, output_col, self.pc)
        out_schema = T.StructType(
            dataset.schema.fields
            + [T.StructField(output_col, T.ArrayType(T.DoubleType()))]
        )
        with trace_range("pca transform"):
            return dataset.mapInArrow(fn, schema=out_schema)


def _is_spark_df(dataset: Any) -> bool:
    mod = type(dataset).__module__ or ""
    return mod.startswith("pyspark.")
